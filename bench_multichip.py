"""Sharded-wave throughput on a multi-device mesh (CPU-mesh evidence).

Measures the ICI-sharded scheduling kernel (SURVEY §2.9 item 1: the
pods×nodes feasibility/score program partitioned over the nodes axis, with
the scan-carried batched assignment) at a scale where sharding matters —
1024 nodes over 8 devices (128 bucket rows per shard), streaming 512-pod
waves — and prints ONE JSON line with the steady-state sharded wave
throughput plus the single-device number for the same program.

The sharded program is an EXPLICIT jax.shard_map (parallel/mesh.py
_sharded_assign_jit): per scan step the only cross-shard traffic is scalar
pmax/pmin normalizations, one [shards] tie-count gather, and two scalar
psums publishing the winner — the per-shard top-k → global argmax design of
SURVEY §7 (round 4 used GSPMD auto-partitioning of the same scan, which
inferred full-vector reductions and ran 6.7x SLOWER than single-device).

A collectives microbench rides along: the measured per-collective cost of
the CPU mesh's emulated psum/pmax/all_gather, times the step count, bounds
how much of any residual gap is collective-emulation overhead rather than
kernel structure. On a real multi-chip TPU the same collectives ride ICI at
~µs latency.

On a multi-chip TPU the same `scheduler_mesh` program runs over ICI; this
bench provisions virtual CPU devices (the driver-validated
`xla_force_host_platform_device_count` path) so the partitioned collectives
are exercised for real, even when only one physical chip is attached.
"""

from __future__ import annotations

import json
import os
import sys
import time

N_DEVICES = 8
N_NODES = 1024
WAVE = 512
ROUNDS = 4


def main() -> None:
    base = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, base)
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={N_DEVICES}"
        ).strip()
    from __graft_entry__ import _ensure_devices

    _ensure_devices(N_DEVICES)
    import jax
    import numpy as np

    from kubernetes_tpu.api.resource import ResourceNames
    from kubernetes_tpu.ops import stack_features
    from kubernetes_tpu.ops.kernels import batched_assign
    from kubernetes_tpu.parallel import (
        scheduler_mesh,
        shard_planes,
        sharded_batched_assign,
    )
    from kubernetes_tpu.parallel.mesh import NODE_AXIS
    from kubernetes_tpu.scheduler.tpu.backend import TPUBackend
    from kubernetes_tpu.testing import make_pod, synthetic_cluster, with_spread
    from kubernetes_tpu.utils.jaxcache import enable_persistent_cache

    enable_persistent_cache()

    names = ResourceNames()
    _, snapshot = synthetic_cluster(N_NODES, n_zones=8, init_pods_per_node=1,
                                    names=names)
    backend = TPUBackend(names)
    pods = []
    for i in range(WAVE):
        p = make_pod(f"w{i}", cpu=f"{1 + i % 2}", mem="1Gi",
                     labels={"app": f"g{i % 4}"})
        p = with_spread(p, max_skew=4, key="topology.kubernetes.io/zone",
                        when="DoNotSchedule")
        pods.append(p)
    for p in pods:
        backend.extractor.register(p)
    planes = backend.builder.sync(snapshot)
    stacked = stack_features(
        [backend.extractor.features(p, planes) for p in pods]
    )
    # narrowed config: only the constraint slots this wave actually uses are
    # traced (the real wave path always narrows; an unnarrowed config drags
    # 4 soft-constraint segment reductions through every scan step)
    cfg = backend.kernel_config(planes, stacked)
    inputs = {**planes.as_dict(), **backend.extractor.affinity_tables(planes)}
    mesh = scheduler_mesh(n_devices=N_DEVICES, wave=1)
    dev = shard_planes(mesh, inputs)

    def run_sharded():
        w, st = sharded_batched_assign(cfg, mesh, dev, stacked)
        jax.block_until_ready(w)
        return w

    def run_single():
        w, st = batched_assign(cfg, inputs, stacked)
        jax.block_until_ready(w)
        return w

    run_sharded()  # compile
    run_single()
    t0 = time.perf_counter()
    for _ in range(ROUNDS):
        w = run_sharded()
    sharded_s = (time.perf_counter() - t0) / ROUNDS
    t0 = time.perf_counter()
    for _ in range(ROUNDS):
        run_single()
    single_s = (time.perf_counter() - t0) / ROUNDS

    # --- collectives microbench: what does ONE emulated scalar collective
    # cost on this CPU mesh? (chained so latencies can't overlap)
    from jax.sharding import NamedSharding, PartitionSpec as P

    reps = 200

    def chain(x):
        # each step FEEDS the next (x changes every iteration) so XLA can
        # neither CSE the psums into one nor overlap their latencies
        for i in range(reps):
            x = jax.lax.psum(x + i, NODE_AXIS) % 1000003
        return x

    chained = jax.jit(jax.shard_map(
        chain, mesh=mesh, in_specs=P(NODE_AXIS), out_specs=P(NODE_AXIS),
    ))
    probe = jax.device_put(
        np.zeros(N_DEVICES, np.int32), NamedSharding(mesh, P(NODE_AXIS))
    )
    jax.block_until_ready(chained(probe))  # compile
    t0 = time.perf_counter()
    jax.block_until_ready(chained(probe))
    per_collective_us = (time.perf_counter() - t0) / reps * 1e6

    placed = int((np.asarray(w) >= 0).sum())
    # collectives per scan step on this workload (see _assign_step): one
    # pmax(best) + tie gather + 2 winner psums + hard-spread domain psum +
    # 2 normalization pmax — measured bound below uses 8/step
    est_collective_s = WAVE * 8 * per_collective_us / 1e6
    residual_s = sharded_s - single_s
    # TPU projection: same program, ICI-latency collectives (~3 µs) and the
    # per-shard compute actually parallel instead of 8 threads on one core
    tpu_collective_s = WAVE * 8 * 3e-6
    print(json.dumps({
        "metric": "sharded_wave_assign_throughput_1k_nodes",
        "value": round(WAVE / sharded_s, 1),
        "unit": "pods/s (kernel only)",
        "devices": N_DEVICES,
        "nodes": N_NODES,
        "wave": WAVE,
        "placed": placed,
        "single_device_pods_per_s": round(WAVE / single_s, 1),
        "sharded_vs_single": round(single_s / sharded_s, 2),
        # the breakdown: the ENTIRE sharded-vs-single residual is CPU-mesh
        # collective emulation (8 virtual devices on one physical core pay a
        # thread barrier per collective); est >= residual means the kernel
        # structure itself adds nothing on top
        "cpu_mesh_collective_us": round(per_collective_us, 1),
        "est_step_collective_overhead_s": round(est_collective_s, 3),
        "residual_s": round(residual_s, 3),
        # null when sharded is already >= single-device (nothing to explain)
        "residual_explained_by_collectives": (
            round(est_collective_s / residual_s, 2)
            if residual_s > 1e-6 else None
        ),
        "projected_tpu_ici_collective_s": round(tpu_collective_s, 4),
        "sharded_s": round(sharded_s, 3),
        "single_s": round(single_s, 3),
        "device": "cpu-mesh",
    }))


if __name__ == "__main__":
    main()
