"""Sharded-wave throughput on a multi-device mesh (CPU-mesh evidence).

Two modes:

**Default** — measures the ICI-sharded scheduling kernel (SURVEY §2.9
item 1: the pods×nodes feasibility/score program partitioned over the
nodes axis, with the scan-carried batched assignment) at a scale where
sharding matters — 1024 nodes over 8 devices (128 bucket rows per shard),
streaming 512-pod waves — and prints ONE JSON line with the steady-state
sharded wave throughput plus the single-device number for the same
program.

**`--nodes-sweep 5000,25000,50000,100000`** — the scale-out
done-criterion: for each node count, run the FULL backend
(`TPUBackend` on a `MeshContext`, launch/collect bursts with node churn
between bursts) and emit one JSONL row per node count with the device
columns the regression gate diffs (`upload_bytes_per_wave` /
`compile_count` / `mem_watermark_bytes`) plus `upload_flat_ratio` —
max/min per-burst upload bytes across the warm bursts. Flat (≤ ~1.1)
means the delta row scatter holds: per-burst upload is O(churn rows),
not O(nodes); only the first burst pays the sanctioned
`_cold_start_upload` full re-put. Rows go to stdout and (unless
`--smoke`) to the standing `MULTICHIP_BENCH_*.jsonl` artifact that
`make bench-gate` diffs against the previous round; `--smoke` instead
asserts flatness and placements inline (the `make verify` multichip
smoke).

The sharded program is an EXPLICIT jax.shard_map (parallel/mesh.py
_sharded_assign_jit): per scan step the only cross-shard traffic is scalar
pmax/pmin normalizations, one [shards] tie-count gather, and two scalar
psums publishing the winner — the per-shard top-k → global argmax design of
SURVEY §7 (round 4 used GSPMD auto-partitioning of the same scan, which
inferred full-vector reductions and ran 6.7x SLOWER than single-device).

A collectives microbench rides along: the measured per-collective cost of
the CPU mesh's emulated psum/pmax/all_gather, times the step count, bounds
how much of any residual gap is collective-emulation overhead rather than
kernel structure. On a real multi-chip TPU the same collectives ride ICI at
~µs latency.

On a multi-chip TPU the same `scheduler_mesh` program runs over ICI; this
bench provisions virtual CPU devices (the driver-validated
`xla_force_host_platform_device_count` path) so the partitioned collectives
are exercised for real, even when only one physical chip is attached.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

N_DEVICES = 8
N_NODES = 1024
WAVE = 512
ROUNDS = 4

ARTIFACT = "MULTICHIP_BENCH_r08.jsonl"


def _boot() -> str:
    """Path setup + virtual CPU mesh; returns the repo root."""
    base = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, base)
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={N_DEVICES}"
        ).strip()
    from __graft_entry__ import _ensure_devices

    _ensure_devices(N_DEVICES)
    return base


def run_sweep(nodes_list: list[int], bursts: int, wave: int, churn: int,
              artifact: str | None) -> None:
    """Backend burst loop per node count; one JSONL row each."""
    base = _boot()
    import random

    from kubernetes_tpu.api.resource import ResourceNames
    from kubernetes_tpu.parallel.mesh import MeshContext, scheduler_mesh
    from kubernetes_tpu.scheduler.tpu.backend import NeedResync, TPUBackend
    from kubernetes_tpu.testing import make_pod, synthetic_cluster
    from kubernetes_tpu.utils.jaxcache import enable_persistent_cache

    enable_persistent_cache()
    rows = []
    for n_nodes in nodes_list:
        names = ResourceNames()
        cache, snap = synthetic_cluster(n_nodes, n_zones=8, names=names)
        backend = TPUBackend(
            names, context=MeshContext(scheduler_mesh(N_DEVICES)))
        rng = random.Random(0)
        uploads, burst_s, placed = [], [], 0
        seq = 0
        for b in range(bursts):
            pods = [make_pod(f"b{b}-p{i}", cpu="500m", mem="512Mi",
                             labels={"app": f"g{i % 4}"})
                    for i in range(wave)]
            up0 = backend.telemetry.summary()["upload_bytes_total"]
            t0 = time.perf_counter()
            try:
                flight = backend.launch_batched(pods, snap, rng=rng,
                                                pad_to=wave)
            except NeedResync:
                # the scheduler-loop protocol after external churn: drop
                # the carry (folding its rows into the pending dirty set)
                # and retry — the relaunch repairs the base mirror with
                # one delta row scatter, not a full re-put
                backend.invalidate_carry()
                flight = backend.launch_batched(pods, snap, rng=rng,
                                                pad_to=wave)
            hosts, _ = backend.collect(flight, rng=rng)
            burst_s.append(time.perf_counter() - t0)
            uploads.append(
                backend.telemetry.summary()["upload_bytes_total"] - up0)
            placed += sum(1 for h in hosts if h)
            # churn: new running pods on a rotating slice of nodes — the
            # next burst's sync dirties exactly those rows, so its upload
            # must be the delta scatter, never a full re-put
            for k in range(churn):
                cache.add_pod(make_pod(
                    f"churn-{seq}", cpu="100m", mem="64Mi",
                    node_name=f"node-{(b * churn + k) % n_nodes}"))
                seq += 1
            snap = cache.update_snapshot(snap)
            backend.mark_external()
        warm_up = uploads[1:] or uploads
        warm_s = burst_s[1:] or burst_s
        cols = backend.telemetry.bench_columns(len(warm_up))
        rows.append({
            "metric": f"multichip_sweep_{n_nodes}_nodes",
            "value": round(wave * len(warm_s) / sum(warm_s), 1),
            "unit": "pods/s (backend burst loop)",
            "devices": N_DEVICES,
            "nodes": n_nodes,
            "wave": wave,
            "bursts": bursts,
            "churn_rows": churn,
            "placed": placed,
            # steady state: warm-burst mean, not the ledger total (which
            # would average the cold full upload in)
            "upload_bytes_per_wave": int(sum(warm_up) / len(warm_up)),
            "upload_bytes_cold": uploads[0],
            "upload_bytes_by_burst": uploads,
            "upload_flat_ratio": (
                round(max(warm_up) / min(warm_up), 3)
                if min(warm_up) else None),
            "compile_count": cols["compile_count"],
            "mem_watermark_bytes": cols["mem_watermark_bytes"],
            "device": "cpu-mesh",
        })
        print(json.dumps(rows[-1]), flush=True)
    if artifact:
        path = os.path.join(base, artifact)
        with open(path, "w") as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")
        print(f"# wrote {path}", file=sys.stderr)


def run_smoke(nodes_list: list[int], bursts: int, wave: int,
              churn: int) -> None:
    """make verify seam: small sweep, flatness asserted inline."""
    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        run_sweep(nodes_list, bursts, wave, churn, artifact=None)
    rows = [json.loads(line) for line in buf.getvalue().splitlines()
            if line.startswith("{")]
    assert len(rows) == len(nodes_list), rows
    for row in rows:
        assert row["placed"] > 0, row
        ratio = row["upload_flat_ratio"]
        assert ratio is not None and ratio <= 1.10, (
            f"upload not flat at {row['nodes']} nodes: per-burst bytes "
            f"{row['upload_bytes_by_burst']} (ratio {ratio}) — a full "
            "node_planes re-put leaked out of _cold_start_upload")
        assert row["upload_bytes_cold"] > row["upload_bytes_per_wave"], row
        print(json.dumps(row))
    print("multichip-smoke: PASS (upload flat burst-over-burst, "
          f"{len(rows)} node counts)")


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--nodes-sweep", default=None,
                        help="comma-separated node counts; enables the "
                             "backend burst-loop sweep mode")
    parser.add_argument("--bursts", type=int, default=4)
    parser.add_argument("--wave", type=int, default=16)
    parser.add_argument("--churn", type=int, default=64,
                        help="node rows churned between bursts")
    parser.add_argument("--artifact", default=ARTIFACT,
                        help="standing JSONL artifact name ('' disables)")
    parser.add_argument("--smoke", action="store_true",
                        help="assert upload flatness, write no artifact")
    args = parser.parse_args()
    if args.nodes_sweep:
        nodes = [int(x) for x in args.nodes_sweep.split(",") if x.strip()]
        if args.smoke:
            run_smoke(nodes, args.bursts, args.wave, args.churn)
        else:
            run_sweep(nodes, args.bursts, args.wave, args.churn,
                      args.artifact or None)
        return
    run_headline()


def run_headline() -> None:
    _boot()
    import jax
    import numpy as np

    from kubernetes_tpu.api.resource import ResourceNames
    from kubernetes_tpu.ops import stack_features
    from kubernetes_tpu.ops.kernels import batched_assign
    from kubernetes_tpu.parallel import (
        scheduler_mesh,
        shard_planes,
        sharded_batched_assign,
    )
    from kubernetes_tpu.parallel.mesh import NODE_AXIS
    from kubernetes_tpu.scheduler.tpu.backend import TPUBackend
    from kubernetes_tpu.testing import make_pod, synthetic_cluster, with_spread
    from kubernetes_tpu.utils.jaxcache import enable_persistent_cache

    enable_persistent_cache()

    names = ResourceNames()
    _, snapshot = synthetic_cluster(N_NODES, n_zones=8, init_pods_per_node=1,
                                    names=names)
    backend = TPUBackend(names)
    pods = []
    for i in range(WAVE):
        p = make_pod(f"w{i}", cpu=f"{1 + i % 2}", mem="1Gi",
                     labels={"app": f"g{i % 4}"})
        p = with_spread(p, max_skew=4, key="topology.kubernetes.io/zone",
                        when="DoNotSchedule")
        pods.append(p)
    for p in pods:
        backend.extractor.register(p)
    planes = backend.builder.sync(snapshot)
    stacked = stack_features(
        [backend.extractor.features(p, planes) for p in pods]
    )
    # narrowed config: only the constraint slots this wave actually uses are
    # traced (the real wave path always narrows; an unnarrowed config drags
    # 4 soft-constraint segment reductions through every scan step)
    cfg = backend.kernel_config(planes, stacked)
    inputs = {**planes.as_dict(), **backend.extractor.affinity_tables(planes)}
    mesh = scheduler_mesh(n_devices=N_DEVICES, wave=1)
    dev = shard_planes(mesh, inputs)

    def run_sharded():
        w, st = sharded_batched_assign(cfg, mesh, dev, stacked)
        jax.block_until_ready(w)
        return w

    def run_single():
        w, st = batched_assign(cfg, inputs, stacked)
        jax.block_until_ready(w)
        return w

    run_sharded()  # compile
    run_single()
    t0 = time.perf_counter()
    for _ in range(ROUNDS):
        w = run_sharded()
    sharded_s = (time.perf_counter() - t0) / ROUNDS
    t0 = time.perf_counter()
    for _ in range(ROUNDS):
        run_single()
    single_s = (time.perf_counter() - t0) / ROUNDS

    # --- collectives microbench: what does ONE emulated scalar collective
    # cost on this CPU mesh? (chained so latencies can't overlap)
    from jax.sharding import NamedSharding, PartitionSpec as P

    reps = 200

    def chain(x):
        # each step FEEDS the next (x changes every iteration) so XLA can
        # neither CSE the psums into one nor overlap their latencies
        for i in range(reps):
            x = jax.lax.psum(x + i, NODE_AXIS) % 1000003
        return x

    # mesh.py's version shim: jax.shard_map only exists on newer jax
    from kubernetes_tpu.parallel.mesh import _shard_map

    chained = jax.jit(_shard_map(
        chain, mesh=mesh, in_specs=P(NODE_AXIS), out_specs=P(NODE_AXIS),
    ))
    probe = jax.device_put(
        np.zeros(N_DEVICES, np.int32), NamedSharding(mesh, P(NODE_AXIS))
    )
    jax.block_until_ready(chained(probe))  # compile
    t0 = time.perf_counter()
    jax.block_until_ready(chained(probe))
    per_collective_us = (time.perf_counter() - t0) / reps * 1e6

    placed = int((np.asarray(w) >= 0).sum())
    # collectives per scan step on this workload (see _assign_step): one
    # pmax(best) + tie gather + 2 winner psums + hard-spread domain psum +
    # 2 normalization pmax — measured bound below uses 8/step
    est_collective_s = WAVE * 8 * per_collective_us / 1e6
    residual_s = sharded_s - single_s
    # TPU projection: same program, ICI-latency collectives (~3 µs) and the
    # per-shard compute actually parallel instead of 8 threads on one core
    tpu_collective_s = WAVE * 8 * 3e-6
    print(json.dumps({
        "metric": "sharded_wave_assign_throughput_1k_nodes",
        "value": round(WAVE / sharded_s, 1),
        "unit": "pods/s (kernel only)",
        "devices": N_DEVICES,
        "nodes": N_NODES,
        "wave": WAVE,
        "placed": placed,
        "single_device_pods_per_s": round(WAVE / single_s, 1),
        "sharded_vs_single": round(single_s / sharded_s, 2),
        # the breakdown: the ENTIRE sharded-vs-single residual is CPU-mesh
        # collective emulation (8 virtual devices on one physical core pay a
        # thread barrier per collective); est >= residual means the kernel
        # structure itself adds nothing on top
        "cpu_mesh_collective_us": round(per_collective_us, 1),
        "est_step_collective_overhead_s": round(est_collective_s, 3),
        "residual_s": round(residual_s, 3),
        # null when sharded is already >= single-device (nothing to explain)
        "residual_explained_by_collectives": (
            round(est_collective_s / residual_s, 2)
            if residual_s > 1e-6 else None
        ),
        "projected_tpu_ici_collective_s": round(tpu_collective_s, 4),
        "sharded_s": round(sharded_s, 3),
        "single_s": round(single_s, 3),
        "device": "cpu-mesh",
    }))


if __name__ == "__main__":
    main()
