"""Sharded-wave throughput on a multi-device mesh (CPU-mesh evidence).

Measures the ICI-sharded scheduling kernel (SURVEY §2.9 item 1: the
pods×nodes feasibility/score program partitioned over the nodes axis, with
the scan-carried batched assignment) at a scale where sharding matters —
1024 nodes over 8 devices (128 bucket rows per shard), streaming 512-pod
waves — and prints ONE JSON line with the steady-state sharded wave
throughput plus the single-device number for the same program.

On a multi-chip TPU the same `scheduler_mesh` program runs over ICI; this
bench provisions virtual CPU devices (the driver-validated
`xla_force_host_platform_device_count` path) so the partitioned collectives
are exercised for real, even when only one physical chip is attached.
"""

from __future__ import annotations

import json
import os
import sys
import time

N_DEVICES = 8
N_NODES = 1024
WAVE = 512
ROUNDS = 4


def main() -> None:
    base = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, base)
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={N_DEVICES}"
        ).strip()
    from __graft_entry__ import _ensure_devices

    _ensure_devices(N_DEVICES)
    import jax

    from kubernetes_tpu.api.resource import ResourceNames
    from kubernetes_tpu.ops import stack_features
    from kubernetes_tpu.ops.kernels import batched_assign
    from kubernetes_tpu.parallel import (
        scheduler_mesh,
        shard_planes,
        sharded_batched_assign,
    )
    from kubernetes_tpu.scheduler.tpu.backend import TPUBackend
    from kubernetes_tpu.testing import make_pod, synthetic_cluster, with_spread
    from kubernetes_tpu.utils.jaxcache import enable_persistent_cache

    enable_persistent_cache()

    names = ResourceNames()
    _, snapshot = synthetic_cluster(N_NODES, n_zones=8, init_pods_per_node=1,
                                    names=names)
    backend = TPUBackend(names)
    pods = []
    for i in range(WAVE):
        p = make_pod(f"w{i}", cpu=f"{1 + i % 2}", mem="1Gi",
                     labels={"app": f"g{i % 4}"})
        p = with_spread(p, max_skew=4, key="topology.kubernetes.io/zone",
                        when="DoNotSchedule")
        pods.append(p)
    for p in pods:
        backend.extractor.register(p)
    planes = backend.builder.sync(snapshot)
    cfg = backend.kernel_config(planes)
    inputs = {**planes.as_dict(), **backend.extractor.affinity_tables(planes)}
    stacked = stack_features(
        [backend.extractor.features(p, planes) for p in pods]
    )
    mesh = scheduler_mesh(n_devices=N_DEVICES, wave=2)
    dev = shard_planes(mesh, inputs)

    def run_sharded():
        w, st = sharded_batched_assign(cfg, mesh, dev, stacked)
        jax.block_until_ready(w)
        return w

    def run_single():
        w, st = batched_assign(cfg, inputs, stacked)
        jax.block_until_ready(w)
        return w

    run_sharded()  # compile
    run_single()
    t0 = time.perf_counter()
    for _ in range(ROUNDS):
        w = run_sharded()
    sharded_s = (time.perf_counter() - t0) / ROUNDS
    t0 = time.perf_counter()
    for _ in range(ROUNDS):
        run_single()
    single_s = (time.perf_counter() - t0) / ROUNDS
    import numpy as np

    placed = int((np.asarray(w) >= 0).sum())
    print(json.dumps({
        "metric": "sharded_wave_assign_throughput_1k_nodes",
        "value": round(WAVE / sharded_s, 1),
        "unit": "pods/s (kernel only)",
        "devices": N_DEVICES,
        "nodes": N_NODES,
        "wave": WAVE,
        "placed": placed,
        "single_device_pods_per_s": round(WAVE / single_s, 1),
        "sharded_vs_single": round(single_s / sharded_s, 2),
        "device": "cpu-mesh",
    }))


if __name__ == "__main__":
    main()
