# Repo tooling (native/Makefile builds the C++ cores; this drives checks)

PY ?= python

.PHONY: lint test obs

# kubesched-lint: AST invariant checker (rule IDs in README "Invariants");
# exits non-zero on any unsuppressed finding
lint:
	$(PY) -m kubernetes_tpu.analysis kubernetes_tpu/

test:
	env JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow'

# flight-recorder CLI smoke: synthetic multi-wave run (no device, no jax),
# exercises ring buffer + watchdog + post-mortem formatting
obs:
	$(PY) -m kubernetes_tpu.scheduler.tpu.flightrecorder --demo
	$(PY) -m kubernetes_tpu.scheduler.tpu.flightrecorder --schema
