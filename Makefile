# Repo tooling (native/Makefile builds the C++ cores; this drives checks)

PY ?= python

.PHONY: lint lint-graph test obs chaos bench-smoke bench-gate multichip-smoke stall-smoke verify

# kubesched-lint: AST invariant checker (rule IDs in README "Invariants");
# runs the whole-program pass (call-graph-transitive EFF01/EFF02, LOCK05,
# RNG01, transitive ownership) by default, memoized under
# .kubesched_lint_cache/; then audits the suppression trail for dead
# disables (LINT02). Exits non-zero on any unsuppressed finding
lint:
	$(PY) -m kubernetes_tpu.analysis kubernetes_tpu/
	$(PY) -m kubernetes_tpu.analysis --audit-suppressions kubernetes_tpu/

# debugging aid for rule authors: dump one function's call-graph slice +
# inferred effect sets (direct and transitive, with provenance chains).
# Usage: make lint-graph FN=TPUBackend.collect
lint-graph:
	$(PY) -m kubernetes_tpu.analysis --graph $(FN)

test:
	env JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow'

# seeded chaos soaks: (1) scale-churn under the standard fault schedule,
# then (2) the arrival-trace soak at two fixed seeds — Poisson/burst
# arrivals with a watch partition, a fleet-wide kubelet outage, and bind
# latency armed; each must converge (no leaked assumes, breaker trip AND
# recover, partition detect AND repair, evicted pods gone, late arrivals
# bound) inside the wall-clock budget — then (3) the gang soak: a kubelet
# killed mid-gang under bind/dispatcher flakes, all-or-nothing asserted
# after convergence (no partially-bound gang, Required gangs single-zone)
# — then (4) the restart storm: seeded scheduler crashes mid-wave /
# mid-bind-commit / mid-gang-permit with ungraceful teardown and warm
# restarts over the same store (zero double binds, zero leaked assumes,
# per-gang all-or-nothing, compile-free warm restart asserted) — then
# (5) the fleet soak: 3 lease-sharded active-active schedulers over ONE
# store under the full ladder plus seeded lease loss, one peer killed
# mid-wave; survivors must adopt the orphaned shard inside the bounded
# window (counted on restart_recoveries{kind="shard_adopt*"}) with zero
# double binds and disjoint ownership after convergence.
# Exits non-zero on divergence — same seed replays the same schedule
chaos:
	env JAX_PLATFORMS=cpu $(PY) -m kubernetes_tpu.testing.chaos --seed 7
	env JAX_PLATFORMS=cpu $(PY) -m kubernetes_tpu.testing.chaos --trace --seed 7 --budget-s 60
	env JAX_PLATFORMS=cpu $(PY) -m kubernetes_tpu.testing.chaos --trace --seed 1234 --budget-s 60
	env JAX_PLATFORMS=cpu $(PY) -m kubernetes_tpu.testing.chaos --gang --seed 7
	env JAX_PLATFORMS=cpu $(PY) -m kubernetes_tpu.testing.chaos --restart --seed 7
	env JAX_PLATFORMS=cpu $(PY) -m kubernetes_tpu.testing.chaos --fleet --seed 7

# flight-recorder CLI smoke: synthetic multi-wave run (no device, no jax),
# exercises ring buffer + watchdog + post-mortem formatting, and asserts
# the device-telemetry block (transfer ledger / compile tracker / memory
# watermark) AND the stall-attribution block (>=95% coverage per wave)
# are present in the dump; then dumps the stall profiler's own summary
obs:
	$(PY) -m kubernetes_tpu.scheduler.tpu.flightrecorder --demo
	$(PY) -m kubernetes_tpu.scheduler.tpu.flightrecorder --schema
	$(PY) -m kubernetes_tpu.scheduler.tpu.stallprofiler --demo

# trace-bench CI smoke: a tiny 200-pod Poisson trace through the real
# loop (virtual-time SLI, deterministic), asserting the standing row keys
# exist and that the regression gate passes an artifact against itself
bench-smoke:
	env JAX_PLATFORMS=cpu $(PY) -m kubernetes_tpu.perf.trace_bench --smoke

# mechanical perf-regression gate: diff the newest two artifacts per
# family (BENCH_* and MULTICHIP_BENCH_*, gated independently) in the repo
# root; >10% regression in any throughput/SLI/device row fails and names
# the ledger segment whose p50 delta explains it
bench-gate:
	$(PY) -m kubernetes_tpu.perf.regression_gate

# sharded-mesh smoke: a small node sweep through the full backend on an
# 8-virtual-CPU-device mesh, asserting per-burst upload bytes stay flat
# (delta scatter, not full re-put) and pods place; no artifact written
multichip-smoke:
	$(PY) bench_multichip.py --nodes-sweep 512,1024 --bursts 3 --wave 8 --churn 16 --smoke

# critical-path analyzer smoke: synthetic waves through the full
# decompose -> analyze path, asserting the coverage invariant and
# dominant-edge selection (no device, no jax)
stall-smoke:
	$(PY) -m kubernetes_tpu.scheduler.tpu.stallprofiler --smoke

# the full gate: invariants, tier-1 tests, chaos soaks (incl. the
# arrival-trace runs), observability smoke, trace-bench smoke, the
# stall critical-path smoke, and the sharded-mesh upload-flatness smoke
verify: lint test chaos obs bench-smoke stall-smoke multichip-smoke
