# Repo tooling (native/Makefile builds the C++ cores; this drives checks)

PY ?= python

.PHONY: lint test

# kubesched-lint: AST invariant checker (rule IDs in README "Invariants");
# exits non-zero on any unsuppressed finding
lint:
	$(PY) -m kubernetes_tpu.analysis kubernetes_tpu/

test:
	env JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow'
