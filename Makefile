# Repo tooling (native/Makefile builds the C++ cores; this drives checks)

PY ?= python

.PHONY: lint test obs chaos

# kubesched-lint: AST invariant checker (rule IDs in README "Invariants");
# exits non-zero on any unsuppressed finding
lint:
	$(PY) -m kubernetes_tpu.analysis kubernetes_tpu/

test:
	env JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow'

# seeded chaos soak: scale-churn under the standard fault schedule must
# converge (all pods bound, no leaked assumes, breaker trips AND recovers);
# exits non-zero on divergence — same seed replays the same schedule
chaos:
	env JAX_PLATFORMS=cpu $(PY) -m kubernetes_tpu.testing.chaos --seed 7

# flight-recorder CLI smoke: synthetic multi-wave run (no device, no jax),
# exercises ring buffer + watchdog + post-mortem formatting
obs:
	$(PY) -m kubernetes_tpu.scheduler.tpu.flightrecorder --demo
	$(PY) -m kubernetes_tpu.scheduler.tpu.flightrecorder --schema
