// Native CBOR transcoder: JSON text <-> CBOR bytes (RFC 8949 subset).
//
// The binary wire format (kubernetes_tpu/api/cbor.py) plays the protobuf
// role of the reference's apimachinery serializers; a pure-Python encoder
// walks objects byte by byte, which makes the "fast" format slower than
// the C-accelerated json module. This transcoder moves the byte work to
// C++: Python calls json.dumps (C speed), this converts the JSON text to
// deterministic CBOR (definite lengths, shortest-form heads), and the
// reverse path emits JSON text for json.loads. Values outside the JSON
// data model (byte strings, >64-bit ints) return an error and Python
// falls back to the pure codec.
//
// ctypes ABI (mirrors store_core.cpp): buffers are malloc'd here and
// released with cj_free.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <cstdio>
#include <cmath>
#include <string>
#include <vector>

namespace {

struct Out {
    std::string buf;
    void u8(uint8_t b) { buf.push_back(static_cast<char>(b)); }
    void raw(const char* p, size_t n) { buf.append(p, n); }
};

void head(Out& o, int major, uint64_t n) {
    int mb = major << 5;
    if (n < 24) {
        o.u8(mb | static_cast<int>(n));
    } else if (n < 0x100) {
        o.u8(mb | 24); o.u8(static_cast<uint8_t>(n));
    } else if (n < 0x10000) {
        o.u8(mb | 25); o.u8(n >> 8); o.u8(n & 0xff);
    } else if (n < 0x100000000ULL) {
        o.u8(mb | 26);
        for (int s = 24; s >= 0; s -= 8) o.u8((n >> s) & 0xff);
    } else {
        o.u8(mb | 27);
        for (int s = 56; s >= 0; s -= 8) o.u8((n >> s) & 0xff);
    }
}

// ---- JSON parser ---------------------------------------------------------

struct Parser {
    const char* p;
    const char* end;
    bool ok = true;

    void ws() { while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) p++; }
    bool lit(const char* s) {
        size_t n = strlen(s);
        if (static_cast<size_t>(end - p) >= n && memcmp(p, s, n) == 0) { p += n; return true; }
        return false;
    }
};

bool parse_value(Parser& in, Out& out);

void utf8_append(std::string& s, uint32_t cp) {
    if (cp < 0x80) s.push_back(static_cast<char>(cp));
    else if (cp < 0x800) {
        s.push_back(static_cast<char>(0xC0 | (cp >> 6)));
        s.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
    } else if (cp < 0x10000) {
        s.push_back(static_cast<char>(0xE0 | (cp >> 12)));
        s.push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
        s.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
    } else {
        s.push_back(static_cast<char>(0xF0 | (cp >> 18)));
        s.push_back(static_cast<char>(0x80 | ((cp >> 12) & 0x3F)));
        s.push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
        s.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
    }
}

bool parse_string_into(Parser& in, std::string& s) {
    if (in.p >= in.end || *in.p != '"') return false;
    in.p++;
    while (in.p < in.end) {
        unsigned char c = *in.p;
        if (c == '"') { in.p++; return true; }
        if (c == '\\') {
            in.p++;
            if (in.p >= in.end) return false;
            char e = *in.p++;
            switch (e) {
                case '"': s.push_back('"'); break;
                case '\\': s.push_back('\\'); break;
                case '/': s.push_back('/'); break;
                case 'b': s.push_back('\b'); break;
                case 'f': s.push_back('\f'); break;
                case 'n': s.push_back('\n'); break;
                case 'r': s.push_back('\r'); break;
                case 't': s.push_back('\t'); break;
                case 'u': {
                    if (in.end - in.p < 4) return false;
                    char tmp[5] = {in.p[0], in.p[1], in.p[2], in.p[3], 0};
                    uint32_t cp = static_cast<uint32_t>(strtoul(tmp, nullptr, 16));
                    in.p += 4;
                    if (cp >= 0xD800 && cp <= 0xDBFF && in.end - in.p >= 6
                        && in.p[0] == '\\' && in.p[1] == 'u') {
                        char tmp2[5] = {in.p[2], in.p[3], in.p[4], in.p[5], 0};
                        uint32_t lo = static_cast<uint32_t>(strtoul(tmp2, nullptr, 16));
                        if (lo >= 0xDC00 && lo <= 0xDFFF) {
                            cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
                            in.p += 6;
                        }
                    }
                    utf8_append(s, cp);
                    break;
                }
                default: return false;
            }
        } else {
            s.push_back(static_cast<char>(c));
            in.p++;
        }
    }
    return false;
}

bool parse_number(Parser& in, Out& out) {
    const char* start = in.p;
    if (in.p < in.end && *in.p == '-') in.p++;
    bool is_float = false;
    while (in.p < in.end) {
        char c = *in.p;
        if (c >= '0' && c <= '9') { in.p++; }
        else if (c == '.' || c == 'e' || c == 'E' || c == '+' || c == '-') {
            is_float = true; in.p++;
        } else break;
    }
    std::string tok(start, in.p - start);
    if (!is_float) {
        errno = 0;
        char* endp = nullptr;
        long long v = strtoll(tok.c_str(), &endp, 10);
        if (errno == ERANGE || endp != tok.c_str() + tok.size())
            return false;  // >64-bit: caller falls back to the pure codec
        if (v >= 0) head(out, 0, static_cast<uint64_t>(v));
        else head(out, 1, static_cast<uint64_t>(-1 - v));
        return true;
    }
    double d = strtod(tok.c_str(), nullptr);
    out.u8(0xfb);
    uint64_t bits;
    memcpy(&bits, &d, 8);
    for (int s = 56; s >= 0; s -= 8) out.u8((bits >> s) & 0xff);
    return true;
}

bool parse_value(Parser& in, Out& out) {
    in.ws();
    if (in.p >= in.end) return false;
    char c = *in.p;
    if (c == 'n') { if (!in.lit("null")) return false; out.u8(0xf6); return true; }
    if (c == 't') { if (!in.lit("true")) return false; out.u8(0xf5); return true; }
    if (c == 'f') { if (!in.lit("false")) return false; out.u8(0xf4); return true; }
    if (c == 'N') {  // NaN (python json.dumps emits it)
        if (!in.lit("NaN")) return false;
        out.u8(0xfb);
        double d = NAN; uint64_t bits; memcpy(&bits, &d, 8);
        for (int s = 56; s >= 0; s -= 8) out.u8((bits >> s) & 0xff);
        return true;
    }
    if (c == 'I' || (c == '-' && in.end - in.p > 1 && in.p[1] == 'I')) {
        bool neg = c == '-';
        if (neg) in.p++;
        if (!in.lit("Infinity")) return false;
        out.u8(0xfb);
        double d = neg ? -INFINITY : INFINITY;
        uint64_t bits; memcpy(&bits, &d, 8);
        for (int s = 56; s >= 0; s -= 8) out.u8((bits >> s) & 0xff);
        return true;
    }
    if (c == '"') {
        std::string s;
        if (!parse_string_into(in, s)) return false;
        head(out, 3, s.size());
        out.raw(s.data(), s.size());
        return true;
    }
    if (c == '[') {
        in.p++;
        // two-pass-free: transcode elements into a scratch buffer, count
        std::vector<std::string> elems;
        in.ws();
        if (in.p < in.end && *in.p == ']') { in.p++; head(out, 4, 0); return true; }
        while (true) {
            Out elem;
            if (!parse_value(in, elem)) return false;
            elems.push_back(std::move(elem.buf));
            in.ws();
            if (in.p < in.end && *in.p == ',') { in.p++; continue; }
            if (in.p < in.end && *in.p == ']') { in.p++; break; }
            return false;
        }
        head(out, 4, elems.size());
        for (auto& e : elems) out.raw(e.data(), e.size());
        return true;
    }
    if (c == '{') {
        in.p++;
        std::vector<std::string> items;
        in.ws();
        if (in.p < in.end && *in.p == '}') { in.p++; head(out, 5, 0); return true; }
        while (true) {
            in.ws();
            Out kv;
            std::string key;
            if (!parse_string_into(in, key)) return false;
            head(kv, 3, key.size());
            kv.raw(key.data(), key.size());
            in.ws();
            if (in.p >= in.end || *in.p != ':') return false;
            in.p++;
            if (!parse_value(in, kv)) return false;
            items.push_back(std::move(kv.buf));
            in.ws();
            if (in.p < in.end && *in.p == ',') { in.p++; continue; }
            if (in.p < in.end && *in.p == '}') { in.p++; break; }
            return false;
        }
        head(out, 5, items.size());
        for (auto& e : items) out.raw(e.data(), e.size());
        return true;
    }
    return parse_number(in, out);
}

// ---- CBOR reader → JSON writer ------------------------------------------

struct Reader {
    const uint8_t* p;
    const uint8_t* end;

    bool take(uint64_t n, const uint8_t** out) {
        if (static_cast<uint64_t>(end - p) < n) return false;
        *out = p; p += n; return true;
    }
    bool length(int info, uint64_t* n) {
        if (info < 24) { *n = static_cast<uint64_t>(info); return true; }
        int extra = info == 24 ? 1 : info == 25 ? 2 : info == 26 ? 4 : info == 27 ? 8 : -1;
        if (extra < 0) return false;
        const uint8_t* b;
        if (!take(static_cast<uint64_t>(extra), &b)) return false;
        uint64_t v = 0;
        for (int i = 0; i < extra; i++) v = (v << 8) | b[i];
        *n = v;
        return true;
    }
};

void json_escape(std::string& out, const uint8_t* s, uint64_t n) {
    out.push_back('"');
    for (uint64_t i = 0; i < n; i++) {
        uint8_t c = s[i];
        switch (c) {
            case '"': out += "\\\""; break;
            case '\\': out += "\\\\"; break;
            case '\b': out += "\\b"; break;
            case '\f': out += "\\f"; break;
            case '\n': out += "\\n"; break;
            case '\r': out += "\\r"; break;
            case '\t': out += "\\t"; break;
            default:
                if (c < 0x20) {
                    char tmp[8];
                    snprintf(tmp, sizeof tmp, "\\u%04x", c);
                    out += tmp;
                } else {
                    out.push_back(static_cast<char>(c));  // raw UTF-8 is valid JSON
                }
        }
    }
    out.push_back('"');
}

bool emit_json(Reader& in, std::string& out) {
    const uint8_t* b;
    if (!in.take(1, &b)) return false;
    int major = b[0] >> 5, info = b[0] & 0x1f;
    if (major == 0 || major == 1) {
        uint64_t n;
        if (!in.length(info, &n)) return false;
        if (major == 0) {
            if (n > INT64_MAX) return false;
            out += std::to_string(n);
        } else {
            if (n > INT64_MAX) return false;  // < -2^63: pure-codec territory
            out += std::to_string(-1 - static_cast<int64_t>(n));
        }
        return true;
    }
    if (major == 2) return false;  // byte strings: not in the JSON model
    if (major == 3) {
        uint64_t n;
        if (!in.length(info, &n)) return false;
        const uint8_t* s;
        if (!in.take(n, &s)) return false;
        json_escape(out, s, n);
        return true;
    }
    if (major == 4 || major == 5) {
        uint64_t n;
        if (!in.length(info, &n)) return false;
        out.push_back(major == 4 ? '[' : '{');
        for (uint64_t i = 0; i < n; i++) {
            if (i) out.push_back(',');
            if (major == 5) {
                // JSON object keys must be text: any other CBOR key type
                // (ints are legal CBOR) punts to the pure codec
                if (in.p >= in.end || (*in.p >> 5) != 3) return false;
                if (!emit_json(in, out)) return false;
                out.push_back(':');
            }
            if (!emit_json(in, out)) return false;
        }
        out.push_back(major == 4 ? ']' : '}');
        return true;
    }
    // major 7: simple / float
    if (b[0] == 0xf6) { out += "null"; return true; }
    if (b[0] == 0xf5) { out += "true"; return true; }
    if (b[0] == 0xf4) { out += "false"; return true; }
    if (b[0] == 0xfb) {
        const uint8_t* f;
        if (!in.take(8, &f)) return false;
        uint64_t bits = 0;
        for (int i = 0; i < 8; i++) bits = (bits << 8) | f[i];
        double d;
        memcpy(&d, &bits, 8);
        if (std::isnan(d)) { out += "NaN"; return true; }
        if (std::isinf(d)) { out += d > 0 ? "Infinity" : "-Infinity"; return true; }
        char tmp[40];
        snprintf(tmp, sizeof tmp, "%.17g", d);
        out += tmp;
        // keep it a FLOAT through json.loads: "3" would parse as int
        if (!strpbrk(tmp, ".eEnN")) out += ".0";
        return true;
    }
    return false;
}

char* dup_buffer(const std::string& s, size_t* out_len) {
    char* mem = static_cast<char*>(malloc(s.size() ? s.size() : 1));
    if (mem == nullptr) return nullptr;
    memcpy(mem, s.data(), s.size());
    *out_len = s.size();
    return mem;
}

}  // namespace

extern "C" {

// JSON text → CBOR bytes. Returns 0 on success, -1 on unsupported input
// (caller uses the pure-Python codec).
int64_t cj_json_to_cbor(const char* json, size_t len,
                        uint8_t** out, size_t* out_len) {
    Parser in{json, json + len};
    Out cbor;
    if (!parse_value(in, cbor)) return -1;
    in.ws();
    if (in.p != in.end) return -1;  // trailing garbage
    size_t n;
    char* mem = dup_buffer(cbor.buf, &n);
    if (mem == nullptr) return -1;
    *out = reinterpret_cast<uint8_t*>(mem);
    *out_len = n;
    return 0;
}

// CBOR bytes → JSON text. Returns 0 on success, -1 on unsupported input.
int64_t cj_cbor_to_json(const uint8_t* buf, size_t len,
                        char** out, size_t* out_len) {
    Reader in{buf, buf + len};
    std::string json;
    if (!emit_json(in, json)) return -1;
    if (in.p != in.end) return -1;  // trailing bytes
    char* mem = dup_buffer(json, out_len);
    if (mem == nullptr) return -1;
    *out = mem;
    return 0;
}

void cj_free(void* p) { free(p); }

}  // extern "C"
