// Native store core: the etcd-equivalent L0 storage engine.
//
// Reference role: etcd + staging/src/k8s.io/apiserver/pkg/storage/etcd3/
// (store.go, watcher via event.go, compact.go). The reference's L0 is a
// native (Go) external process; this is the TPU framework's native
// equivalent, linked in-process: a revisioned KV map with a gap-free event
// log (watch cache), CAS updates, compaction, and durable snapshot
// save/load (checkpoint/resume, SURVEY.md §5.4 — "etcd IS the checkpoint").
//
// C ABI for ctypes. All out-buffers are malloc'd and must be released with
// sc_buf_free. Values are opaque bytes (the Python layer stores JSON).
//
// Wire framing for lists/logs (little-endian):
//   list:  repeat { u32 key_len, key, u32 val_len, val }
//   log:   repeat { u8 type, i64 rev, f64 ts, u32 key_len, key, u32 val_len, val }
//     type: 0=ADDED 1=MODIFIED 2=DELETED

#include <cstdint>
#include <cstring>
#include <cstdio>
#include <cstdlib>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <vector>

namespace {

struct Entry {
  std::string value;
  int64_t mod_rev = 0;
  int64_t create_rev = 0;
};

struct LogEvent {
  uint8_t type;  // 0 add, 1 modify, 2 delete
  int64_t rev;
  double ts;  // caller-supplied write timestamp (Python time.perf_counter)
  std::string key;
  std::string value;
};

struct Core {
  std::mutex mu;
  int64_t revision = 0;
  // kind -> key -> entry
  std::map<std::string, std::map<std::string, Entry>> objects;
  // kind -> event log (ascending revisions)
  std::map<std::string, std::deque<LogEvent>> logs;
  // kind -> highest revision dropped from that kind's log (compaction or
  // cap-trimming); watches from below this horizon must relist
  std::map<std::string, int64_t> compacted;
  size_t log_cap = 200000;
};

void append_u32(std::string& buf, uint32_t v) {
  buf.append(reinterpret_cast<const char*>(&v), sizeof(v));
}
void append_i64(std::string& buf, int64_t v) {
  buf.append(reinterpret_cast<const char*>(&v), sizeof(v));
}
void append_f64(std::string& buf, double v) {
  buf.append(reinterpret_cast<const char*>(&v), sizeof(v));
}

char* out_copy(const std::string& s, size_t* out_len) {
  char* p = static_cast<char*>(std::malloc(s.size() ? s.size() : 1));
  std::memcpy(p, s.data(), s.size());
  *out_len = s.size();
  return p;
}

void log_emit(Core* c, const std::string& kind, uint8_t type, int64_t rev,
              double ts, const std::string& key, const std::string& value) {
  auto& log = c->logs[kind];
  log.push_back(LogEvent{type, rev, ts, key, value});
  if (log.size() > c->log_cap) {
    for (size_t i = 0; i < c->log_cap / 2; ++i) {
      c->compacted[kind] = log.front().rev;
      log.pop_front();
    }
  }
}

}  // namespace

extern "C" {

// error codes (negative returns)
enum {
  SC_OK = 0,
  SC_ERR_NOT_FOUND = -1,
  SC_ERR_ALREADY_EXISTS = -2,
  SC_ERR_CONFLICT = -3,
  SC_ERR_IO = -4,
};

void* sc_new() { return new Core(); }

void sc_free(void* h) { delete static_cast<Core*>(h); }

void sc_buf_free(char* p) { std::free(p); }

int64_t sc_revision(void* h) {
  Core* c = static_cast<Core*>(h);
  std::lock_guard<std::mutex> lock(c->mu);
  return c->revision;
}

// Returns new revision (>0) or error code. expected_rev: -1 = no CAS check.
// is_create: 1 -> fail if key exists; 0 -> fail if key missing.
int64_t sc_put(void* h, const char* kind, const char* key, const char* val,
               uint32_t val_len, int64_t expected_rev, int is_create,
               double ts) {
  Core* c = static_cast<Core*>(h);
  std::lock_guard<std::mutex> lock(c->mu);
  auto& objs = c->objects[kind];
  auto it = objs.find(key);
  if (is_create) {
    if (it != objs.end()) return SC_ERR_ALREADY_EXISTS;
  } else {
    if (it == objs.end()) return SC_ERR_NOT_FOUND;
    if (expected_rev >= 0 && it->second.mod_rev != expected_rev)
      return SC_ERR_CONFLICT;
  }
  int64_t rev = ++c->revision;
  Entry& e = objs[key];
  e.value.assign(val, val_len);
  e.mod_rev = rev;
  if (is_create) e.create_rev = rev;
  log_emit(c, kind, is_create ? 0 : 1, rev, ts, key, e.value);
  return rev;
}

// Returns mod revision (>0) or SC_ERR_NOT_FOUND. *out malloc'd.
int64_t sc_get(void* h, const char* kind, const char* key, char** out,
               size_t* out_len) {
  Core* c = static_cast<Core*>(h);
  std::lock_guard<std::mutex> lock(c->mu);
  auto kit = c->objects.find(kind);
  if (kit == c->objects.end()) return SC_ERR_NOT_FOUND;
  auto it = kit->second.find(key);
  if (it == kit->second.end()) return SC_ERR_NOT_FOUND;
  *out = out_copy(it->second.value, out_len);
  return it->second.mod_rev;
}

// Returns deletion revision or SC_ERR_NOT_FOUND; *out = last value.
int64_t sc_delete(void* h, const char* kind, const char* key, char** out,
                  size_t* out_len, double ts) {
  Core* c = static_cast<Core*>(h);
  std::lock_guard<std::mutex> lock(c->mu);
  auto kit = c->objects.find(kind);
  if (kit == c->objects.end()) return SC_ERR_NOT_FOUND;
  auto it = kit->second.find(key);
  if (it == kit->second.end()) return SC_ERR_NOT_FOUND;
  int64_t rev = ++c->revision;
  std::string value = std::move(it->second.value);
  kit->second.erase(it);
  log_emit(c, kind, 2, rev, ts, key, value);
  *out = out_copy(value, out_len);
  return rev;
}

// Returns store revision; *out = framed (key, value) pairs in key order.
int64_t sc_list(void* h, const char* kind, char** out, size_t* out_len) {
  Core* c = static_cast<Core*>(h);
  std::lock_guard<std::mutex> lock(c->mu);
  std::string buf;
  auto kit = c->objects.find(kind);
  if (kit != c->objects.end()) {
    for (const auto& [key, entry] : kit->second) {
      append_u32(buf, static_cast<uint32_t>(key.size()));
      buf += key;
      append_u32(buf, static_cast<uint32_t>(entry.value.size()));
      buf += entry.value;
    }
  }
  *out = out_copy(buf, out_len);
  return c->revision;
}

// Events with revision > from_rev. Returns count; -1 if compaction dropped
// events this watch would have needed (from_rev below the kind's horizon —
// revisions are store-global, so only the per-kind compaction marker can
// prove a gap; a sparse log alone cannot).
int64_t sc_log_since(void* h, const char* kind, int64_t from_rev, char** out,
                     size_t* out_len) {
  Core* c = static_cast<Core*>(h);
  std::lock_guard<std::mutex> lock(c->mu);
  auto cit = c->compacted.find(kind);
  if (cit != c->compacted.end() && from_rev < cit->second) {
    *out = out_copy("", out_len);
    return -1;
  }
  std::string buf;
  int64_t n = 0;
  auto lit = c->logs.find(kind);
  if (lit != c->logs.end()) {
    for (const auto& ev : lit->second) {
      if (ev.rev <= from_rev) continue;
      buf.push_back(static_cast<char>(ev.type));
      append_i64(buf, ev.rev);
      append_f64(buf, ev.ts);
      append_u32(buf, static_cast<uint32_t>(ev.key.size()));
      buf += ev.key;
      append_u32(buf, static_cast<uint32_t>(ev.value.size()));
      buf += ev.value;
      ++n;
    }
  }
  *out = out_copy(buf, out_len);
  return n;
}

// Drop log events with revision <= rev (etcd compaction).
int64_t sc_compact(void* h, int64_t rev) {
  Core* c = static_cast<Core*>(h);
  std::lock_guard<std::mutex> lock(c->mu);
  int64_t dropped = 0;
  for (auto& [kind, log] : c->logs) {
    while (!log.empty() && log.front().rev <= rev) {
      c->compacted[kind] = log.front().rev;
      log.pop_front();
      ++dropped;
    }
  }
  return dropped;
}

// Durable snapshot: revision + all entries (log is not persisted — watches
// relist after restore, which is exactly the reference's resync-on-compact).
int64_t sc_save(void* h, const char* path) {
  Core* c = static_cast<Core*>(h);
  std::lock_guard<std::mutex> lock(c->mu);
  FILE* f = std::fopen(path, "wb");
  if (!f) return SC_ERR_IO;
  std::string buf;
  buf += "SCK1";
  append_i64(buf, c->revision);
  for (const auto& [kind, objs] : c->objects) {
    for (const auto& [key, e] : objs) {
      append_u32(buf, static_cast<uint32_t>(kind.size()));
      buf += kind;
      append_u32(buf, static_cast<uint32_t>(key.size()));
      buf += key;
      append_i64(buf, e.mod_rev);
      append_i64(buf, e.create_rev);
      append_u32(buf, static_cast<uint32_t>(e.value.size()));
      buf += e.value;
    }
  }
  size_t written = std::fwrite(buf.data(), 1, buf.size(), f);
  std::fclose(f);
  return written == buf.size() ? SC_OK : SC_ERR_IO;
}

int64_t sc_load(void* h, const char* path) {
  Core* c = static_cast<Core*>(h);
  std::lock_guard<std::mutex> lock(c->mu);
  FILE* f = std::fopen(path, "rb");
  if (!f) return SC_ERR_IO;
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  if (size < 0) { std::fclose(f); return SC_ERR_IO; }
  std::fseek(f, 0, SEEK_SET);
  std::string buf(static_cast<size_t>(size), '\0');
  if (std::fread(buf.data(), 1, buf.size(), f) != buf.size()) {
    std::fclose(f);
    return SC_ERR_IO;
  }
  std::fclose(f);
  if (size < 0 || buf.size() < 12 || buf.compare(0, 4, "SCK1") != 0)
    return SC_ERR_IO;
  size_t off = 4;
  bool bad = false;
  // every read bounds-checks: a truncated/corrupt checkpoint must yield
  // SC_ERR_IO, never an OOB read or a C++ exception crossing the C ABI
  auto read_u32 = [&](uint32_t* v) {
    if (off + 4 > buf.size()) { bad = true; *v = 0; return; }
    std::memcpy(v, buf.data() + off, 4);
    off += 4;
  };
  auto read_i64 = [&](int64_t* v) {
    if (off + 8 > buf.size()) { bad = true; *v = 0; return; }
    std::memcpy(v, buf.data() + off, 8);
    off += 8;
  };
  auto read_str = [&](std::string* s_out, uint32_t len) {
    if (bad || off + len > buf.size()) { bad = true; return; }
    s_out->assign(buf, off, len);
    off += len;
  };
  int64_t revision = 0;
  read_i64(&revision);
  std::map<std::string, std::map<std::string, Entry>> objects;
  while (!bad && off < buf.size()) {
    uint32_t kind_len = 0, key_len = 0, val_len = 0;
    std::string kind, key;
    Entry e;
    read_u32(&kind_len);
    read_str(&kind, kind_len);
    read_u32(&key_len);
    read_str(&key, key_len);
    read_i64(&e.mod_rev);
    read_i64(&e.create_rev);
    read_u32(&val_len);
    read_str(&e.value, val_len);
    if (bad) break;
    objects[kind][key] = std::move(e);
  }
  if (bad) return SC_ERR_IO;
  c->revision = revision;
  c->objects = std::move(objects);
  c->logs.clear();
  c->compacted.clear();
  return SC_OK;
}

}  // extern "C"
