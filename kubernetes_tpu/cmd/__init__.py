"""Component entry points (the cmd/ layer of the reference)."""
