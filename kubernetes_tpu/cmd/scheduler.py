"""Scheduler CLI/server: config loading, leader election, health + metrics.

Reference: cmd/kube-scheduler/app/server.go (NewSchedulerCommand:93, Run:174,
leader election :301-345, healthz/metrics mux :367-390). argparse stands in
for cobra; the serving mux exposes /healthz, /readyz, /metrics and
/debug/pprof-style stats.
"""

from __future__ import annotations

import argparse
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..config.types import SchedulerConfiguration, load_config_file
from ..scheduler import Profile, Scheduler
from ..scheduler.metrics import SchedulerMetrics
from ..store.store import Store
from ..utils.featuregate import FeatureGate


class SchedulerServer:
    """One running scheduler instance + its serving mux."""

    def __init__(self, store: Store, config: SchedulerConfiguration,
                 identity: str = "scheduler-0", fleet_size: int = 1,
                 shard_id: int | None = None):
        self.config = config
        self.store = store
        self.identity = identity
        self.fleet_size = max(1, int(fleet_size))
        self.shard_id = shard_id
        self.metrics = SchedulerMetrics()
        gates = FeatureGate()
        gates.set_from_map(config.feature_gates)
        self.feature_gates = gates
        profiles = [
            Profile(
                name=p.scheduler_name,
                percentage_of_nodes_to_score=(
                    p.percentage_of_nodes_to_score
                    if p.percentage_of_nodes_to_score is not None
                    else config.percentage_of_nodes_to_score
                ),
                plugin_args=p.plugin_args,
                backend=p.backend,
                wave_size=p.wave_size,
                disabled_plugins=tuple(p.plugins.disabled),
                enabled_plugins=tuple(p.plugins.enabled),
            )
            for p in config.profiles
        ]
        # span export for /debug/traces: a bounded in-memory exporter takes
        # the OTLP exporter's role; the flight recorder's phase/wave spans
        # all land here because the scheduler shares this tracer
        from ..utils.tracing import InMemoryExporter, Tracer

        self.trace_exporter = InMemoryExporter(capacity=512)
        self.tracer = Tracer("tpu-scheduler", exporter=self.trace_exporter)
        # AOT warm restart (README "Restart & recovery"): any device
        # profile pre-lowers its wave kernels at start() so a restarted
        # scheduler re-enters service compile-free; KUBE_TPU_WARMUP=0
        # opts out (lazy compilation, first waves pay the tracing tax)
        from ..utils.envknob import int_env

        warm = (any(p.backend == "tpu" for p in profiles)
                and int_env("KUBE_TPU_WARMUP", 1) != 0)
        self.scheduler = Scheduler(
            store,
            profiles=profiles,
            feature_gates=gates.as_map(),
            metrics=self.metrics,
            async_api_calls=gates.enabled("SchedulerAsyncAPICalls"),
            parallelism=config.parallelism,
            extenders=config.extenders,
            tracer=self.tracer,
            warm_start=warm,
        )
        # SIGUSR2 → cache dump + cache/store comparison (the reference's
        # backend/cache/debugger wiring)
        from ..scheduler.cache.debugger import CacheDebugger

        backend = next(
            (b for algo in self.scheduler.algorithms.values()
             if (b := getattr(algo, "backend", None)) is not None),
            None,
        )
        self.backend = backend  # also serves /debug/flightrecorder
        self.debugger = CacheDebugger(
            self.scheduler.cache, self.scheduler.queue, store,
            backend=backend,
        )
        try:
            self.debugger.install()
        except ValueError:
            pass  # not the main thread (tests): on-demand calls still work
        self.elector = None
        self.fleet = None
        if self.fleet_size > 1:
            # active-active fleet (scheduler/fleet.py): shard ownership
            # replaces the single global lease — per-shard leases when
            # leader election is on, a pinned --shard-id otherwise
            from ..scheduler.fleet import FleetMember

            le = config.leader_election
            static = (
                {shard_id}
                if (shard_id is not None and not le.leader_elect)
                else None
            )
            self.fleet = FleetMember(
                self.scheduler,
                self.fleet_size,
                identity,
                preferred_shard=shard_id,
                static_shards=static,
                lease_name=le.resource_name,
                namespace=le.resource_namespace,
                lease_duration=le.lease_duration,
                renew_deadline=le.renew_deadline,
                retry_period=le.retry_period,
            )
        elif config.leader_election.leader_elect:
            from ..client.leaderelection import LeaderElector

            le = config.leader_election
            self.elector = LeaderElector(
                store=store,
                identity=identity,
                name=le.resource_name,
                namespace=le.resource_namespace,
                lease_duration=le.lease_duration,
                renew_deadline=le.renew_deadline,
                retry_period=le.retry_period,
            )
        self._stop = threading.Event()
        self._http: ThreadingHTTPServer | None = None
        self._threads: list[threading.Thread] = []
        self.started = False
        import time as _time

        self.start_time = _time.time()
        self.start_mono = _time.monotonic()
        self.flags: dict = {}  # effective flags, filled by main()

    # -- serving mux (server.go:367-390) -------------------------------------

    def _build_handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def _send(self, code: int, body: str, ctype="text/plain"):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/healthz":
                    self._send(200, "ok")
                elif self.path == "/readyz":
                    # readyz includes informer sync + leadership watchdog
                    # (server.go:199-253)
                    ready = server.started and (
                        server.elector is None or server.elector.is_leader()
                    )
                    self._send(200 if ready else 503, "ok" if ready else "not ready")
                elif self.path == "/metrics":
                    self._send(200, server.metrics.expose(),
                               "text/plain; version=0.0.4")
                elif self.path == "/configz":
                    self._send(200, json.dumps({
                        "parallelism": server.config.parallelism,
                        "featureGates": server.feature_gates.as_map(),
                        "profiles": [p.scheduler_name for p in server.config.profiles],
                    }), "application/json")
                elif self.path == "/statusz":
                    # component-base/zpages/statusz: liveness + identity
                    import time as _time

                    self._send(200, json.dumps({
                        "component": "tpu-scheduler",
                        "startTime": server.start_time,
                        "uptimeSeconds": round(
                            _time.monotonic() - server.start_mono, 1
                        ),
                        "leader": (server.elector is None
                                   or server.elector.is_leader()),
                    }), "application/json")
                elif self.path.startswith("/debug/pprof/profile"):
                    # sampling CPU profile (routes.Profiling, server.go:390)
                    from urllib.parse import parse_qs, urlparse

                    from ..utils.pprof import take_profile

                    q = parse_qs(urlparse(self.path).query)
                    try:
                        secs = min(float(q.get("seconds", ["1"])[0]), 30.0)
                    except ValueError:
                        self._send(400, "seconds must be a number")
                        return
                    self._send(200, take_profile(seconds=secs))
                elif self.path.startswith("/debug/flightrecorder"):
                    # wave flight-recorder post-mortem dump (zpages-style);
                    # ?last=N bounds the ring-buffer slice
                    from urllib.parse import parse_qs, urlparse

                    rec = getattr(server.backend, "recorder", None)
                    if rec is None:
                        self._send(404, "no TPU backend / flight recorder")
                        return
                    q = parse_qs(urlparse(self.path).query)
                    try:
                        last = (int(q["last"][0]) if "last" in q else None)
                    except ValueError:
                        self._send(400, "last must be an integer")
                        return
                    self._send(200, rec.dump(last), "application/json")
                elif self.path.startswith("/debug/podlatency"):
                    # pod latency ledger zpage: per-pod e2e decomposition;
                    # ?last=N (recent completions) &slowest=K (worst e2e)
                    from urllib.parse import parse_qs, urlparse

                    ledger = server.scheduler.flight_recorder.pod_ledger
                    q = parse_qs(urlparse(self.path).query)
                    try:
                        last = int(q.get("last", ["10"])[0])
                        slowest = int(q.get("slowest", ["5"])[0])
                    except ValueError:
                        self._send(400, "last/slowest must be integers")
                        return
                    self._send(200, json.dumps(
                        ledger.snapshot(last=last, slowest=slowest), indent=2
                    ), "application/json")
                elif self.path.startswith("/debug/devicetelemetry"):
                    # device telemetry zpage: transfer ledger per plane,
                    # compile tracker, device-memory watermark
                    telemetry = (
                        server.scheduler.flight_recorder.device_telemetry
                    )
                    self._send(200, json.dumps(
                        telemetry.snapshot(), indent=2
                    ), "application/json")
                elif self.path.startswith("/debug/stalls"):
                    # stall profiler zpage: per-wave wall-clock attribution
                    # (overlap + named stall reasons), the dominant reason,
                    # and the slowest wave's critical path; ?last=N bounds
                    # the per-wave rows
                    from urllib.parse import parse_qs, urlparse

                    profiler = (
                        server.scheduler.flight_recorder.stall_profiler
                    )
                    q = parse_qs(urlparse(self.path).query)
                    try:
                        last = int(q.get("last", ["10"])[0])
                    except ValueError:
                        self._send(400, "last must be an integer")
                        return
                    self._send(200, json.dumps(
                        profiler.snapshot(last=last), indent=2
                    ), "application/json")
                elif self.path.startswith("/debug/traces"):
                    # OTLP-shaped span export (the /debug/traces zpage);
                    # ?last=N bounds to the most recent N root spans
                    import json as _json
                    from urllib.parse import parse_qs, urlparse

                    from ..utils.tracing import spans_to_otlp

                    q = parse_qs(urlparse(self.path).query)
                    try:
                        last = (int(q["last"][0]) if "last" in q else None)
                    except ValueError:
                        self._send(400, "last must be an integer")
                        return
                    spans = server.trace_exporter.last(last)
                    self._send(200, _json.dumps(
                        spans_to_otlp(spans, component=server.tracer.component)
                    ), "application/json")
                elif self.path == "/flagz":
                    # component-base/zpages/flagz: effective flag values
                    self._send(200, json.dumps(server.flags),
                               "application/json")
                else:
                    self._send(404, "not found")

            def log_message(self, *a):
                pass

        return Handler

    def serve(self, port: int = 0) -> int:
        """Start the health/metrics mux; returns the bound port."""
        self._http = ThreadingHTTPServer(("127.0.0.1", port), self._build_handler())
        t = threading.Thread(target=self._http.serve_forever, daemon=True)
        t.start()
        self._threads.append(t)
        return self._http.server_port

    # -- run -----------------------------------------------------------------

    def run(self, block: bool = True) -> None:
        """server.go Run: serve health endpoints immediately, schedule only
        while holding leadership (if enabled)."""
        if self._http is None and self.config.health_bind_port:
            self.serve(self.config.health_bind_port)
        if self.elector is not None:
            self.elector.on_started_leading = self._start_scheduling
            self.elector.on_stopped_leading = self._stop_scheduling
            t = threading.Thread(target=self.elector.run, daemon=True)
            t.start()
            self._threads.append(t)
        else:
            self._start_scheduling()
        if block:
            try:
                while not self._stop.wait(0.2):
                    pass
            except KeyboardInterrupt:
                pass
            self.shutdown()

    def _start_scheduling(self) -> None:
        # per-leadership-term stop event: losing the lease MUST halt this
        # term's loop (split-brain double-binding otherwise), and a
        # re-acquired term starts a fresh loop
        if self.started:
            return
        self._sched_stop = threading.Event()
        if self.fleet is not None:
            # fleet start: informer sync + per-shard lease contention;
            # shard_adopt/acquire reconciles run inside the acquire
            # callbacks, scoped to each shard as it is won
            self.fleet.start()
        else:
            self.scheduler.start()
        self.started = True

        def run_term(stop=self._sched_stop):
            retry = self.config.leader_election.retry_period
            last_elect = self.scheduler.clock.now()
            while not stop.is_set() and not self._stop.is_set():
                if self.fleet is not None:
                    now = self.scheduler.clock.now()
                    if now - last_elect >= retry:
                        last_elect = now
                        # renew held shard leases / adopt orphans between
                        # scheduling rounds (single-threaded with the pops)
                        self.fleet.elect_once()
                self.scheduler.pump()
                self.scheduler.loop.schedule_one(timeout=0.05)

        t = threading.Thread(target=run_term, daemon=True)
        t.start()
        self._threads.append(t)

    def _stop_scheduling(self) -> None:
        self.started = False
        stop = getattr(self, "_sched_stop", None)
        if stop is not None:
            stop.set()

    def shutdown(self) -> None:
        self._stop.set()
        if self.fleet is not None:
            self.fleet.stop()  # release shard leases for instant adoption
        if self.elector is not None:
            self.elector.stop()
        if self._http is not None:
            self._http.shutdown()
        if self.scheduler.api_dispatcher is not None:
            self.scheduler.api_dispatcher.close()


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="tpu-scheduler",
        description="TPU-native scheduler (cmd/kube-scheduler equivalent)",
    )
    parser.add_argument("--config", help="KubeSchedulerConfiguration YAML")
    parser.add_argument("--backend", choices=["host", "tpu"], default=None,
                        help="override profile backend")
    parser.add_argument("--wave-size", type=int, default=None,
                        help="override profile waveSize (batched device "
                             "waves; requires backend=tpu)")
    parser.add_argument("--port", type=int, default=10259,
                        help="health/metrics port")
    parser.add_argument("--leader-elect", action="store_true")
    parser.add_argument("--fleet-size", type=int, default=None,
                        help="active-active fleet: total shard count "
                             "(env KUBE_TPU_FLEET_SIZE; 1 = single "
                             "scheduler, the default)")
    parser.add_argument("--shard-id", type=int, default=None,
                        help="this member's preferred shard (env "
                             "KUBE_TPU_SHARD_ID; with --leader-elect it "
                             "seeds lease contention, without it the "
                             "shard is pinned statically)")
    parser.add_argument("--v", type=int, default=0,
                        help="log verbosity (klog levels)")
    parser.add_argument("--log-format", choices=["text", "json"],
                        default="text")
    args = parser.parse_args(argv)

    from ..utils.logging import configure as configure_logging

    configure_logging(fmt=args.log_format, verbosity_level=args.v)

    config = (
        load_config_file(args.config) if args.config else SchedulerConfiguration()
    )
    if args.backend:
        for p in config.profiles:
            p.backend = args.backend
    if args.wave_size is not None:
        for p in config.profiles:
            p.wave_size = args.wave_size
    if args.leader_elect:
        config.leader_election.leader_elect = True
    config.health_bind_port = args.port
    if any(p.backend == "tpu" for p in config.profiles):
        # persistent XLA compilation cache: restarts replay lowerings from
        # disk instead of recompiling (the warm-restart path assumes it)
        from ..utils.jaxcache import enable_persistent_cache

        enable_persistent_cache()
    from ..utils.envknob import int_env

    fleet_size = (args.fleet_size if args.fleet_size is not None
                  else int_env("KUBE_TPU_FLEET_SIZE", 1))
    shard_id = (args.shard_id if args.shard_id is not None
                else int_env("KUBE_TPU_SHARD_ID", -1))
    if shard_id is not None and shard_id < 0:
        shard_id = None
    identity = (f"scheduler-{shard_id}" if shard_id is not None
                else "scheduler-0")
    server = SchedulerServer(Store(), config, identity=identity,
                             fleet_size=fleet_size, shard_id=shard_id)
    server.flags = {k: v for k, v in vars(args).items()}
    server.run(block=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
