"""kube-controller-manager binary: the controller set behind one process.

Reference: cmd/kube-controller-manager — flags → controller set on a
shared informer factory, Lease-based leader election (only the leader's
controllers run), /healthz. Controllers run threaded (Controller.run per
controller) while leadership holds; losing the lease stops them.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..controllers import ControllerManager, default_controllers


class ControllerManagerServer:
    def __init__(self, store, identity: str = "kcm-0",
                 leader_elect: bool = False):
        self.store = store
        self.identity = identity
        self.leader_elect = leader_elect
        self.manager = ControllerManager(store, default_controllers(store))
        self.elector = None
        self._stop = threading.Event()
        self._run_stop: threading.Event | None = None
        self._http: ThreadingHTTPServer | None = None

    def _build_handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path == "/healthz":
                    ok = not server._stop.is_set()
                    body = b"ok" if ok else b"stopping"
                    self.send_response(200 if ok else 503)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path == "/readyz":
                    leading = (server.elector is None
                               or server.elector.is_leader())
                    body = b"ok" if leading else b"not leader"
                    self.send_response(200 if leading else 503)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_response(404)
                    self.end_headers()

            def log_message(self, *a):
                pass

        return Handler

    def serve(self, port: int = 0) -> int:
        self._http = ThreadingHTTPServer(("127.0.0.1", port),
                                         self._build_handler())
        threading.Thread(target=self._http.serve_forever, daemon=True).start()
        return self._http.server_address[1]

    def _start_controllers(self) -> None:
        if self._run_stop is None:
            self._run_stop = threading.Event()
            self.manager.run(self._run_stop)

    def _stop_controllers(self) -> None:
        if self._run_stop is not None:
            self._run_stop.set()
            self._run_stop = None

    def run(self, block: bool = False) -> None:
        if not self.leader_elect:
            self._start_controllers()
            if block:
                self._stop.wait()
            return
        from ..client.leaderelection import LeaderElector

        self.elector = LeaderElector(
            store=self.store,
            identity=self.identity,
            name="kube-controller-manager",
            on_started_leading=self._start_controllers,
            on_stopped_leading=self._stop_controllers,
        )
        threading.Thread(target=self.elector.run, daemon=True).start()
        if block:
            self._stop.wait()

    def shutdown(self) -> None:
        self._stop.set()
        self._stop_controllers()
        if self.elector is not None:
            self.elector.stop()
        if self._http is not None:
            self._http.shutdown()


def main(argv: list[str] | None = None) -> int:
    import argparse

    from ..client.rest import RESTStore

    parser = argparse.ArgumentParser(description="controller manager")
    parser.add_argument("--server", required=True, help="API server URL")
    parser.add_argument("--token", default="")
    parser.add_argument("--cacert", default=None,
                        help="CA bundle for an https:// server")
    parser.add_argument("--identity", default="kcm-0")
    parser.add_argument("--leader-elect", action="store_true")
    parser.add_argument("--port", type=int, default=10257)
    args = parser.parse_args(argv)
    server = ControllerManagerServer(
        RESTStore(args.server, token=args.token,
                  ca_cert=getattr(args, 'cacert', None)),
        identity=args.identity, leader_elect=args.leader_elect,
    )
    server.serve(args.port)
    server.run(block=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
