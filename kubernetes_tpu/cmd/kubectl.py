"""kubectl-equivalent CLI over the REST API.

Reference: staging/src/k8s.io/kubectl + cmd/kubectl — the verb surface
(get, describe, create -f, apply -f, delete, scale, cordon/uncordon) over
client-go. Manifests use the api/serialization wire shape; `apply` is
SERVER-SIDE apply under the "kubectl" field manager (apiserver/apply.py
fieldmanager: ownership tracking, conflict detection, dropped-field
removal).
"""

from __future__ import annotations

import argparse
import json
import sys

from ..api.serialization import decode, encode
from ..client.rest import RESTStore
from ..store.store import AlreadyExistsError, NotFoundError

DEFAULT_SERVER = "http://127.0.0.1:6443"

# kubectl resource aliases
ALIASES = {
    "pod": "Pod", "pods": "Pod", "po": "Pod",
    "node": "Node", "nodes": "Node", "no": "Node",
    "deployment": "Deployment", "deployments": "Deployment", "deploy": "Deployment",
    "replicaset": "ReplicaSet", "replicasets": "ReplicaSet", "rs": "ReplicaSet",
    "job": "Job", "jobs": "Job",
    "service": "Service", "services": "Service", "svc": "Service",
    "endpointslice": "EndpointSlice", "endpointslices": "EndpointSlice",
    "pv": "PersistentVolume", "persistentvolume": "PersistentVolume",
    "pvc": "PersistentVolumeClaim", "persistentvolumeclaim": "PersistentVolumeClaim",
    "storageclass": "StorageClass", "sc": "StorageClass",
    "podgroup": "PodGroup", "podgroups": "PodGroup", "pg": "PodGroup",
    "resourceclaim": "ResourceClaim", "resourceclaims": "ResourceClaim",
    "configmap": "ConfigMap", "configmaps": "ConfigMap", "cm": "ConfigMap",
    "secret": "Secret", "secrets": "Secret",
    "cronjob": "CronJob", "cronjobs": "CronJob", "cj": "CronJob",
    "hpa": "HorizontalPodAutoscaler",
    "horizontalpodautoscaler": "HorizontalPodAutoscaler",
    "resourcequota": "ResourceQuota", "quota": "ResourceQuota",
    "statefulset": "StatefulSet", "statefulsets": "StatefulSet",
    "sts": "StatefulSet",
    "daemonset": "DaemonSet", "daemonsets": "DaemonSet", "ds": "DaemonSet",
    "resourceslice": "ResourceSlice", "resourceslices": "ResourceSlice",
    "lease": "Lease", "leases": "Lease",
}


def _kind(resource: str) -> str:
    return ALIASES.get(resource.lower(), resource)


def _key(kind: str, name: str, namespace: str) -> str:
    # one source of truth for scoping (discovery.CLUSTER_SCOPED)
    from ..apiserver.discovery import CLUSTER_SCOPED

    return name if kind in CLUSTER_SCOPED else f"{namespace}/{name}"


def _status_of(obj) -> str:
    if obj.kind == "Pod":
        return obj.status.phase if not obj.spec.node_name else (
            f"{obj.status.phase} on {obj.spec.node_name}"
        )
    if obj.kind == "Node":
        ready = next((c for c in obj.status.conditions if c.type == "Ready"), None)
        return "Ready" if ready and ready.status == "True" else "NotReady"
    if obj.kind in ("Deployment", "ReplicaSet"):
        return f"{obj.status.ready_replicas}/{obj.spec.replicas} ready"
    if obj.kind == "Job":
        return "Complete" if obj.status.completed else f"{obj.status.succeeded} succeeded"
    if obj.kind == "PersistentVolumeClaim":
        return obj.status.phase
    return ""


def _aggregated_resource(client: RESTStore, resource: str):
    """Resolve a resource name through aggregated-API discovery: walk
    /apis (merged APIGroupList), then each group/version's proxied
    APIResourceList, matching name or kind (kubectl's RESTMapper over
    discovery). Returns (groupVersion, resource-name, namespaced)."""
    try:
        groups = client.raw_get("/apis").get("groups", [])
    except Exception:  # noqa: BLE001 - no aggregation layer configured
        return None
    want = resource.lower()
    for g in groups:
        for v in g.get("versions", []):
            gv = v["groupVersion"]
            try:
                rl = client.raw_get(f"/apis/{gv}")
            except Exception:  # noqa: BLE001 - delegate down; keep looking
                continue
            for r in rl.get("resources", []):
                if want in (r.get("name", "").lower(),
                            r.get("kind", "").lower(),
                            r.get("kind", "").lower() + "s"):
                    return gv, r["name"], bool(r.get("namespaced"))
    return None


def _get_aggregated(client: RESTStore, args) -> int:
    """kubectl get over an aggregated resource: fetch through the MAIN
    server (which proxies to the APIService delegate) and print the
    unstructured items."""
    found = _aggregated_resource(client, args.resource)
    if found is None:
        print(f"Error: the server doesn't have a resource type "
              f"{args.resource!r}", file=sys.stderr)
        return 1
    gv, rname, namespaced = found
    if namespaced and not args.all_namespaces:
        path = f"/apis/{gv}/namespaces/{args.namespace}/{rname}"
    else:
        path = f"/apis/{gv}/{rname}"
    if args.name:
        path += f"/{args.name}"
    try:
        doc = client.raw_get(path)
    except Exception as e:  # noqa: BLE001 - surfaced to the user
        print(f"Error: {e}", file=sys.stderr)
        return 1
    if args.output == "json":
        print(json.dumps(doc, indent=2))
        return 0
    items = doc.get("items", [doc])
    print("NAME\tUSAGE")
    for item in items:
        meta = item.get("metadata", {})
        usage = item.get("usage") or {}
        if not usage and item.get("containers"):
            usage = item["containers"][0].get("usage", {})
        usage_s = ",".join(f"{k}={v}" for k, v in sorted(usage.items()))
        print(f"{meta.get('name', '?')}\t{usage_s}")
    return 0


def cmd_get(client: RESTStore, args) -> int:
    kind = _kind(args.resource)
    from ..api.serialization import _KINDS, _register_all

    _register_all()
    if kind not in _KINDS:
        # not a core kind: try the aggregation layer's discovery
        return _get_aggregated(client, args)
    if args.name:
        try:
            obj = client.get(kind, _key(kind, args.name, args.namespace))
        except NotFoundError as e:
            print(f"Error: {e}", file=sys.stderr)
            return 1
        if args.output == "json":
            print(json.dumps(encode(obj), indent=2))
        else:
            print(f"{obj.meta.name}\t{_status_of(obj)}")
        return 0
    items, _ = client.list(kind)
    visible = [
        obj for obj in sorted(items, key=lambda o: o.meta.key)
        if obj.meta.namespace in ("", args.namespace) or args.all_namespaces
    ]
    if args.output == "json":
        print(json.dumps([encode(o) for o in visible], indent=2))
    else:
        print(f"NAME\tSTATUS")
        for obj in visible:
            print(f"{obj.meta.name}\t{_status_of(obj)}")
    return 0


def cmd_describe(client: RESTStore, args) -> int:
    kind = _kind(args.resource)
    try:
        obj = client.get(kind, _key(kind, args.name, args.namespace))
    except NotFoundError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    print(json.dumps(encode(obj), indent=2))
    return 0


def _load_manifests(path: str) -> list[dict]:
    import yaml

    source = sys.stdin if path == "-" else open(path)
    with source if path != "-" else sys.stdin as f:
        return [d for d in yaml.safe_load_all(f) if d]


def cmd_apply(client: RESTStore, args) -> int:
    """Server-side apply under the "kubectl" field manager (the reference's
    kubectl --server-side path): per-field ownership, conflict detection
    (--force-conflicts transfers), dropped fields removed."""
    from kubernetes_tpu.store.store import ConflictError

    from kubernetes_tpu.client.rest import ApplyConflictError

    force = getattr(args, "force_conflicts", False)
    for doc in _load_manifests(args.filename):
        obj = decode(doc)  # decode validates the manifest + resolves keys
        try:
            # a plain Conflict is a CAS race against a concurrent writer:
            # retry (the reference's patch handler retries internally); a
            # FieldManagerConflict is ownership and needs --force-conflicts
            for attempt in range(3):
                try:
                    client.apply(obj.kind, obj.meta.key, doc, "kubectl",
                                 force=force)
                    break
                except ApplyConflictError:
                    raise
                except ConflictError:
                    if attempt == 2:
                        raise
        except ApplyConflictError as e:
            print(f"Error: {e}\nhint: --force-conflicts transfers ownership",
                  file=sys.stderr)
            return 1
        except ConflictError as e:
            print(f"Error: {e}", file=sys.stderr)
            return 1
        print(f"{obj.kind.lower()}/{obj.meta.name} "
              f"{'created' if client.last_apply_created else 'configured'}")
    return 0


def cmd_create(client: RESTStore, args) -> int:
    for doc in _load_manifests(args.filename):
        obj = decode(doc)
        client.create(obj)
        print(f"{obj.kind.lower()}/{obj.meta.name} created")
    return 0


def cmd_delete(client: RESTStore, args) -> int:
    kind = _kind(args.resource)
    try:
        client.delete(kind, _key(kind, args.name, args.namespace))
        print(f"{kind.lower()}/{args.name} deleted")
        return 0
    except NotFoundError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1


def cmd_scale(client: RESTStore, args) -> int:
    kind = _kind(args.resource)
    obj = client.get(kind, _key(kind, args.name, args.namespace))
    obj.spec.replicas = args.replicas
    client.update(obj, check_version=False)
    print(f"{kind.lower()}/{args.name} scaled to {args.replicas}")
    return 0


def cmd_cordon(client: RESTStore, args, unschedulable: bool = True) -> int:
    node = client.get("Node", args.name)
    node.spec.unschedulable = unschedulable
    client.update(node, check_version=False)
    print(f"node/{args.name} {'cordoned' if unschedulable else 'uncordoned'}")
    return 0


def cmd_patch(client: RESTStore, args) -> int:
    kind = _kind(args.resource)
    try:
        patch = json.loads(args.patch)
    except json.JSONDecodeError as e:
        print(f"Error: invalid patch JSON: {e}", file=sys.stderr)
        return 1
    try:
        client.patch(kind, _key(kind, args.name, args.namespace), patch)
    except Exception as e:  # noqa: BLE001 - CLI boundary
        print(f"Error: {e}", file=sys.stderr)
        return 1
    print(f"{kind.lower()}/{args.name} patched")
    return 0


def cmd_logs(client: RESTStore, args) -> int:
    """kubectl logs: the pods/log subresource (apiserver proxies to the
    pod's kubelet /containerLogs endpoint)."""
    try:
        sys.stdout.write(client.pod_logs(
            _key("Pod", args.name, args.namespace),
            container=args.container, tail_lines=args.tail,
        ))
        return 0
    except Exception as e:  # noqa: BLE001 - CLI boundary
        print(f"Error: {e}", file=sys.stderr)
        return 1


def cmd_drain(client: RESTStore, args) -> int:
    """kubectl drain: cordon, then evict every pod on the node, honoring
    PodDisruptionBudgets (staging/.../kubectl/pkg/drain): an eviction that
    would take a PDB below its budget is refused and retried; --force
    overrides for pods with no budget room after the grace rounds."""
    cmd_cordon(client, args, True)
    import time as _time

    deadline = _time.monotonic() + args.timeout
    warned_ds = False
    while True:
        pods = [p for p in client.pods() if p.spec.node_name == args.name]
        # DaemonSet pods tolerate the cordon taint and would be re-minted
        # onto this node forever — real kubectl ignores them for the same
        # reason (--ignore-daemonsets is effectively mandatory)
        ds_pods = [p for p in pods if any(
            r.kind == "DaemonSet" and r.controller
            for r in p.meta.owner_references
        )]
        if ds_pods and not warned_ds:
            warned_ds = True
            for p in ds_pods:
                print(f"ignoring DaemonSet-managed pod {p.meta.key}")
        pods = [p for p in pods if p not in ds_pods]
        if not pods:
            print(f"node/{args.name} drained")
            return 0
        pdbs = list(client.iter_kind("PodDisruptionBudget"))  # once per round
        blocked = []
        for pod in pods:
            pdb = _pdb_for(pdbs, pod)
            if pdb is not None and not _consume_disruption(client, pdb, pod):
                blocked.append(pod.meta.key)
                continue
            client.delete("Pod", pod.meta.key)
            print(f"evicting pod {pod.meta.key}")
        if _time.monotonic() >= deadline:
            if blocked and args.force:
                for key in blocked:
                    client.delete("Pod", key)
                    print(f"evicting pod {key} (forced)")
                continue
            if blocked:
                print(f"error: cannot evict {len(blocked)} pod(s) "
                      f"(PodDisruptionBudget), use --force to override")
                return 1
            print(f"error: node/{args.name} still has pods after "
                  f"{args.timeout}s")
            return 1
        _time.sleep(args.poll)


def _pdb_for(pdbs, pod):
    from ..api.labels import matches_selector

    for pdb in pdbs:
        if pdb.meta.namespace != pod.meta.namespace:
            continue
        sel = pdb.spec.selector
        if sel is not None and matches_selector(sel, pod.meta.labels):
            return pdb
    return None


def _consume_disruption(client: RESTStore, pdb, pod, retries: int = 3) -> bool:
    """Atomically take one disruption from the budget: versioned
    compare-and-swap with retry, so concurrent drains (or the disruption
    controller) can't both spend the last allowed disruption — the
    client-side analogue of the server-side Eviction subresource."""
    import time as _time

    from ..store.store import ConflictError

    for _ in range(retries):
        if pdb.status.disruptions_allowed <= 0:
            return False
        pdb.status.disruptions_allowed -= 1
        pdb.status.disrupted_pods[pod.meta.name] = _time.time()
        try:
            client.update(pdb)  # CAS on resourceVersion
            return True
        except ConflictError:
            pdb = client.get("PodDisruptionBudget", pdb.meta.key)
    return False


def cmd_rollout(client: RESTStore, args) -> int:
    """kubectl rollout status|history|undo for Deployments
    (staging/.../kubectl/pkg/polymorphichelpers + rollback.go): revisions
    live on the owned ReplicaSets' deployment.kubernetes.io/revision
    annotations; undo copies a past revision's template back into the
    deployment spec (minus the pod-template-hash label)."""
    import time as _time

    kind = _kind(args.resource)
    if kind != "Deployment":
        print("error: rollout supports deployments", file=sys.stderr)
        return 1
    key = _key(kind, args.name, args.namespace)
    dep = client.get(kind, key)
    rs_list = [
        rs for rs in client.iter_kind("ReplicaSet")
        if rs.meta.namespace == dep.meta.namespace
        and any(r.kind == "Deployment" and r.name == dep.meta.name
                and r.controller for r in rs.meta.owner_references)
    ]
    rev_key = "deployment.kubernetes.io/revision"
    by_rev = {int(rs.meta.annotations.get(rev_key, 0)): rs for rs in rs_list}

    if args.action in ("pause", "resume"):
        dep.spec.paused = args.action == "pause"
        client.update(dep, check_version=False)
        print(f"deployment/{args.name} {args.action}d")
        return 0

    if args.action == "history":
        for rev in sorted(by_rev):
            rs = by_rev[rev]
            print(f"{rev}\t{rs.meta.name}\treplicas={rs.spec.replicas}")
        return 0

    if args.action == "status":
        deadline = _time.monotonic() + args.timeout
        while _time.monotonic() < deadline:
            dep = client.get(kind, key)
            if (dep.status.ready_replicas >= dep.spec.replicas
                    and dep.status.updated_replicas >= dep.spec.replicas):
                print(f'deployment "{args.name}" successfully rolled out')
                return 0
            _time.sleep(args.poll)
        print(f'error: deployment "{args.name}" not rolled out: '
              f"{dep.status.ready_replicas}/{dep.spec.replicas} ready",
              file=sys.stderr)
        return 1

    if args.action == "undo":
        current = int(dep.meta.annotations.get(rev_key, 0))
        target_rev = args.to_revision or max(
            (r for r in by_rev if r != current), default=0
        )
        if target_rev not in by_rev:
            print(f"error: revision {target_rev} not found", file=sys.stderr)
            return 1
        rs = by_rev[target_rev]
        template = rs.spec.template
        labels = {k: v for k, v in template.labels.items()
                  if k != "pod-template-hash"}
        dep.spec.template = type(template)(labels=labels, spec=template.spec)
        client.update(dep, check_version=False)
        print(f"deployment/{args.name} rolled back to revision {target_rev}")
        return 0

    print(f"error: unknown rollout action {args.action}", file=sys.stderr)
    return 1


def cmd_top(client: RESTStore, args) -> int:
    """kubectl top pods/nodes — the metrics.k8s.io view (PodMetrics
    published by kubelets)."""
    kind = _kind(args.resource)
    if kind == "Pod":
        metrics, _ = client.list("PodMetrics")
        print("NAME\tCPU(m)\tMEMORY(Mi)")
        for m in sorted(metrics, key=lambda m: m.meta.key):
            if not args.all_namespaces and m.meta.namespace != args.namespace:
                continue
            print(f"{m.meta.name}\t{m.cpu_usage_milli}m\t"
                  f"{m.memory_usage_bytes >> 20}Mi")
        return 0
    if kind == "Node":
        metrics, _ = client.list("PodMetrics")
        pods, _ = client.list("Pod")
        node_of = {p.meta.key: p.spec.node_name for p in pods}
        by_node: dict[str, list] = {}
        for m in metrics:
            node = node_of.get(m.meta.key)
            if node:
                by_node.setdefault(node, []).append(m)
        print("NAME\tCPU(m)\tMEMORY(Mi)")
        for node in sorted(n.meta.name for n in client.nodes()):
            ms = by_node.get(node, [])
            cpu = sum(m.cpu_usage_milli for m in ms)
            mem = sum(m.memory_usage_bytes for m in ms)
            print(f"{node}\t{cpu}m\t{mem >> 20}Mi")
        return 0
    print(f"error: top supports pods/nodes, not {args.resource}",
          file=sys.stderr)
    return 1


def cmd_events(client: RESTStore, args) -> int:
    """kubectl get events — the Scheduled/FailedScheduling stream."""
    events = sorted(client.iter_kind("Event"),
                    key=lambda e: getattr(e, "last_timestamp", 0))
    for ev in events:
        if not args.all_namespaces and ev.meta.namespace != args.namespace:
            continue
        print(f"{ev.type}\t{ev.reason}\t{ev.involved_object}\t"
              f"{ev.message}\t{getattr(ev, 'count', 1)}")
    return 0


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog="kubectl-tpu")
    parser.add_argument("--server", "-s", default=DEFAULT_SERVER)
    parser.add_argument("--cacert", default=None,
                        help="CA bundle for an https:// server")
    parser.add_argument("--namespace", "-n", default="default")
    sub = parser.add_subparsers(dest="verb", required=True)

    g = sub.add_parser("get")
    g.add_argument("resource")
    g.add_argument("name", nargs="?")
    g.add_argument("-o", "--output", choices=["wide", "json"], default="wide")
    g.add_argument("-A", "--all-namespaces", action="store_true")

    d = sub.add_parser("describe")
    d.add_argument("resource")
    d.add_argument("name")

    for verb in ("apply", "create"):
        a = sub.add_parser(verb)
        a.add_argument("-f", "--filename", required=True)
        if verb == "apply":
            a.add_argument("--force-conflicts", action="store_true",
                           dest="force_conflicts")

    rm = sub.add_parser("delete")
    rm.add_argument("resource")
    rm.add_argument("name")

    sc = sub.add_parser("scale")
    sc.add_argument("resource")
    sc.add_argument("name")
    sc.add_argument("--replicas", type=int, required=True)

    for verb in ("cordon", "uncordon"):
        c = sub.add_parser(verb)
        c.add_argument("name")

    dr = sub.add_parser("drain")
    dr.add_argument("name")
    dr.add_argument("--force", action="store_true")
    dr.add_argument("--timeout", type=float, default=5.0)
    dr.add_argument("--poll", type=float, default=0.1)

    ev = sub.add_parser("events")
    ev.add_argument("-A", "--all-namespaces", action="store_true")

    tp = sub.add_parser("top")
    tp.add_argument("resource")
    tp.add_argument("-A", "--all-namespaces", action="store_true")

    pt = sub.add_parser("patch")
    pt.add_argument("resource")
    pt.add_argument("name")
    pt.add_argument("-p", "--patch", required=True,
                    help="JSON merge patch (RFC 7386)")

    lg = sub.add_parser("logs")
    lg.add_argument("name")
    lg.add_argument("-c", "--container", default="")
    lg.add_argument("--tail", type=int, default=None)

    ro = sub.add_parser("rollout")
    ro.add_argument("action",
                    choices=["status", "history", "undo", "pause", "resume"])
    ro.add_argument("resource")
    ro.add_argument("name")
    ro.add_argument("--to-revision", type=int, default=0)
    ro.add_argument("--timeout", type=float, default=10.0)
    ro.add_argument("--poll", type=float, default=0.05)
    return parser


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    client = RESTStore(args.server,
                       ca_cert=getattr(args, 'cacert', None))
    verbs = {
        "get": cmd_get,
        "describe": cmd_describe,
        "apply": cmd_apply,
        "create": cmd_create,
        "delete": cmd_delete,
        "scale": cmd_scale,
        "cordon": lambda c, a: cmd_cordon(c, a, True),
        "uncordon": lambda c, a: cmd_cordon(c, a, False),
        "drain": cmd_drain,
        "events": cmd_events,
        "top": cmd_top,
        "rollout": cmd_rollout,
        "logs": cmd_logs,
        "patch": cmd_patch,
    }
    return verbs[args.verb](client, args)


if __name__ == "__main__":
    raise SystemExit(main())
