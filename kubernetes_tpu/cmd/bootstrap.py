"""Cluster bootstrap: the kubeadm-init equivalent.

Reference: cmd/kubeadm brings up a control plane (apiserver, controller
manager, scheduler), mints credentials, and joins nodes. Here the whole
cluster is process-local: `ClusterBootstrap.init()` starts the API server
(optionally with bearer-token authn + RBAC bootstrap policy), the scheduler
loop, the controller manager, per-node hollow kubelets, and a node proxy —
and returns a kubeconfig-shaped dict (server URL + admin token) a client
can use immediately. `kubeadm join` is `add_node()`.
"""

from __future__ import annotations

import secrets
import threading

from ..apiserver.auth import RBACAuthorizer, TokenAuthenticator, User, bootstrap_policy
from ..apiserver.server import APIServer
from ..controllers import ControllerManager, default_controllers
from ..kubelet import HollowKubelet
from ..proxy import Proxier
from ..scheduler import Scheduler
from ..store.store import Store


class ClusterBootstrap:
    def __init__(self, nodes: int = 3, secure: bool = False, clock=None,
                 store: Store | None = None, backend: str = "host",
                 tls: bool = False):
        from ..utils.clock import Clock

        self.clock = clock or Clock()
        self.store = store or Store()
        self.nodes = nodes
        self.secure = secure
        self.tls = tls  # HTTPS serving (kubeadm's cert phase)
        self.ca_cert: str | None = None
        self._tls_key: str | None = None
        self.backend = backend
        self.admin_token = ""
        self.apiserver: APIServer | None = None
        self.scheduler: Scheduler | None = None
        self.controller_manager: ControllerManager | None = None
        self.kubelets: list[HollowKubelet] = []
        self.proxiers: list[Proxier] = []
        # node name -> (client key path, CA-signed client cert PEM) minted
        # by the CSR join flow (TLS mode)
        self.node_credentials: dict[str, tuple[str, str]] = {}
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    # -- phases (kubeadm's init workflow) ------------------------------------

    def init(self, serve_port: int = 0) -> dict:
        """Run all init phases; returns the admin kubeconfig dict."""
        self._phase_certs_and_auth()
        self._phase_control_plane(serve_port)
        self._phase_bootstrap_policy()
        self._phase_join_nodes()
        return self.kubeconfig()

    def _phase_certs_and_auth(self) -> None:
        if self.secure:
            self.admin_token = secrets.token_urlsafe(16)
        if self.tls:
            from ..apiserver.certs import generate_self_signed

            self.ca_cert, self._tls_key = generate_self_signed()

    def _phase_control_plane(self, serve_port: int) -> None:
        authn = authz = None
        if self.secure:
            from ..apiserver.auth import ServiceAccountIssuer

            authn = TokenAuthenticator({
                self.admin_token: User("kubernetes-admin",
                                       ("system:masters",)),
            }, sa_issuer=ServiceAccountIssuer(self.store))
            authz = RBACAuthorizer(self.store)
        from ..apiserver.admission import default_admission_chain

        self.apiserver = APIServer(self.store,
                                   admission=default_admission_chain(self.store),
                                   authenticator=authn, authorizer=authz)
        if self.tls:
            self.apiserver.serve(serve_port, tls_cert=self.ca_cert,
                                 tls_key=self._tls_key)
        else:
            self.apiserver.serve(serve_port)
        from ..scheduler import Profile

        profiles = [Profile(backend=self.backend,
                            wave_size=256 if self.backend == "tpu" else 0)]
        self.scheduler = Scheduler(self.store, profiles=profiles,
                                   clock=self.clock)
        self.scheduler.start()  # sync informers before any pods arrive
        self.controller_manager = ControllerManager(
            self.store, default_controllers(
                self.store, clock=self.clock,
                ca_cert=self.ca_cert or "", ca_key=self._tls_key or "",
            )
        )

    def _phase_bootstrap_policy(self) -> None:
        if not self.secure:
            return
        for obj in bootstrap_policy():
            if self.store.try_get(obj.kind, obj.meta.key) is None:
                self.store.create(obj)

    def _phase_join_nodes(self) -> None:
        for i in range(self.nodes):
            self.add_node(f"node-{i}", zone=f"zone-{i % 8}")

    def add_node(self, name: str, cpu: str = "8", mem: str = "32Gi",
                 zone: str = "zone-0") -> HollowKubelet:
        """kubeadm join: register a kubelet + per-node proxy. With TLS on,
        the node's client identity is MINTED through the CSR flow first
        (kubelet bootstrap: CSR → auto-approve → CA-signed cert), not
        pre-shared."""
        from ..testing.wrappers import make_node

        if self.tls:
            self.join_certificate(name)
        kubelet = HollowKubelet(self.store, make_node(name, cpu=cpu, mem=mem,
                                                      zone=zone),
                                clock=self.clock)
        kubelet.register()
        self.kubelets.append(kubelet)
        self.proxiers.append(Proxier(self.store, node_name=name))
        return kubelet

    def join_certificate(self, node_name: str) -> tuple[str, str]:
        """The kubelet TLS-bootstrap half of kubeadm join
        (pkg/kubelet/certificate/bootstrap): generate a key + CSR with the
        node identity (CN=system:node:<name>, O=system:nodes), submit a
        CertificateSigningRequest, drive the approval + signing
        controllers, and return (key_path, signed cert PEM) chained to the
        cluster CA."""
        from ..api.certificates import CertificateSigningRequest, CSRSpec
        from ..api.meta import ObjectMeta
        from ..apiserver.certs import new_key_and_csr

        from ..store.store import NotFoundError

        assert self.controller_manager is not None
        key_path, csr_pem = new_key_and_csr(
            f"system:node:{node_name}", org="system:nodes")
        csr_name = f"node-csr-{node_name}"
        # a re-join replaces any prior CSR: the fresh key needs its OWN
        # signature — returning a cert minted for an older key would hand
        # the node a mismatched key/cert pair
        try:
            self.store.delete("CertificateSigningRequest", csr_name)
        except NotFoundError:
            pass
        self.store.create(CertificateSigningRequest(
            meta=ObjectMeta(name=csr_name, namespace=""),
            spec=CSRSpec(request=csr_pem,
                         username=f"system:node:{node_name}"),
        ))
        # drive approver + signer and WAIT for the certificate: in threaded
        # mode a worker may hold the CSR key mid-reconcile while our
        # sync_once sees an empty queue — polling covers both modes
        import os
        import shutil
        import time as _t

        cert = ""
        deadline = _t.monotonic() + 10.0
        while _t.monotonic() < deadline:
            self.controller_manager.sync_once()
            csr = self.store.get("CertificateSigningRequest", csr_name)
            cert = csr.status.get("certificate", "")
            if cert:
                break
            _t.sleep(0.02)
        if not cert:
            raise RuntimeError(
                f"CSR {csr_name} was not signed: {csr.status}")
        old = self.node_credentials.get(node_name)
        if old is not None:
            # a re-join replaces the key: the superseded key material must
            # not linger on disk
            shutil.rmtree(os.path.dirname(old[0]), ignore_errors=True)
        self.node_credentials[node_name] = (key_path, cert)
        return key_path, cert

    # -- convergence ---------------------------------------------------------

    def converge(self, rounds: int = 10) -> None:
        """Deterministic single-threaded convergence (tests): controllers →
        scheduler → kubelets → proxies until a fixed point."""
        assert self.scheduler is not None and self.controller_manager is not None
        for _ in range(rounds):
            n = self.controller_manager.sync_once()
            n += self.scheduler.schedule_pending()
            for k in self.kubelets:
                n += k.sync_once()
            if n == 0:
                break
        for p in self.proxiers:
            p.sync()

    def run(self) -> None:
        """Threaded mode: every component loops until shutdown()."""
        assert self.controller_manager is not None
        self.controller_manager.run(self._stop)
        for k in self.kubelets:
            self._threads.append(k.run(self._stop))

        def sched_loop():
            while not self._stop.is_set():
                if self.scheduler.schedule_pending() == 0:
                    self._stop.wait(0.01)

        def proxy_loop():
            while not self._stop.is_set():
                for p in self.proxiers:
                    p.sync()
                self._stop.wait(0.05)

        for fn in (sched_loop, proxy_loop):
            t = threading.Thread(target=fn, daemon=True)
            t.start()
            self._threads.append(t)

    # -- client access -------------------------------------------------------

    def kubeconfig(self) -> dict:
        assert self.apiserver is not None
        cfg = {
            "server": self.apiserver.url,
            "token": self.admin_token,
        }
        if self.ca_cert:
            cfg["certificate-authority"] = self.ca_cert
        return cfg

    def client(self):
        from ..client.rest import RESTStore

        cfg = self.kubeconfig()
        return RESTStore(cfg["server"], token=cfg["token"],
                         ca_cert=cfg.get("certificate-authority"))

    def shutdown(self) -> None:
        import os
        import shutil

        self._stop.set()
        for t in self._threads:
            t.join(timeout=2)
        if self.apiserver is not None:
            self.apiserver.shutdown()
        # private-key material minted by the CSR join flow must not
        # outlive the cluster (each join created one temp dir)
        for key_path, _cert in self.node_credentials.values():
            shutil.rmtree(os.path.dirname(key_path), ignore_errors=True)
        self.node_credentials.clear()


def main(argv: list[str] | None = None) -> int:
    import argparse
    import json

    parser = argparse.ArgumentParser(description="cluster bootstrap (kubeadm init)")
    parser.add_argument("--nodes", type=int, default=3)
    parser.add_argument("--secure", action="store_true")
    parser.add_argument("--tls", action="store_true",
                        help="serve HTTPS with a generated self-signed cert")
    parser.add_argument("--port", type=int, default=6443)
    args = parser.parse_args(argv)
    boot = ClusterBootstrap(nodes=args.nodes, secure=args.secure,
                            tls=args.tls)
    cfg = boot.init(serve_port=args.port)
    boot.run()
    print(json.dumps(cfg))
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        boot.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
