"""kube-proxy binary equivalent: per-node proxy server.

Reference: cmd/kube-proxy (Options → ProxyServer → Proxier.SyncLoop) — the
server wires a Proxier to the API store, runs the periodic sync loop, and
serves /healthz (reporting whether the last sync is recent, the reference's
healthcheck server semantics, pkg/proxy/healthcheck/) and /rules (debug dump
of the programmed dataplane — the analogue of `iptables-save` output).
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..proxy import Proxier
from ..store.store import Store


class ProxyServer:
    def __init__(self, store: Store, node_name: str = "",
                 sync_period_s: float = 1.0):
        self.proxier = Proxier(store, node_name=node_name)
        self.sync_period_s = sync_period_s
        self.last_sync: float | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._http: ThreadingHTTPServer | None = None

    # -- serving -------------------------------------------------------------

    def _build_handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def _send(self, code: int, body: str, ctype="text/plain"):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/healthz":
                    last = server.last_sync
                    healthy = (last is not None and
                               time.monotonic() - last < 2 * server.sync_period_s + 5)
                    self._send(200 if healthy else 503,
                               "ok" if healthy else "stale")
                elif self.path == "/rules":
                    rules = server.proxier.dataplane.rules()
                    dump = {
                        f"{vip}:{port}/{proto}": {
                            "service": r.service,
                            "backends": [f"{b.address}:{b.port}" for b in r.backends],
                            "sessionAffinity": r.session_affinity,
                        }
                        for (vip, port, proto), r in sorted(rules.items())
                    }
                    self._send(200, json.dumps(dump, indent=1), "application/json")
                else:
                    self._send(404, "not found")

            def log_message(self, *a):
                pass

        return Handler

    def serve(self, port: int = 0) -> int:
        self._http = ThreadingHTTPServer(("127.0.0.1", port), self._build_handler())
        threading.Thread(target=self._http.serve_forever, daemon=True).start()
        return self._http.server_address[1]

    # -- sync loop (Proxier.SyncLoop) ----------------------------------------

    def sync_once(self) -> int:
        n = self.proxier.sync()
        self.last_sync = time.monotonic()
        return n

    def run(self, block: bool = False) -> None:
        def loop():
            while not self._stop.is_set():
                self.sync_once()
                self._stop.wait(self.sync_period_s)

        if block:
            loop()
        else:
            self._thread = threading.Thread(target=loop, daemon=True)
            self._thread.start()

    def shutdown(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self._http is not None:
            self._http.shutdown()


def main(argv: list[str] | None = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(description="node service proxy")
    parser.add_argument("--node", default="")
    parser.add_argument("--server", required=True, help="API server URL")
    parser.add_argument("--token", default="", help="bearer token")
    parser.add_argument("--cacert", default=None,
                        help="CA bundle for an https:// server")
    parser.add_argument("--port", type=int, default=10256)
    parser.add_argument("--sync-period", type=float, default=1.0)
    args = parser.parse_args(argv)
    from ..client.rest import RESTStore

    store = RESTStore(args.server, token=args.token,
                      ca_cert=getattr(args, 'cacert', None))
    server = ProxyServer(store, node_name=args.node,
                         sync_period_s=args.sync_period)
    server.serve(args.port)
    server.run(block=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
