"""kubelet binary: a node agent joined to an API server over REST.

Reference: cmd/kubelet — flags → KubeletServer → RunKubelet; the agent
registers its Node, heartbeats a Lease, and drives the sync loop against
the cluster through client-go (here RESTStore). Serves /healthz (the
kubelet's 10248 endpoint) reporting sync-loop liveness.
"""

from __future__ import annotations

import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..kubelet import Kubelet, Threshold
from ..kubelet.eviction import MEMORY_AVAILABLE


class KubeletServer:
    def __init__(self, store, node, sync_period_s: float = 0.5,
                 eviction_thresholds: list[Threshold] | None = None):
        self.kubelet = Kubelet(store, node,
                               eviction_thresholds=eviction_thresholds or [])
        self.sync_period_s = sync_period_s
        self.last_sync: float | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._http: ThreadingHTTPServer | None = None

    def _build_handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path.startswith("/containerLogs/"):
                    # kubelet API: /containerLogs/<ns>/<pod>/<container>
                    # (?tailLines=N) — the apiserver's pods/log proxy target
                    from urllib.parse import parse_qs, urlparse

                    u = urlparse(self.path)
                    parts = u.path.split("/")[2:]
                    if len(parts) != 3:
                        self.send_response(404); self.end_headers(); return
                    ns, pod, container = parts
                    q = parse_qs(u.query)
                    tail = q.get("tailLines", [None])[0]
                    try:
                        tail_n = int(tail) if tail else None
                    except ValueError:
                        body = f"invalid tailLines {tail!r}".encode()
                        tail_n, code = None, 400
                    else:
                        code = 200
                    if code == 200:
                        try:
                            body = server.kubelet.container_logs(
                                f"{ns}/{pod}", container, tail_lines=tail_n,
                            ).encode()
                        except KeyError as e:
                            body = str(e).encode()
                            code = 404
                    self.send_response(code)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path == "/healthz":
                    last = server.last_sync
                    healthy = (last is not None and time.monotonic() - last
                               < 4 * server.sync_period_s + 10)
                    body = b"ok" if healthy else b"stale"
                    self.send_response(200 if healthy else 503)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_response(404)
                    self.end_headers()

            def log_message(self, *a):
                pass

        return Handler

    def serve(self, port: int = 0) -> int:
        self._http = ThreadingHTTPServer(("127.0.0.1", port),
                                         self._build_handler())
        threading.Thread(target=self._http.serve_forever, daemon=True).start()
        bound = self._http.server_address[1]
        # publish the endpoint (node.status.daemonEndpoints) so the
        # apiserver's log proxy can dial this kubelet
        self.kubelet.node.status.daemon_endpoint_port = bound
        return bound

    def run(self, block: bool = False) -> None:
        self.kubelet.register()

        def loop():
            while not self._stop.is_set():
                self.kubelet.sync_loop_iteration()
                self.last_sync = time.monotonic()
                self._stop.wait(self.sync_period_s)

        if block:
            loop()
        else:
            self._thread = threading.Thread(target=loop, daemon=True)
            self._thread.start()

    def shutdown(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.kubelet.shutdown()
        if self._http is not None:
            self._http.shutdown()


def main(argv: list[str] | None = None) -> int:
    import argparse

    from ..client.rest import RESTStore
    from ..testing.wrappers import make_node

    parser = argparse.ArgumentParser(description="node agent")
    parser.add_argument("--server", required=True, help="API server URL")
    parser.add_argument("--token", default="")
    parser.add_argument("--cacert", default=None,
                        help="CA bundle for an https:// server")
    parser.add_argument("--node-name", required=True)
    parser.add_argument("--cpu", default="8")
    parser.add_argument("--memory", default="32Gi")
    parser.add_argument("--zone", default="zone-0")
    parser.add_argument("--port", type=int, default=10248)
    parser.add_argument("--sync-period", type=float, default=0.5)
    parser.add_argument("--eviction-memory-min-bytes", type=int, default=0)
    args = parser.parse_args(argv)
    store = RESTStore(args.server, token=args.token,
                      ca_cert=getattr(args, 'cacert', None))
    node = make_node(args.node_name, cpu=args.cpu, mem=args.memory,
                     zone=args.zone)
    thresholds = []
    if args.eviction_memory_min_bytes:
        thresholds.append(Threshold(MEMORY_AVAILABLE,
                                    args.eviction_memory_min_bytes))
    server = KubeletServer(store, node, sync_period_s=args.sync_period,
                           eviction_thresholds=thresholds)
    server.serve(args.port)
    server.run(block=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
