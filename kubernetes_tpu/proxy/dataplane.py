"""In-memory dataplane: the programmed ruleset a node's proxy would install.

The reference programs kernel dataplanes (iptables chains, ipvs virtual
servers, nftables maps — /root/reference/pkg/proxy/iptables/proxier.go etc.);
the capability being modeled is "given a packet to VIP:port, pick a backend".
This table is that capability as a data structure: `program()` swaps in a
full ruleset atomically (the reference's iptables-restore semantics: rules
are rebuilt and applied as one transaction), `resolve()` is the DNAT hook.

Session affinity reproduces the ClientIP mode (recent-destination map with a
timeout, like the kernel's `recent` match); load balancing is round-robin
per rule (ipvs rr semantics; iptables uses random statistic match — a
deterministic rr is test-friendlier and distributionally equivalent).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass


@dataclass(frozen=True)
class Backend:
    """One DNAT target."""

    address: str
    port: int
    node_name: str = ""


@dataclass
class Rule:
    """All backends programmed for one (vip, port, protocol) key."""

    service: str  # namespace/name:portname — provenance for debugging
    backends: tuple[Backend, ...]
    session_affinity: bool = False
    affinity_timeout_s: int = 10800


class DataplaneTable:
    """Atomic-swap rule table with per-rule round-robin + ClientIP affinity."""

    def __init__(self, clock=time.monotonic):
        self._lock = threading.Lock()
        self._rules: dict[tuple[str, int, str], Rule] = {}
        self._rr: dict[tuple[str, int, str], int] = {}
        # (rule key, client ip) → (backend, stamp)
        self._affinity: dict[tuple, tuple[Backend, float]] = {}
        self._clock = clock
        self._last_sweep = 0.0
        self.generation = 0

    def program(self, rules: dict[tuple[str, int, str], Rule]) -> None:
        """Swap in a complete ruleset (one transaction, like
        iptables-restore). Affinity entries for vanished rules or backends
        are dropped; round-robin cursors for unchanged rules persist."""
        with self._lock:
            self._rules = dict(rules)
            self._rr = {k: self._rr.get(k, 0) for k in rules}
            now = self._clock()
            keep = {}
            for (key, client), (backend, stamp) in self._affinity.items():
                rule = rules.get(key)
                if (rule is not None and backend in rule.backends
                        and now - stamp <= rule.affinity_timeout_s):
                    keep[(key, client)] = (backend, stamp)
            self._affinity = keep
            self.generation += 1

    def rules(self) -> dict[tuple[str, int, str], Rule]:
        with self._lock:
            return dict(self._rules)

    def resolve(self, vip: str, port: int, protocol: str = "TCP",
                client_ip: str = "") -> Backend | None:
        """The DNAT decision for one connection; None = no rule / no
        backends (the reference REJECTs such packets)."""
        key = (vip, port, protocol)
        with self._lock:
            rule = self._rules.get(key)
            if rule is None or not rule.backends:
                return None
            now = self._clock()
            if now - self._last_sweep > 60.0:
                # periodic sweep: one-shot clients of a stable ruleset
                # would otherwise grow the map forever (program() reaps,
                # but the no-change sync fast path never calls it)
                self._last_sweep = now
                self._affinity = {
                    k: (b, stamp) for k, (b, stamp) in self._affinity.items()
                    if (r := self._rules.get(k[0])) is not None
                    and now - stamp <= r.affinity_timeout_s
                }
            if rule.session_affinity and client_ip:
                hit = self._affinity.get((key, client_ip))
                if hit is not None:
                    backend, stamp = hit
                    if now - stamp <= rule.affinity_timeout_s:
                        self._affinity[(key, client_ip)] = (backend, now)
                        return backend
                    # expired: reap (the kernel's `recent` match reaps on
                    # timeout; without this, one-shot clients leak entries)
                    del self._affinity[(key, client_ip)]
            i = self._rr.get(key, 0) % len(rule.backends)
            self._rr[key] = i + 1
            backend = rule.backends[i]
            if rule.session_affinity and client_ip:
                self._affinity[(key, client_ip)] = (backend, now)
            return backend
