"""Service proxy layer: the pkg/proxy equivalent.

Watches Services and EndpointSlices and programs an in-memory dataplane
table — the analogue of the reference's iptables/ipvs/nftables rule
programming (/root/reference/pkg/proxy/). The dataplane here is a lookup
structure (`DataplaneTable`) instead of kernel rules: virtual-IP:port →
backend endpoints, with session affinity and traffic-policy filtering, so
tests and the hollow kubelet can resolve service VIPs the way a node's
dataplane would.
"""

from .dataplane import DataplaneTable, Rule
from .proxier import (
    EndpointsChangeTracker,
    Proxier,
    ServiceChangeTracker,
    ServicePortName,
)

__all__ = [
    "DataplaneTable", "Rule", "Proxier",
    "ServiceChangeTracker", "EndpointsChangeTracker", "ServicePortName",
]
