"""Proxier: Services + EndpointSlices → dataplane rules.

The pkg/proxy control loop re-expressed: informer events land in change
trackers (pending deltas between syncs — servicechangetracker.go:33,
endpointschangetracker.go:33), a sync pass folds pending changes into the
applied maps (ServicePortMap.Update, EndpointsMap.Update) and rebuilds the
dataplane ruleset as one transaction (the iptables-restore model of
iptables/proxier.go syncProxyRules). Endpoint selection per service port
follows topology.go CategorizeEndpoints: ready endpoints, falling back to
serving-terminating ones; internal/externalTrafficPolicy=Local narrows to
this node's endpoints.

Unlike the reference there is no kernel below — the programmed artifact is
an in-memory DataplaneTable (dataplane.py) shared with whoever wants VIP
resolution (tests, hollow kubelet).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..api.workloads import EndpointSlice, Service
from ..client.informer import InformerFactory
from .dataplane import Backend, DataplaneTable, Rule


@dataclass(frozen=True)
class ServicePortName:
    """Unique id of one load-balanced port (proxy/types.go:44)."""

    namespace: str
    name: str
    port: str
    protocol: str = "TCP"

    def __str__(self) -> str:
        p = f":{self.port}" if self.port else ""
        return f"{self.namespace}/{self.name}{p}"


class ServiceChangeTracker:
    """Pending service changes since the last sync
    (servicechangetracker.go:76 Update semantics: track (previous, current)
    per key, collapse no-op pairs)."""

    def __init__(self):
        self._pending: dict[str, tuple[Service | None, Service | None]] = {}

    def update(self, previous: Service | None, current: Service | None) -> bool:
        obj = current if current is not None else previous
        if obj is None:
            return False
        key = obj.meta.key
        if key in self._pending:
            first, _ = self._pending[key]
            self._pending[key] = (first, current)
            if first is current:  # add then delete of the same object
                del self._pending[key]
        else:
            self._pending[key] = (previous, current)
        return True

    def drain(self) -> dict[str, tuple[Service | None, Service | None]]:
        pending, self._pending = self._pending, {}
        return pending


class EndpointsChangeTracker:
    """Pending slice changes keyed by owning service
    (endpointschangetracker.go:81 EndpointSliceUpdate): remembers which
    services need their endpoint sets rebuilt."""

    def __init__(self):
        # service key → {slice key: slice}
        self._by_service: dict[str, dict[str, EndpointSlice]] = {}
        self._touched: set[str] = set()  # service keys

    def update(self, slice_: EndpointSlice, removed: bool = False) -> bool:
        if not slice_.service_name:
            return False
        svc_key = f"{slice_.meta.namespace}/{slice_.service_name}"
        bucket = self._by_service.setdefault(svc_key, {})
        if removed:
            bucket.pop(slice_.meta.key, None)
            if not bucket:
                del self._by_service[svc_key]
        else:
            bucket[slice_.meta.key] = slice_
        self._touched.add(svc_key)
        return True

    def drain(self) -> set[str]:
        touched, self._touched = self._touched, set()
        return touched

    def slices_for(self, service_key: str) -> list[EndpointSlice]:
        return list(self._by_service.get(service_key, {}).values())


class Proxier:
    """One node's proxy: trackers + applied maps + dataplane programming."""

    def __init__(self, store, node_name: str = "",
                 informers: InformerFactory | None = None,
                 dataplane: DataplaneTable | None = None):
        self.store = store
        self.node_name = node_name  # "" = policy-Local matches nothing
        self.dataplane = dataplane or DataplaneTable()
        self.service_changes = ServiceChangeTracker()
        self.endpoint_changes = EndpointsChangeTracker()
        self._services: dict[str, Service] = {}  # applied ServicePortMap src
        self.informers = informers or InformerFactory(store)
        self.informers.informer("Service").add_handler(self._on_service)
        self.informers.informer("EndpointSlice").add_handler(self._on_slice)
        self._started = False
        self.syncs = 0

    # -- informer handlers (pkg/proxy/config handlers) -----------------------

    def _on_service(self, etype, old, new) -> None:
        from ..store.store import DELETED

        if etype == DELETED:
            self.service_changes.update(new if new is not None else old, None)
        else:
            self.service_changes.update(old, new)

    def _on_slice(self, etype, old, new) -> None:
        from ..store.store import DELETED

        obj = new if new is not None else old
        self.endpoint_changes.update(obj, removed=(etype == DELETED))

    # -- sync (syncProxyRules) ----------------------------------------------

    def start(self) -> None:
        if not self._started:
            self.informers.start_all()
            self._started = True

    def sync(self) -> int:
        """Pump informers, fold pending changes, reprogram the dataplane.
        Returns the number of programmed rules. Cheap when nothing changed
        (the reference's partial-sync fast path)."""
        self.start()
        self.informers.pump_all()
        svc_pending = self.service_changes.drain()
        ep_touched = self.endpoint_changes.drain()
        if not svc_pending and not ep_touched and self.syncs:
            return len(self.dataplane.rules())
        for key, (_prev, cur) in svc_pending.items():
            if cur is None:
                self._services.pop(key, None)
            else:
                self._services[key] = cur
        rules: dict[tuple[str, int, str], Rule] = {}
        for key, svc in self._services.items():
            self._rules_for(key, svc, rules)
        self.dataplane.program(rules)
        self.syncs += 1
        return len(rules)

    def _rules_for(self, key: str, svc: Service,
                   rules: dict[tuple[str, int, str], Rule]) -> None:
        if not svc.spec.cluster_ip and svc.spec.type == "ClusterIP":
            return  # headless
        slices = self.endpoint_changes.slices_for(key)
        affinity = svc.spec.session_affinity == "ClientIP"
        for sp in svc.spec.ports:
            spn = ServicePortName(svc.meta.namespace, svc.meta.name,
                                  sp.name, sp.protocol)
            target = sp.target_port or sp.port
            cluster_eps = self._select(slices, target, local_only=False)
            if svc.spec.internal_traffic_policy == "Local":
                internal_eps = self._select(slices, target, local_only=True)
            else:
                internal_eps = cluster_eps
            if svc.spec.cluster_ip:
                rules[(svc.spec.cluster_ip, sp.port, sp.protocol)] = Rule(
                    service=str(spn), backends=internal_eps,
                    session_affinity=affinity,
                    affinity_timeout_s=svc.spec.session_affinity_timeout_s,
                )
            if svc.spec.type in ("NodePort", "LoadBalancer") and sp.node_port:
                if svc.spec.external_traffic_policy == "Local":
                    external_eps = self._select(slices, target, local_only=True)
                else:
                    external_eps = cluster_eps
                # node-port rule: any node address; modeled as vip="*"
                rules[("*", sp.node_port, sp.protocol)] = Rule(
                    service=str(spn), backends=external_eps,
                    session_affinity=affinity,
                    affinity_timeout_s=svc.spec.session_affinity_timeout_s,
                )

    def _select(self, slices, target_port: int,
                local_only: bool) -> tuple[Backend, ...]:
        """topology.go CategorizeEndpoints: ready endpoints first; when a
        service has none, fall back to serving-terminating endpoints so
        rolling restarts don't blackhole traffic."""
        ready: list[Backend] = []
        serving: list[Backend] = []
        for s in slices:
            for ep in s.endpoints:
                if local_only and ep.node_name != self.node_name:
                    continue
                for addr in ep.addresses:
                    b = Backend(addr, target_port, ep.node_name)
                    if ep.ready:
                        ready.append(b)
                    elif ep.serving and ep.terminating:
                        serving.append(b)
        chosen = ready if ready else serving
        # deterministic order: iptables rules are ordered by insertion; we
        # sort for reproducibility across informer orderings
        return tuple(sorted(chosen, key=lambda b: (b.address, b.port)))
