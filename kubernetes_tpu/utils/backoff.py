"""Shared bounded-retry helper: exponential backoff with full jitter.

This module is THE sanctioned retry loop (kubesched-lint rule RET01 flags
hand-rolled sleep-in-except retry loops everywhere else): one policy
object describing what is retryable and how long to wait, one `retry_call`
that runs a callable under it. The jitter follows the "full jitter"
scheme (delay drawn uniformly from [0, min(cap, base * 2^attempt)]) —
the AWS-architecture-blog result that decorrelated sleeps empty a
contended queue in near-minimal time, and the shape client-go's
wait.Backoff{Jitter: 1.0} approximates.

The rng is the CALLER's (seeded): retries are host-side control flow and
never touch the scheduler's tie-break stream, but a seeded jitter source
keeps chaos-soak timing reproducible run to run.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Callable


@dataclass
class RetryPolicy:
    """How many attempts, how long between them, and what qualifies."""

    max_attempts: int = 4
    base_s: float = 0.002
    cap_s: float = 0.1
    # exception classes that merit another attempt; anything else (and the
    # last attempt's failure) propagates to the caller unchanged
    retryable: tuple = field(default_factory=tuple)

    def is_retryable(self, err: Exception) -> bool:
        if isinstance(err, self.retryable):
            return True
        # duck-typed escape hatch: injected faults and facade errors mark
        # themselves rather than importing every consumer's exception types
        return bool(getattr(err, "transient", False))

    def delay_s(self, attempt: int, rng: random.Random) -> float:
        """Full-jitter delay before retry number `attempt` (1-based)."""
        ceiling = min(self.cap_s, self.base_s * (2 ** (attempt - 1)))
        return rng.uniform(0.0, ceiling)


def retry_call(
    fn: Callable[[], object],
    policy: RetryPolicy,
    rng: random.Random,
    *,
    sleep: Callable[[float], None] = time.sleep,
    should_abort: Callable[[], bool] | None = None,
    on_backoff: Callable[[int, float], None] | None = None,
):
    """Run `fn`, retrying retryable failures up to policy.max_attempts.

    `on_backoff(attempt, delay_s)` fires before each sleep (metrics hook);
    `should_abort` short-circuits remaining attempts (dispatcher shutdown)
    by re-raising the last error immediately.
    """
    attempt = 1
    while True:
        try:
            return fn()
        except Exception as err:  # noqa: BLE001 - classified right below
            if (
                attempt >= policy.max_attempts
                or not policy.is_retryable(err)
                or (should_abort is not None and should_abort())
            ):
                raise
            delay = policy.delay_s(attempt, rng)
            if on_backoff is not None:
                on_backoff(attempt, delay)
            sleep(delay)
            attempt += 1
