"""Sampling CPU profiler: the /debug/pprof role.

Reference: every component serves net/http/pprof when profiling is enabled
(routes.Profiling{}.Install, cmd/kube-scheduler/app/server.go:390), and the
perf workflow is "hit /debug/pprof/profile?seconds=N, look at the hot
stacks". Go's CPU profile is a sampling profiler; this is the same idea on
sys._current_frames(): sample every thread's stack at `hz` for `seconds`,
aggregate self/cumulative hits per function, render the familiar
flat-profile table. Pure stdlib, safe to run in production (sampling cost
only while a profile is being taken).
"""

from __future__ import annotations

import sys
import threading
import time
from collections import Counter


def take_profile(seconds: float = 1.0, hz: int = 100,
                 top: int = 30) -> str:
    """Sample all threads for `seconds`; returns a flat-profile text table
    (samples ~ CPU+wait time per frame, like a wall-clock pprof)."""
    interval = 1.0 / hz
    self_hits: Counter[str] = Counter()
    cum_hits: Counter[str] = Counter()
    ticks = 0  # percentages normalize per TICK: "this frame was on-CPU in
    # X% of sampling instants" — not per thread-sample, which would dilute
    # a hot thread by however many idle threads exist
    me = threading.get_ident()
    deadline = time.monotonic() + seconds
    while time.monotonic() < deadline:
        ticks += 1
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            first = True
            seen: set[str] = set()
            while frame is not None:
                code = frame.f_code
                # co_qualname is 3.11+; co_name loses the class prefix only
                qn = getattr(code, "co_qualname", code.co_name)
                loc = f"{qn} ({code.co_filename.rsplit('/', 1)[-1]}:{frame.f_lineno})"
                if first:
                    self_hits[loc] += 1
                    first = False
                if loc not in seen:  # recursion: one cum hit per sample
                    seen.add(loc)
                    cum_hits[loc] += 1
                frame = frame.f_back
        time.sleep(interval)
    lines = [
        f"sampling profile: {ticks} ticks over {seconds}s at {hz}Hz",
        f"{'self':>6} {'self%':>7} {'cum':>6} {'cum%':>7}  location",
    ]
    total = max(ticks, 1)
    # every sampled frame gets a cum hit, so cum_hits is the full row set;
    # callers with 0 self time (all samples in callees) still rank by cum —
    # dropping them would hide the hot call path's entry points
    entries = sorted(
        ((self_hits.get(loc, 0), cum_hits[loc], loc) for loc in cum_hits),
        key=lambda e: (-e[0], -e[1], e[2]),
    )[:top]
    for n, c, loc in entries:
        lines.append(
            f"{n:>6} {100 * n / total:>6.1f}% {c:>6} {100 * c / total:>6.1f}%  {loc}"
        )
    return "\n".join(lines) + "\n"
