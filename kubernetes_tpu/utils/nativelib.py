"""Shared loader for the native/ C++ cores.

One locked build-and-load path for every native library (store engine,
CBOR transcoder): builds via `make -C native` on first use, caches the
CDLL, and returns None when the toolchain is unavailable so callers fall
back to their pure-Python implementations.
"""

from __future__ import annotations

import ctypes
import subprocess
import threading
from pathlib import Path

_NATIVE_DIR = Path(__file__).resolve().parents[2] / "native"
_cache: dict[str, ctypes.CDLL | None] = {}
_lock = threading.Lock()


def load_native(lib_name: str) -> ctypes.CDLL | None:
    """Load native/<lib_name> (building if missing); None = unavailable.
    Thread-safe: concurrent first calls serialize on the build."""
    with _lock:
        if lib_name in _cache:
            return _cache[lib_name]
        lib = None
        try:
            path = _NATIVE_DIR / lib_name
            if not path.exists():
                # build ONLY the requested target: one broken .cpp must not
                # take down the other native cores
                subprocess.run(["make", "-C", str(_NATIVE_DIR), lib_name],
                               check=True, capture_output=True)
            lib = ctypes.CDLL(str(path))
        except (OSError, subprocess.CalledProcessError):
            lib = None
        _cache[lib_name] = lib
        return lib
