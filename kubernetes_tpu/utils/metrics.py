"""Minimal Prometheus-style metrics library.

Reference: staging/src/k8s.io/component-base/metrics — Counter/Gauge/Histogram
vectors with stability levels, a shared registry, and text exposition. The
reference wraps prometheus/client_golang; this is a self-contained equivalent
with the same call-shape (WithLabelValues().Inc()/Observe()) flattened to
Python (inc(*labels) / observe(value, *labels)).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

ALPHA = "ALPHA"
STABLE = "STABLE"

# scheduler histogram defaults mirror prometheus.ExponentialBuckets(0.001,2,15)
DEF_BUCKETS = tuple(0.001 * 2**i for i in range(15))


class _Metric:
    def __init__(self, name: str, help: str, label_names: tuple[str, ...] = (),
                 stability: str = ALPHA):
        self.name = name
        self.help = help
        self.label_names = label_names
        self.stability = stability
        self._lock = threading.Lock()

    def _key(self, labels: tuple[str, ...]) -> tuple[str, ...]:
        if len(labels) != len(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, got {labels}"
            )
        return labels


class Counter(_Metric):
    type = "counter"

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.values: dict[tuple[str, ...], float] = {}

    def inc(self, *labels: str, by: float = 1.0) -> None:
        key = self._key(labels)
        with self._lock:
            self.values[key] = self.values.get(key, 0.0) + by

    def get(self, *labels: str) -> float:
        return self.values.get(labels, 0.0)


class Gauge(_Metric):
    type = "gauge"

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.values: dict[tuple[str, ...], float] = {}

    def set(self, value: float, *labels: str) -> None:
        with self._lock:
            self.values[self._key(labels)] = value

    def inc(self, *labels: str, by: float = 1.0) -> None:
        key = self._key(labels)
        with self._lock:
            self.values[key] = self.values.get(key, 0.0) + by

    def dec(self, *labels: str) -> None:
        self.inc(*labels, by=-1.0)

    def get(self, *labels: str) -> float:
        return self.values.get(labels, 0.0)


@dataclass
class _HistState:
    buckets: list[int]
    total: float = 0.0
    count: int = 0


class Histogram(_Metric):
    type = "histogram"

    def __init__(self, name, help, label_names=(), buckets=DEF_BUCKETS,
                 stability=ALPHA):
        super().__init__(name, help, label_names, stability)
        self.bounds = tuple(buckets)
        self.values: dict[tuple[str, ...], _HistState] = {}

    def observe(self, value: float, *labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            st = self.values.get(key)
            if st is None:
                st = self.values[key] = _HistState([0] * len(self.bounds))
            for i, b in enumerate(self.bounds):
                if value <= b:
                    st.buckets[i] += 1
            st.total += value
            st.count += 1

    def percentile(self, q: float, *labels: str) -> float:
        """Linear-interpolated estimate from bucket counts (for tests and the
        perf harness; the reference computes these in scheduler_perf/util.go)."""
        st = self.values.get(labels)
        if st is None or st.count == 0:
            return 0.0
        rank = q * st.count
        cum = 0
        for i, b in enumerate(self.bounds):
            prev_cum = cum
            cum = st.buckets[i]
            if cum >= rank:
                lo = self.bounds[i - 1] if i else 0.0
                span = cum - prev_cum
                frac = (rank - prev_cum) / span if span else 1.0
                return lo + (b - lo) * frac
        return self.bounds[-1]

    def average(self, *labels: str) -> float:
        st = self.values.get(labels)
        return st.total / st.count if st and st.count else 0.0

    def count(self, *labels: str) -> int:
        st = self.values.get(labels)
        return st.count if st else 0


class Registry:
    """component-base/metrics/legacyregistry equivalent."""

    def __init__(self) -> None:
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def register(self, metric: _Metric) -> _Metric:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                return existing
            self._metrics[metric.name] = metric
            return metric

    def counter(self, name, help="", labels=(), stability=ALPHA) -> Counter:
        return self.register(Counter(name, help, tuple(labels), stability))  # type: ignore[return-value]

    def gauge(self, name, help="", labels=(), stability=ALPHA) -> Gauge:
        return self.register(Gauge(name, help, tuple(labels), stability))  # type: ignore[return-value]

    def histogram(self, name, help="", labels=(), buckets=DEF_BUCKETS,
                  stability=ALPHA) -> Histogram:
        return self.register(Histogram(name, help, tuple(labels), buckets, stability))  # type: ignore[return-value]

    def get(self, name: str) -> _Metric | None:
        return self._metrics.get(name)

    def expose(self) -> str:
        """Prometheus text exposition format (/metrics payload)."""
        lines: list[str] = []

        def fmt_labels(names, values, extra=()):
            pairs = [f'{n}="{v}"' for n, v in zip(names, values)] + list(extra)
            return "{" + ",".join(pairs) + "}" if pairs else ""

        for m in sorted(self._metrics.values(), key=lambda m: m.name):
            lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.type}")
            if isinstance(m, (Counter, Gauge)):
                for labels, v in sorted(m.values.items()):
                    lines.append(f"{m.name}{fmt_labels(m.label_names, labels)} {v}")
            elif isinstance(m, Histogram):
                for labels, st in sorted(m.values.items()):
                    for bound, n in zip(m.bounds, st.buckets):
                        le = 'le="%s"' % bound
                        lines.append(
                            f"{m.name}_bucket"
                            f"{fmt_labels(m.label_names, labels, [le])} {n}"
                        )
                    inf = 'le="+Inf"'
                    lines.append(
                        f"{m.name}_bucket"
                        f"{fmt_labels(m.label_names, labels, [inf])} {st.count}"
                    )
                    lines.append(f"{m.name}_sum{fmt_labels(m.label_names, labels)} {st.total}")
                    lines.append(f"{m.name}_count{fmt_labels(m.label_names, labels)} {st.count}")
        return "\n".join(lines) + "\n"
