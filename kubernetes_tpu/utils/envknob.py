"""Centralized KUBE_TPU_* environment-knob parsing.

Every tunable the scheduler reads from the environment used to be a bare
`int(os.environ.get(...))` / `float(os.environ.get(...))` at module import
time — a malformed value (`KUBE_TPU_RETRY_MAX=three`) raised ValueError
during import and killed the process before any logging was configured.
A bad knob should never be fatal: these helpers log one warning naming the
variable, the rejected value, and the default they fell back to, then
return the default. An unset or empty variable silently yields the default
(empty string is how ops "unset" a knob in some launchers).
"""

from __future__ import annotations

import logging
import os

_log = logging.getLogger("kubernetes_tpu.envknob")


def int_env(name: str, default: int) -> int:
    """Parse env var `name` as int; warn and fall back on malformed input."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return int(raw)
    except ValueError:
        _log.warning("ignoring malformed %s=%r; using default %r",
                     name, raw, default)
        return default


def float_env(name: str, default: float | None) -> float | None:
    """Parse env var `name` as float; warn and fall back on malformed input.

    `default` may be None (e.g. KUBE_TPU_SLOW_WAVE_S, where unset/empty
    means "watchdog off") — unset, empty, and malformed all yield it."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return float(raw)
    except ValueError:
        _log.warning("ignoring malformed %s=%r; using default %r",
                     name, raw, default)
        return default
