"""Span tracing: the component-base/tracing (OpenTelemetry) role.

Reference: staging/src/k8s.io/component-base/tracing/tracing.go wraps OTel
spans; the apiserver emits a span per request (request-filter spans), the
kubelet around syncs. This module provides the same surface — start a
span, annotate attributes/events, nest children — with pluggable
exporters (the OTLP exporter's role): InMemoryExporter for tests and
introspection, or any callable consuming finished spans. Zero overhead
when no exporter is installed (the no-op tracer pattern).

    with tracer.span("HTTP GET /api/v1/Pod", verb="list") as sp:
        ...
        sp.event("cache hit")
"""

from __future__ import annotations

import logging
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field


@dataclass
class Span:
    name: str
    start: float
    end: float = 0.0
    attributes: dict = field(default_factory=dict)
    events: list = field(default_factory=list)  # (offset_s, message)
    children: list = field(default_factory=list)
    parent: "Span | None" = None

    @property
    def duration_s(self) -> float:
        return (self.end or time.perf_counter()) - self.start

    def event(self, message: str, **attrs) -> None:
        self.events.append((time.perf_counter() - self.start, message, attrs))

    def set(self, **attrs) -> None:
        self.attributes.update(attrs)


class Tracer:
    """Per-component tracer; spans nest through a thread-local stack (the
    context propagation OTel does via Context)."""

    def __init__(self, component: str, exporter=None):
        self.component = component
        self.exporter = exporter
        self._local = threading.local()

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    @contextmanager
    def span(self, name: str, **attrs):
        if self.exporter is None:
            # no-op fast path: tracing off costs one attribute lookup
            yield _NOOP_SPAN
            return
        sp = Span(name=name, start=time.perf_counter(), attributes=dict(attrs))
        stack = self._stack()
        if stack:
            sp.parent = stack[-1]
            stack[-1].children.append(sp)
        stack.append(sp)
        try:
            yield sp
        except Exception as e:
            sp.set(error=f"{type(e).__name__}: {e}")
            raise
        finally:
            sp.end = time.perf_counter()
            stack.pop()
            if sp.parent is None:
                self.exporter(sp)  # export ROOT spans (children ride along)


class _NoopSpan:
    def event(self, message: str, **attrs) -> None:
        pass

    def set(self, **attrs) -> None:
        pass


_NOOP_SPAN = _NoopSpan()


def threshold_log_exporter(threshold: float, logger=None):
    """Exporter that logs a finished span's event timeline iff its total
    duration crossed `threshold` — the utiltrace LogIfLong contract
    (vendor/k8s.io/utils/trace/trace.go:208) expressed as a span exporter.
    The legacy utiltrace line format is preserved so existing log scrapers
    keep matching.

    Returns a callable(span) -> bool (whether it logged)."""
    log = logger or logging.getLogger("kubernetes_tpu.trace")

    def export(sp: Span) -> bool:
        total = sp.duration_s
        if total < threshold:
            return False
        fields = ",".join(f"{k}={v}" for k, v in sp.attributes.items())
        lines = [f'Trace "{sp.name}" ({fields}): total {total * 1000:.1f}ms '
                 f'(threshold {threshold * 1000:.0f}ms):']
        prev = 0.0
        for off, msg, _attrs in sp.events:
            lines.append(f"  +{(off - prev) * 1000:.1f}ms {msg}")
            prev = off
        log.warning("\n".join(lines))
        return True

    return export


class InMemoryExporter:
    """Collects finished root spans (the testing exporter; also serves the
    /debug/traces introspection endpoint)."""

    def __init__(self, capacity: int = 256):
        self.capacity = capacity
        self._lock = threading.Lock()
        self.spans: list[Span] = []

    def __call__(self, span: Span) -> None:
        with self._lock:
            self.spans.append(span)
            if len(self.spans) > self.capacity:
                del self.spans[: self.capacity // 2]

    def find(self, name_prefix: str) -> list[Span]:
        with self._lock:
            return [s for s in self.spans if s.name.startswith(name_prefix)]

    def last(self, n: int | None = None) -> list[Span]:
        """The most recent n finished root spans (all when n is None)."""
        with self._lock:
            return list(self.spans[-n:] if n else self.spans)


# -- OTLP-shaped export --------------------------------------------------------


def spans_to_otlp(spans: list[Span], component: str = "kubernetes-tpu") -> dict:
    """Serialize finished root spans (children included) into the OTLP/JSON
    trace shape (resourceSpans → scopeSpans → spans) so the /debug/traces
    payload drops straight into any OTLP-speaking viewer. Span/trace ids
    are synthesized by traversal order — this process never talked to a
    real collector, so there is no propagated context to preserve. Span
    times are exported as epoch nanos via one perf_counter→epoch offset
    captured per export call (spans record perf_counter internally)."""
    # perf_counter and time.time advance in lockstep; one offset converts
    epoch_offset = time.time() - time.perf_counter()

    def _attrs(d: dict) -> list[dict]:
        return [
            {"key": str(k), "value": {"stringValue": str(v)}}
            for k, v in d.items()
        ]

    out_spans: list[dict] = []
    counter = [0]

    def _walk(sp: Span, trace_id: str, parent_id: str) -> None:
        counter[0] += 1
        span_id = f"{counter[0]:016x}"
        start_ns = int((sp.start + epoch_offset) * 1e9)
        end_ns = int(((sp.end or time.perf_counter()) + epoch_offset) * 1e9)
        out_spans.append({
            "traceId": trace_id,
            "spanId": span_id,
            "parentSpanId": parent_id,
            "name": sp.name,
            "kind": 1,  # SPAN_KIND_INTERNAL
            "startTimeUnixNano": str(start_ns),
            "endTimeUnixNano": str(end_ns),
            "attributes": _attrs(sp.attributes),
            "events": [
                {
                    "timeUnixNano": str(int((sp.start + off + epoch_offset) * 1e9)),
                    "name": msg,
                    "attributes": _attrs(attrs),
                }
                for off, msg, attrs in sp.events
            ],
        })
        for child in sp.children:
            _walk(child, trace_id, span_id)

    for i, root in enumerate(spans, start=1):
        _walk(root, f"{i:032x}", "")

    return {
        "resourceSpans": [{
            "resource": {"attributes": _attrs({"service.name": component})},
            "scopeSpans": [{
                "scope": {"name": "kubernetes_tpu.utils.tracing"},
                "spans": out_spans,
            }],
        }],
    }


# -- CLI: dump an exporter-shaped demo / inspect OTLP dumps --------------------


def main(argv: list[str] | None = None) -> int:
    import argparse
    import json

    parser = argparse.ArgumentParser(
        prog="python -m kubernetes_tpu.utils.tracing",
        description="Span tracing introspection",
    )
    parser.add_argument("--dump", action="store_true",
                        help="run a short synthetic trace and print it as "
                             "OTLP JSON (the /debug/traces payload shape)")
    parser.add_argument("--last", type=int, default=None,
                        help="limit the dump to the last N root spans")
    args = parser.parse_args(argv)

    if not args.dump:
        parser.print_usage()
        return 2

    exporter = InMemoryExporter()
    tracer = Tracer("tracing-cli", exporter=exporter)
    with tracer.span("demo/schedule", pods="3") as sp:
        sp.event("queue popped", pods="3")
        with tracer.span("demo/kernel", tier="dedup"):
            pass
        with tracer.span("demo/bind"):
            sp.event("bind dispatched")
    print(json.dumps(spans_to_otlp(exporter.last(args.last),
                                   component="tracing-cli"), indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
