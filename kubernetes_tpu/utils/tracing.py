"""Span tracing: the component-base/tracing (OpenTelemetry) role.

Reference: staging/src/k8s.io/component-base/tracing/tracing.go wraps OTel
spans; the apiserver emits a span per request (request-filter spans), the
kubelet around syncs. This module provides the same surface — start a
span, annotate attributes/events, nest children — with pluggable
exporters (the OTLP exporter's role): InMemoryExporter for tests and
introspection, or any callable consuming finished spans. Zero overhead
when no exporter is installed (the no-op tracer pattern).

    with tracer.span("HTTP GET /api/v1/Pod", verb="list") as sp:
        ...
        sp.event("cache hit")
"""

from __future__ import annotations

import logging
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field


@dataclass
class Span:
    name: str
    start: float
    end: float = 0.0
    attributes: dict = field(default_factory=dict)
    events: list = field(default_factory=list)  # (offset_s, message)
    children: list = field(default_factory=list)
    parent: "Span | None" = None

    @property
    def duration_s(self) -> float:
        return (self.end or time.perf_counter()) - self.start

    def event(self, message: str, **attrs) -> None:
        self.events.append((time.perf_counter() - self.start, message, attrs))

    def set(self, **attrs) -> None:
        self.attributes.update(attrs)


class Tracer:
    """Per-component tracer; spans nest through a thread-local stack (the
    context propagation OTel does via Context)."""

    def __init__(self, component: str, exporter=None):
        self.component = component
        self.exporter = exporter
        self._local = threading.local()

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    @contextmanager
    def span(self, name: str, **attrs):
        if self.exporter is None:
            # no-op fast path: tracing off costs one attribute lookup
            yield _NOOP_SPAN
            return
        sp = Span(name=name, start=time.perf_counter(), attributes=dict(attrs))
        stack = self._stack()
        if stack:
            sp.parent = stack[-1]
            stack[-1].children.append(sp)
        stack.append(sp)
        try:
            yield sp
        except Exception as e:
            sp.set(error=f"{type(e).__name__}: {e}")
            raise
        finally:
            sp.end = time.perf_counter()
            stack.pop()
            if sp.parent is None:
                self.exporter(sp)  # export ROOT spans (children ride along)


class _NoopSpan:
    def event(self, message: str, **attrs) -> None:
        pass

    def set(self, **attrs) -> None:
        pass


_NOOP_SPAN = _NoopSpan()


def threshold_log_exporter(threshold: float, logger=None):
    """Exporter that logs a finished span's event timeline iff its total
    duration crossed `threshold` — the utiltrace LogIfLong contract
    (vendor/k8s.io/utils/trace/trace.go:208) expressed as a span exporter.
    `utils.trace.Trace` is a shim over this; the legacy line format is
    preserved so existing log scrapers keep matching.

    Returns a callable(span) -> bool (whether it logged)."""
    log = logger or logging.getLogger("kubernetes_tpu.trace")

    def export(sp: Span) -> bool:
        total = sp.duration_s
        if total < threshold:
            return False
        fields = ",".join(f"{k}={v}" for k, v in sp.attributes.items())
        lines = [f'Trace "{sp.name}" ({fields}): total {total * 1000:.1f}ms '
                 f'(threshold {threshold * 1000:.0f}ms):']
        prev = 0.0
        for off, msg, _attrs in sp.events:
            lines.append(f"  +{(off - prev) * 1000:.1f}ms {msg}")
            prev = off
        log.warning("\n".join(lines))
        return True

    return export


class InMemoryExporter:
    """Collects finished root spans (the testing exporter; also serves the
    /debug/traces introspection endpoint)."""

    def __init__(self, capacity: int = 256):
        self.capacity = capacity
        self._lock = threading.Lock()
        self.spans: list[Span] = []

    def __call__(self, span: Span) -> None:
        with self._lock:
            self.spans.append(span)
            if len(self.spans) > self.capacity:
                del self.spans[: self.capacity // 2]

    def find(self, name_prefix: str) -> list[Span]:
        with self._lock:
            return [s for s in self.spans if s.name.startswith(name_prefix)]
