"""Hollow-network address assignment.

Real kubelets get pod IPs from CNI; hollow nodes synthesize them. Addresses
must be (a) stable across processes for the same pod uid (the endpointslice
controller and the kubelet must agree without coordination), and (b) outside
the service-VIP range so a pod can't shadow a ClusterIP. We use the upper
half of 10/8 — 10.128.0.0/9, the conventional pod CIDR — keyed by a 23-bit
crc32 of the uid. Collisions are possible (birthday bound ≈ n²/2²⁴) but
merely merge two backends in a slice; VIPs conventionally live in
10.0.0.0/16 and can never collide with this range.
"""

from __future__ import annotations

import zlib


def stable_pod_ip(uid: str) -> str:
    h = zlib.crc32(uid.encode()) & 0x7FFFFF  # 23 bits
    return f"10.{128 + (h >> 16)}.{(h >> 8) & 0xFF}.{h & 0xFF}"
