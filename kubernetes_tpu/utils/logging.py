"""Structured, leveled logging: the klog v2 role.

Reference: the whole control plane logs through klog's structured calls —
logger.Info("Scheduled pod", "pod", klog.KObj(pod), "node", node) — with
verbosity gating V(0)-V(10) and a JSON backend
(component-base/logs/json). This module is that contract on stdlib
logging: key-value pairs always travel as structured fields (never
formatted into the message), V-levels gate cheaply before argument
formatting, and the backend renders text or JSON.

Usage:
    log = get_logger("scheduler")
    log.info("Scheduled pod", pod=pod.meta.key, node=node)
    if log.v(4):
        log.v4("score details", scores=long_list)
"""

from __future__ import annotations

import json
import logging
import sys
import threading
import time

_VERBOSITY = 0
_lock = threading.Lock()


def set_verbosity(v: int) -> None:
    """--v flag (klog verbosity); 0 is the production default."""
    global _VERBOSITY
    _VERBOSITY = v


def verbosity() -> int:
    return _VERBOSITY


class JSONFormatter(logging.Formatter):
    """component-base/logs/json: one object per line, fields flattened."""

    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": round(record.created, 6),
            "v": getattr(record, "v", 0),
            "logger": record.name,
            "level": record.levelname.lower(),
            "msg": record.getMessage(),
        }
        out.update(getattr(record, "kv", {}))
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, default=str)


class TextFormatter(logging.Formatter):
    """klog text: msg followed by key=value pairs."""

    def format(self, record: logging.LogRecord) -> str:
        kv = getattr(record, "kv", {})
        pairs = "".join(f' {k}="{v}"' for k, v in kv.items())
        t = time.strftime("%H:%M:%S", time.localtime(record.created))
        return (f"{record.levelname[0]}{t} {record.name}] "
                f"{record.getMessage()}{pairs}")


def configure(fmt: str = "text", stream=None, verbosity_level: int = 0) -> None:
    """Install the backend on the package root logger (logs.Options.Apply)."""
    set_verbosity(verbosity_level)
    root = logging.getLogger("kubernetes_tpu")
    with _lock:
        for h in list(root.handlers):
            root.removeHandler(h)
        handler = logging.StreamHandler(stream or sys.stderr)
        handler.setFormatter(
            JSONFormatter() if fmt == "json" else TextFormatter()
        )
        root.addHandler(handler)
        root.setLevel(logging.INFO)
        # never double-emit through root-logger handlers (basicConfig,
        # pytest's capture handler, ...) — the backend owns the format
        root.propagate = False


class StructuredLogger:
    def __init__(self, name: str, values: dict | None = None):
        self._log = logging.getLogger(f"kubernetes_tpu.{name}")
        self._values = dict(values or {})  # WithValues context

    def with_values(self, **kv) -> "StructuredLogger":
        """klog LoggerWithValues: context that rides on every line."""
        merged = dict(self._values)
        merged.update(kv)
        out = StructuredLogger.__new__(StructuredLogger)
        out._log = self._log
        out._values = merged
        return out

    def v(self, level: int) -> bool:
        """Cheap verbosity gate: `if log.v(4): ...expensive args...`."""
        return _VERBOSITY >= level

    def _emit(self, lvl: int, msg: str, v: int, kv: dict) -> None:
        if self._values:
            merged = dict(self._values)
            merged.update(kv)
            kv = merged
        self._log.log(lvl, msg, extra={"kv": kv, "v": v})

    def info(self, msg: str, **kv) -> None:
        self._emit(logging.INFO, msg, 0, kv)

    def error(self, msg: str, **kv) -> None:
        self._emit(logging.ERROR, msg, 0, kv)

    def v2(self, msg: str, **kv) -> None:
        if self.v(2):
            self._emit(logging.INFO, msg, 2, kv)

    def v4(self, msg: str, **kv) -> None:
        if self.v(4):
            self._emit(logging.INFO, msg, 4, kv)

    def v10(self, msg: str, **kv) -> None:
        if self.v(10):
            self._emit(logging.INFO, msg, 10, kv)


def get_logger(name: str) -> StructuredLogger:
    return StructuredLogger(name)
