"""Feature-gate registry with versioned specs.

Reference: staging/src/k8s.io/component-base/featuregate/feature_gate.go
(versioned specs, emulation-version aware :353) and the scheduler-relevant
catalog in pkg/features/kube_features.go (GenericWorkload:348,
OpportunisticBatching:671, TopologyAwareWorkloadScheduling:1062,
SchedulerAsyncAPICalls:899, SchedulerQueueingHints:920,
DynamicResourceAllocation:302, NodeDeclaredFeatures:635).
"""

from __future__ import annotations

from dataclasses import dataclass

ALPHA = "ALPHA"
BETA = "BETA"
GA = "GA"


@dataclass(frozen=True)
class FeatureSpec:
    default: bool
    pre_release: str = ALPHA
    locked_to_default: bool = False


# The catalog: our framework's gates, defaults mirroring the reference's
# maturity levels for the same features.
KNOWN_FEATURES: dict[str, FeatureSpec] = {
    # gang scheduling (GenericWorkload + PodGroup API, alpha fork feature —
    # on by default here because the TPU framework's north star is gangs)
    "GangScheduling": FeatureSpec(default=True, pre_release=BETA),
    "TopologyAwareWorkloadScheduling": FeatureSpec(default=True, pre_release=ALPHA),
    # KEP-5598 batch reuse (alpha -> default off)
    "OpportunisticBatching": FeatureSpec(default=False, pre_release=ALPHA),
    "SchedulerAsyncAPICalls": FeatureSpec(default=False, pre_release=BETA),
    "SchedulerQueueingHints": FeatureSpec(default=True, pre_release=BETA),
    "DynamicResourceAllocation": FeatureSpec(default=True, pre_release=GA),
    "NodeDeclaredFeatures": FeatureSpec(default=True, pre_release=ALPHA),
    "DefaultPreemption": FeatureSpec(default=True, pre_release=GA,
                                     locked_to_default=False),
    # TPU-native additions
    "TPUBackend": FeatureSpec(default=True, pre_release=BETA),
}


class FeatureGate:
    """Mutable view over the catalog (featuregate.MutableFeatureGate)."""

    def __init__(self, known: dict[str, FeatureSpec] | None = None):
        self.known = dict(known or KNOWN_FEATURES)
        self.overrides: dict[str, bool] = {}

    def enabled(self, name: str) -> bool:
        if name in self.overrides:
            return self.overrides[name]
        spec = self.known.get(name)
        if spec is None:
            raise KeyError(f"unknown feature gate {name!r}")
        return spec.default

    def set_from_map(self, m: dict[str, bool]) -> None:
        for name, value in m.items():
            spec = self.known.get(name)
            if spec is None:
                raise KeyError(f"unknown feature gate {name!r}")
            if spec.locked_to_default and value != spec.default:
                raise ValueError(f"cannot set locked feature gate {name}")
            self.overrides[name] = bool(value)

    def as_map(self) -> dict[str, bool]:
        return {name: self.enabled(name) for name in self.known}
