"""Deterministic fault injection: one seeded registry, named points.

The chaos contract (README "Fault injection & degradation ladder"): every
place the scheduler talks to something that can fail in production — the
store write path, the async dispatcher's call execution, the TPU wave
launch/collect pair, watch delivery — declares a NAMED injection point
and calls `fire(point)` on it. A disarmed registry (the default, and the
only mode outside chaos tests) answers with one attribute read and a
bool check; an armed registry consults its schedule of `FaultSpec`s and
either raises a transient/permanent error, sleeps (latency), or tells
the caller to drop the delivery.

Everything is reproducible from one seed: each spec draws from its own
`random.Random` seeded by (registry seed, point, spec index), so whether
spec A fires on its point's Nth visit never depends on how often any
OTHER point was visited. Re-running the same workload with the same seed
replays the same fault schedule.

kubesched-lint rule RET01 enforces that this module is the only fault
source (no ad-hoc `if random(): raise` flakes in the tree).
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable


class FaultInjected(Exception):
    """Base class for injected errors (never raised by real code paths)."""

    transient = False


class TransientFault(FaultInjected):
    """An injected failure that a bounded retry is expected to absorb."""

    transient = True


class PermanentFault(FaultInjected):
    """An injected failure that must surface through the failure handler."""


class SchedulerCrashed(FaultInjected):
    """Injected scheduler death: rips straight through the scheduling loop.

    Deliberately NOT transient — the dispatcher's bounded retry and the
    device path's DeviceFlakeError wrapping must never absorb it. The
    chaos restart soak catches it above `schedule_pending`, tears the
    scheduler down ungracefully (no drain, no flush) and constructs a
    fresh one over the same store."""

    transient = False


# fault modes
ERROR = "error"
LATENCY = "latency"
DROP = "drop"
# the process dies mid-flight: fire() raises SchedulerCrashed, which no
# retry layer may absorb — only the restart soak driver catches it
CRASH = "crash"
# a long-lived gap: once triggered, the spec drops `window` CONSECUTIVE
# visits unconditionally — on a watch point that is a contiguous
# revision-range loss the informer must detect by itself (bookmark
# staleness), not a per-delivery coin flip like DROP
PARTITION = "partition"

# every injection point threaded through the tree; the golden bit-compat
# tests assert this exact set is registered (and disarmed) — a new call
# site must be declared here or `fire` raises KeyError under chaos tests.
# kubesched-lint rule FI01 cross-checks every fire() call site against
# this constant, so a typo'd point name can't silently never arm.
FAULT_POINTS = (
    "store.create",
    "store.update",
    "store.delete",
    "store.bind_pod",
    "store.patch_pod_status",
    "dispatcher.execute",
    "tpu.launch",
    "tpu.collect",
    "watch.deliver",
    "watch.partition",
    "kubelet.sync",
    "kubelet.lease",
    "kubelet.pleg",
    "controller.reconcile",
    "controller.lifecycle",
    "controller.workloads",
    # one leader-election CAS round (acquire or renew): ERROR/LATENCY model
    # a flaky or slow coordination write, PARTITION a window where every
    # renewal is lost — seeded lease loss and renew storms for the fleet
    "lease.renew",
    # crash points on the main scheduling thread: unlike tpu.* (whose
    # FaultInjected raises are caught locally and wrapped as device
    # flakes) these sit where SchedulerCrashed can propagate cleanly up
    # through schedule_pending to the restart soak driver
    "loop.wave",
    "loop.bind_commit",
    "gang.permit",
)
# historical alias (pre-FI01 name); same object, never diverges
POINTS = FAULT_POINTS


@dataclass
class FaultSpec:
    """One scheduled fault at one point.

    `start_after` skips the first N visits to the point; `times` bounds how
    often the spec fires (None = unlimited); `probability` gates each
    remaining visit through the spec's own seeded rng. `exc` overrides the
    raised exception (e.g. a real store ConflictError) for ERROR mode.

    PARTITION mode: `times` bounds how often the partition OPENS; each
    opening then drops `window` consecutive visits unconditionally (the
    opening visit included), producing one contiguous gap per opening."""

    point: str
    mode: str = ERROR
    transient: bool = True
    probability: float = 1.0
    times: int | None = None
    start_after: int = 0
    latency_s: float = 0.0
    window: int = 1
    message: str = "injected fault"
    exc: Callable[[str], Exception] | None = None
    # runtime state (owned by the registry)
    fired: int = 0
    _open_left: int = 0
    _rng: random.Random | None = field(default=None, repr=False)

    def make_error(self) -> Exception:
        msg = f"{self.point}: {self.message}"
        if self.exc is not None:
            return self.exc(msg)
        return TransientFault(msg) if self.transient else PermanentFault(msg)


class FaultRegistry:
    """Seeded, schedule-driven fault registry behind the `fire` points."""

    def __init__(self, seed: int = 0):
        self.seed = seed
        self.armed = False
        self._mu = threading.Lock()
        self._specs: dict[str, list[FaultSpec]] = {p: [] for p in POINTS}
        self._visits: dict[str, int] = {p: 0 for p in POINTS}
        self.fired_total = 0
        self.fired_by_point: dict[str, int] = {p: 0 for p in POINTS}

    # -- configuration -----------------------------------------------------

    def register(self, spec: FaultSpec) -> FaultSpec:
        with self._mu:
            if spec.point not in self._specs:
                raise KeyError(
                    f"unknown injection point {spec.point!r} "
                    f"(known: {', '.join(POINTS)})"
                )
            idx = len(self._specs[spec.point])
            # per-spec stream: independent of visit order at other points;
            # a str seed hashes via sha512 (stable across processes, unlike
            # tuple hashing under PYTHONHASHSEED randomization)
            spec._rng = random.Random(f"{self.seed}:{spec.point}:{idx}")
            spec.fired = 0
            spec._open_left = 0
            self._specs[spec.point].append(spec)
            return spec

    def arm(self) -> None:
        with self._mu:
            self.armed = True

    def disarm(self) -> None:
        with self._mu:
            self.armed = False

    def reset(self, seed: int | None = None) -> None:
        """Drop every spec and counter; optionally reseed."""
        with self._mu:
            if seed is not None:
                self.seed = seed
            self.armed = False
            self._specs = {p: [] for p in POINTS}
            self._visits = {p: 0 for p in POINTS}
            self.fired_total = 0
            self.fired_by_point = {p: 0 for p in POINTS}

    # -- the hot call ------------------------------------------------------

    def fire(self, point: str) -> bool:
        """Visit an injection point. Disarmed: False immediately. Armed:
        the first matching spec acts — ERROR raises, LATENCY sleeps then
        returns False, DROP returns True (caller skips the delivery)."""
        if not self.armed:
            return False
        sleep_s = 0.0
        err: Exception | None = None
        dropped = False
        with self._mu:
            visit = self._visits[point]  # KeyError = undeclared point
            self._visits[point] = visit + 1
            for spec in self._specs[point]:
                # an open partition window swallows every visit
                # unconditionally until it closes — that is what makes
                # the gap contiguous (a revision RANGE, not scattered
                # drops a probability gate would produce)
                if spec.mode == PARTITION and spec._open_left > 0:
                    spec._open_left -= 1
                    self.fired_total += 1
                    self.fired_by_point[point] += 1
                    dropped = True
                    break
                if visit < spec.start_after:
                    continue
                if spec.times is not None and spec.fired >= spec.times:
                    continue
                if spec.probability < 1.0 and (
                    spec._rng.random() >= spec.probability
                ):
                    continue
                spec.fired += 1
                self.fired_total += 1
                self.fired_by_point[point] += 1
                if spec.mode == ERROR:
                    err = spec.make_error()
                elif spec.mode == CRASH:
                    err = SchedulerCrashed(
                        f"{point}: {spec.message} (seed {self.seed})"
                    )
                elif spec.mode == LATENCY:
                    sleep_s = spec.latency_s
                elif spec.mode == DROP:
                    dropped = True
                elif spec.mode == PARTITION:
                    # this visit opens the gap and is itself dropped;
                    # the remaining window - 1 visits drop above
                    spec._open_left = max(spec.window - 1, 0)
                    dropped = True
                break
        # act OUTSIDE the registry lock: a latency injection must not
        # serialize every other point behind this one's sleep
        if sleep_s > 0.0:
            time.sleep(sleep_s)
        if err is not None:
            raise err
        return dropped

    # -- introspection -----------------------------------------------------

    def points(self) -> tuple[str, ...]:
        return POINTS

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "seed": self.seed,
                "armed": self.armed,
                "fired_total": self.fired_total,
                "fired_by_point": {
                    p: n for p, n in self.fired_by_point.items() if n
                },
                "visits": {p: n for p, n in self._visits.items() if n},
                "specs": {
                    p: len(specs) for p, specs in self._specs.items() if specs
                },
            }


# one process-wide registry: call sites fire on it via the module functions
# below, tests/chaos own its lifecycle through reset()/arm()/disarm()
_REGISTRY = FaultRegistry()


def registry() -> FaultRegistry:
    return _REGISTRY


def fire(point: str) -> bool:
    """Module-level fast path — the form every call site uses."""
    r = _REGISTRY
    if not r.armed:
        return False
    return r.fire(point)


def fired_total() -> int:
    return _REGISTRY.fired_total
