"""Injectable clocks — deterministic time in tests.

Reference: k8s.io/utils/clock (clock.WithTicker injected at scheduler.go:242).
"""

from __future__ import annotations

import threading
import time


class Clock:
    def now(self) -> float:
        return time.time()

    def sleep(self, seconds: float) -> None:
        time.sleep(seconds)


class FakeClock(Clock):
    def __init__(self, start: float = 1000.0):
        self._now = start
        self._mu = threading.Lock()

    def now(self) -> float:
        with self._mu:
            return self._now

    def sleep(self, seconds: float) -> None:
        self.step(seconds)

    def step(self, seconds: float) -> None:
        with self._mu:
            self._now += seconds
