"""Injectable clocks — deterministic time in tests.

Reference: k8s.io/utils/clock (clock.WithTicker injected at scheduler.go:242).
"""

from __future__ import annotations

import threading
import time


class Clock:
    def now(self) -> float:
        return time.time()

    def sleep(self, seconds: float) -> None:
        time.sleep(seconds)

    def wait_for(self, waiter, timeout: float):
        """Block up to `timeout` on a blocking waiter (e.g. a condition
        wait); returns the waiter's result. Virtual clocks override this —
        they cannot block on wall time, so they advance virtually instead.
        Keeping the branch INSIDE the clock means callers never type-check
        the clock (a subclass silently degrading to a poll loop was the
        failure mode this replaces)."""
        return waiter(timeout)


class FakeClock(Clock):
    def __init__(self, start: float = 1000.0):
        self._now = start
        self._mu = threading.Lock()

    def now(self) -> float:
        with self._mu:
            return self._now

    def sleep(self, seconds: float) -> None:
        self.step(seconds)

    def step(self, seconds: float) -> None:
        with self._mu:
            self._now += seconds

    def wait_for(self, waiter, timeout: float):
        # non-blocking probe, then advance virtual time so deadline loops
        # (e.g. WaitOnPermit) progress deterministically
        result = waiter(0)
        if result is None:
            self.step(min(timeout, 0.001))
        return result
