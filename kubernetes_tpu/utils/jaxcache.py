"""Persistent XLA compilation cache setup.

The wave kernel (ops/kernels.py batched_assign) is one big scanned program;
compiling it for a 512-pod wave over a 5k-node cluster costs tens of seconds
on TPU, while steady-state execution is ~0.1s. The reference amortizes its
equivalent cost (Go compile) at build time; we amortize XLA compiles across
processes with JAX's persistent compilation cache.

This JAX build does NOT honor the JAX_COMPILATION_CACHE_DIR /
JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS environment variables (the config
values stay None/default when they are set), so the cache silently never
engages — it must be enabled via jax.config.update before the first compile.
Call enable_persistent_cache() from every entry point that compiles kernels
(bench, perf harness, tests, graft entry).
"""

from __future__ import annotations

import os

_DEFAULT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    ".jax_cache",
)

def enable_persistent_cache(path: str | None = None,
                            min_compile_secs: float = 1.0) -> str:
    """Point JAX's persistent compilation cache at `path` (default:
    $KUBERNETES_TPU_JAX_CACHE or <repo>/.jax_cache). Idempotent — repeat
    calls just re-apply the config, so the latest explicit path wins; safe
    before or after the first device use, but only compiles issued
    afterwards are cached."""
    cache_dir = path or os.environ.get("KUBERNETES_TPU_JAX_CACHE", _DEFAULT_DIR)
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(min_compile_secs))
    return cache_dir
