"""CEL subset: device selection expressions + admission policy expressions.

Reference, two consumers:
- DRA device selection (pkg/scheduler/framework/plugins/dynamicresources/
  dynamicresources.go:637 via staging/src/k8s.io/dynamic-resource-
  allocation/cel/compile.go): predicates over a `device` variable —
      device.driver == "gpu.example.com"
      device.capacity["memory"] >= quantity("40Gi")
- ValidatingAdmissionPolicy (staging/src/k8s.io/apiserver/pkg/admission/
  plugin/policy/validating): predicates over `object` / `oldObject` /
  `request` —
      object.spec.replicas <= 5
      has(object.meta.labels) && object.meta.labels["env"] == "prod"

This module implements exactly that surface: a recursive descent parser
producing a compiled closure, with ==, !=, <, <=, >, >=, &&, ||, !, `in`
over list literals, parentheses, string/int/float/bool literals, the
`quantity()` / `size()` functions and the `has()` presence macro, and
generic variable paths (`<root>(.field | [key])*`) walked over dict
contexts. Compilation is cached per expression.

Security note: expressions are parsed into closures over a fixed AST — no
Python eval, no attribute access beyond the provided context dicts.
"""

from __future__ import annotations

import re
from typing import Any, Callable

_TOKEN_RE = re.compile(r"""
    \s*(?:
      (?P<num>-?\d+(?:\.\d+)?)
    | (?P<str>"(?:[^"\\]|\\.)*"|'(?:[^'\\]|\\.)*')
    | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
    | (?P<op>==|!=|>=|<=|&&|\|\||[><!()\[\],.])
    )""", re.VERBOSE)


class CELError(ValueError):
    pass


def _tokenize(src: str) -> list[tuple[str, str]]:
    out = []
    pos = 0
    while pos < len(src):
        m = _TOKEN_RE.match(src, pos)
        if m is None or m.end() == pos:
            rest = src[pos:].strip()
            if not rest:
                break
            raise CELError(f"cannot tokenize at: {rest[:20]!r}")
        if m.group("num") is not None:
            out.append(("num", m.group("num")))
        elif m.group("str") is not None:
            s = m.group("str")[1:-1]
            if "\\" in s:
                # resolve backslash escapes only when present — the UTF-8
                # round trip through unicode_escape mangles non-ASCII text
                s = s.encode("latin-1", "backslashreplace").decode("unicode_escape")
            out.append(("str", s))
        elif m.group("ident") is not None:
            out.append(("ident", m.group("ident")))
        else:
            out.append(("op", m.group("op")))
        pos = m.end()
    return out


class _Parser:
    def __init__(self, tokens: list[tuple[str, str]]):
        self.toks = tokens
        self.i = 0

    def peek(self):
        return self.toks[self.i] if self.i < len(self.toks) else ("eof", "")

    def next(self):
        t = self.peek()
        self.i += 1
        return t

    def expect(self, kind: str, value: str | None = None):
        t = self.next()
        if t[0] != kind or (value is not None and t[1] != value):
            raise CELError(f"expected {value or kind}, got {t[1]!r}")
        return t

    # expr := or_expr
    def parse(self) -> Callable[[dict], Any]:
        fn = self.parse_or()
        if self.peek()[0] != "eof":
            raise CELError(f"trailing tokens at {self.peek()[1]!r}")
        return fn

    def parse_or(self):
        left = self.parse_and()
        while self.peek() == ("op", "||"):
            self.next()
            right = self.parse_and()
            left = (lambda l, r: lambda ctx: bool(l(ctx)) or bool(r(ctx)))(left, right)
        return left

    def parse_and(self):
        left = self.parse_unary()
        while self.peek() == ("op", "&&"):
            self.next()
            right = self.parse_unary()
            left = (lambda l, r: lambda ctx: bool(l(ctx)) and bool(r(ctx)))(left, right)
        return left

    def parse_unary(self):
        if self.peek() == ("op", "!"):
            self.next()
            inner = self.parse_unary()
            return lambda ctx: not bool(inner(ctx))
        return self.parse_comparison()

    _CMP = {
        "==": lambda a, b: a == b,
        "!=": lambda a, b: a != b,
        ">": lambda a, b: _numeric(a) > _numeric(b),
        ">=": lambda a, b: _numeric(a) >= _numeric(b),
        "<": lambda a, b: _numeric(a) < _numeric(b),
        "<=": lambda a, b: _numeric(a) <= _numeric(b),
    }

    def parse_comparison(self):
        left = self.parse_operand()
        t = self.peek()
        if t[0] == "op" and t[1] in self._CMP:
            op = self._CMP[self.next()[1]]
            right = self.parse_operand()
            return (lambda l, r, op: lambda ctx: op(l(ctx), r(ctx)))(left, right, op)
        if t == ("ident", "in"):
            self.next()
            right = self.parse_operand()
            return (lambda l, r: lambda ctx: l(ctx) in r(ctx))(left, right)
        return left

    def parse_operand(self):
        t = self.peek()
        if t == ("op", "("):
            self.next()
            inner = self.parse_or()
            self.expect("op", ")")
            return inner
        if t == ("op", "["):
            self.next()
            items = []
            while self.peek() != ("op", "]"):
                items.append(self.parse_operand())
                if self.peek() == ("op", ","):
                    self.next()
            self.expect("op", "]")
            return (lambda items: lambda ctx: [f(ctx) for f in items])(items)
        if t[0] == "num":
            self.next()
            val = float(t[1]) if "." in t[1] else int(t[1])
            return lambda ctx, val=val: val
        if t[0] == "str":
            self.next()
            return lambda ctx, val=t[1]: val
        if t[0] == "ident":
            return self.parse_path_or_call()
        raise CELError(f"unexpected token {t[1]!r}")

    def parse_path_or_call(self):
        name = self.next()[1]
        if name == "true":
            return lambda ctx: True
        if name == "false":
            return lambda ctx: False
        if name == "null":
            return lambda ctx: None
        if name == "quantity":
            self.expect("op", "(")
            arg = self.parse_operand()
            self.expect("op", ")")

            def q(ctx, arg=arg):
                from ..api.quantity import parse_quantity

                return parse_quantity(str(arg(ctx)))

            return q
        if name == "size":
            self.expect("op", "(")
            arg = self.parse_operand()
            self.expect("op", ")")

            def sz(ctx, arg=arg):
                v = arg(ctx)
                if v is None:
                    raise CELError("size() of missing value")
                return len(v)

            return sz
        if name == "has":
            # CEL's has() macro: field-presence test; a missing path (or
            # any error walking it) is absence, never an evaluation error
            self.expect("op", "(")
            arg = self.parse_operand()
            self.expect("op", ")")

            def present(ctx, arg=arg):
                try:
                    return arg(ctx) is not None
                except (CELError, TypeError, KeyError):
                    return False

            return present
        # generic variable path: <root>(.field | [key])* over dict contexts
        # (the reference compiles against declared variables — object,
        # oldObject, request, device; an unknown ROOT is a runtime error so
        # admission failurePolicy applies, a missing FIELD is None so
        # comparisons read as non-matching)
        steps: list = []
        while True:
            t = self.peek()
            if t == ("op", "."):
                self.next()
                steps.append(("field", self.expect("ident")[1]))
            elif t == ("op", "["):
                self.next()
                key = self.parse_operand()
                self.expect("op", "]")
                steps.append(("index", key))
            else:
                break

        def walk(ctx, name=name, steps=tuple(steps)):
            if name not in ctx:
                raise CELError(f"unknown variable {name!r}")
            cur = ctx[name]
            for kind, step in steps:
                if cur is None:
                    return None
                key = step if kind == "field" else step(ctx)
                if isinstance(cur, dict):
                    cur = cur.get(key)
                elif isinstance(cur, (list, tuple)) and isinstance(key, int):
                    cur = cur[key] if -len(cur) <= key < len(cur) else None
                else:
                    raise CELError(
                        f"cannot access {key!r} on {type(cur).__name__}"
                    )
            return cur

        return walk


def _numeric(v) -> float:
    if isinstance(v, bool) or v is None:
        raise CELError(f"not numeric: {v!r}")
    if isinstance(v, (int, float)):
        return v
    try:
        return float(v)  # covers numeric strings and Fractions (quantity())
    except (TypeError, ValueError) as e:
        raise CELError(f"not numeric: {v!r}") from e


_compiled: dict[str, Callable[[dict], Any]] = {}


def compile_expression(src: str) -> Callable[[dict], Any]:
    """Compile (with cache) a device selection expression."""
    fn = _compiled.get(src)
    if fn is None:
        fn = _Parser(_tokenize(src)).parse()
        _compiled[src] = fn
    return fn


def evaluate_device(src: str, *, driver: str = "", name: str = "",
                    attributes=None, capacity=None) -> bool:
    """Evaluate an expression against one device; mis-typed comparisons and
    missing attributes evaluate False (the reference treats runtime CEL
    errors as non-matching devices)."""
    # no copies: this runs per candidate device inside the Filter hot loop,
    # and the compiled closures only ever .get() from these mappings
    _empty: dict = {}
    ctx = {"device": {
        "driver": driver,
        "name": name,
        "attributes": attributes if attributes is not None else _empty,
        "capacity": capacity if capacity is not None else _empty,
    }}
    try:
        return bool(compile_expression(src)(ctx))
    except (CELError, TypeError, KeyError, ValueError):
        # compile failures and runtime type errors (e.g. quantity() over a
        # missing attribute) are NON-MATCHES, never scheduler errors — a
        # bad expression must not put the pod on the error-backoff loop
        return False
