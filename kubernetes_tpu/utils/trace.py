"""Latency tracing: utiltrace-style step traces logged only when slow.

Reference: vendor/k8s.io/utils/trace/trace.go:154-216 — schedulePod opens
utiltrace.New("Scheduling", ...) and LogIfLong(100ms)
(pkg/scheduler/schedule_one.go:570-571,581,611): steps are recorded cheaply
(a perf_counter read each) and the trace is only FORMATTED and logged when
the whole operation exceeded the threshold — the diagnostic exists exactly
when the perf problem does.

This module is a thin shim over `utils.tracing`: a Trace IS a Span (steps
are span events, fields are span attributes) and log_if_long runs it
through `tracing.threshold_log_exporter`, which owns the legacy line
format.

DEPRECATED: the scheduler now uses `utils.tracing` Span +
`threshold_log_exporter` directly (one tracer surface, so the pod latency
ledger's exemplar links resolve against the same span tree the flight
recorder exports). Constructing a Trace emits a DeprecationWarning; new
call sites should build a Span and run it through
`threshold_log_exporter` as schedule_one.py does.
"""

from __future__ import annotations

import logging
import time
import warnings

from .tracing import Span, threshold_log_exporter

logger = logging.getLogger("kubernetes_tpu.trace")


class Trace:
    """One traced operation; steps are span events on the backing Span."""

    __slots__ = ("span",)

    def __init__(self, name: str, **fields):
        warnings.warn(
            "utils.trace.Trace is deprecated; use utils.tracing Span + "
            "threshold_log_exporter (one tracer surface)",
            DeprecationWarning, stacklevel=2,
        )
        self.span = Span(name=name, start=time.perf_counter(),
                         attributes=dict(fields))

    @property
    def name(self) -> str:
        return self.span.name

    @property
    def fields(self) -> dict:
        return self.span.attributes

    @property
    def start(self) -> float:
        return self.span.start

    @property
    def steps(self) -> list[tuple[float, str]]:
        # legacy view: absolute (timestamp, message) pairs
        return [(self.span.start + off, msg)
                for off, msg, _attrs in self.span.events]

    def step(self, msg: str) -> None:
        self.span.event(msg)

    def total_time(self) -> float:
        return self.span.duration_s

    def log_if_long(self, threshold: float = 0.1) -> bool:
        """Format + log the step timeline iff total exceeded threshold
        (LogIfLong, trace.go:208). Returns whether it logged."""
        self.span.end = time.perf_counter()
        return threshold_log_exporter(threshold, logger)(self.span)
