"""Latency tracing: utiltrace-style step traces logged only when slow.

Reference: vendor/k8s.io/utils/trace/trace.go:154-216 — schedulePod opens
utiltrace.New("Scheduling", ...) and LogIfLong(100ms)
(pkg/scheduler/schedule_one.go:570-571,581,611): steps are recorded cheaply
(a perf_counter read each) and the trace is only FORMATTED and logged when
the whole operation exceeded the threshold — the diagnostic exists exactly
when the perf problem does.
"""

from __future__ import annotations

import logging
import time

logger = logging.getLogger("kubernetes_tpu.trace")


class Trace:
    """One traced operation; nested steps are (timestamp, message)."""

    __slots__ = ("name", "fields", "start", "steps")

    def __init__(self, name: str, **fields):
        self.name = name
        self.fields = fields
        self.start = time.perf_counter()
        self.steps: list[tuple[float, str]] = []

    def step(self, msg: str) -> None:
        self.steps.append((time.perf_counter(), msg))

    def total_time(self) -> float:
        return time.perf_counter() - self.start

    def log_if_long(self, threshold: float = 0.1) -> bool:
        """Format + log the step timeline iff total exceeded threshold
        (LogIfLong, trace.go:208). Returns whether it logged."""
        total = self.total_time()
        if total < threshold:
            return False
        fields = ",".join(f"{k}={v}" for k, v in self.fields.items())
        lines = [f'Trace "{self.name}" ({fields}): total {total * 1000:.1f}ms '
                 f'(threshold {threshold * 1000:.0f}ms):']
        prev = self.start
        for ts, msg in self.steps:
            lines.append(f"  +{(ts - prev) * 1000:.1f}ms {msg}")
            prev = ts
        logger.warning("\n".join(lines))
        return True
