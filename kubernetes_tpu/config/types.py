"""KubeSchedulerConfiguration: the scheduler's versioned component config.

Reference: pkg/scheduler/apis/config/types.go (KubeSchedulerConfiguration:37,
Parallelism:49 default 16, PercentageOfNodesToScore:70, profiles:100) with
v1 defaulting (apis/config/v1/defaults.go) and validation
(apis/config/validation/validation.go).
"""

from __future__ import annotations

from dataclasses import dataclass, field

API_VERSION = "kubescheduler.config.tpu.io/v1"
KIND = "KubeSchedulerConfiguration"

DEFAULT_PARALLELISM = 16
DEFAULT_PERCENTAGE_OF_NODES_TO_SCORE = 0  # 0 = adaptive 50 - nodes/125


@dataclass
class PluginSet:
    enabled: list[str] = field(default_factory=list)
    disabled: list[str] = field(default_factory=list)  # ["*"] disables all


@dataclass
class ProfileConfig:
    scheduler_name: str = "default-scheduler"
    percentage_of_nodes_to_score: int | None = None
    plugins: PluginSet = field(default_factory=PluginSet)
    plugin_args: dict = field(default_factory=dict)  # plugin name -> args
    backend: str = "host"  # TPU-native addition: "host" | "tpu"
    # >0 with backend="tpu": schedule each run of up to waveSize pods in
    # one device program (bit-identical to per-pod; throughput mode)
    wave_size: int = 0


@dataclass
class LeaderElectionConfig:
    leader_elect: bool = False
    lease_duration: float = 15.0
    renew_deadline: float = 10.0
    retry_period: float = 2.0
    resource_name: str = "kube-scheduler"
    resource_namespace: str = "kube-system"


@dataclass
class SchedulerConfiguration:
    parallelism: int = DEFAULT_PARALLELISM
    percentage_of_nodes_to_score: int = DEFAULT_PERCENTAGE_OF_NODES_TO_SCORE
    profiles: list[ProfileConfig] = field(default_factory=lambda: [ProfileConfig()])
    extenders: list = field(default_factory=list)  # ExtenderConfig
    feature_gates: dict[str, bool] = field(default_factory=dict)
    leader_election: LeaderElectionConfig = field(
        default_factory=LeaderElectionConfig
    )
    pod_initial_backoff_seconds: float = 1.0
    pod_max_backoff_seconds: float = 10.0
    health_bind_port: int = 0  # 0 = disabled

    def validate(self) -> list[str]:
        """validation.go ValidateKubeSchedulerConfiguration."""
        errs = []
        if self.parallelism <= 0:
            errs.append("parallelism must be greater than 0")
        if not (0 <= self.percentage_of_nodes_to_score <= 100):
            errs.append("percentageOfNodesToScore must be in [0, 100]")
        if not self.profiles:
            errs.append("at least one profile is required")
        names = [p.scheduler_name for p in self.profiles]
        if len(names) != len(set(names)):
            errs.append("profile schedulerNames must be unique")
        for p in self.profiles:
            if p.backend not in ("host", "tpu"):
                errs.append(f"profile {p.scheduler_name}: unknown backend {p.backend}")
            if p.wave_size < 0:
                errs.append(f"profile {p.scheduler_name}: waveSize must be >= 0")
            if p.wave_size > 0 and p.backend != "tpu":
                errs.append(
                    f"profile {p.scheduler_name}: waveSize requires backend=tpu"
                )
            if p.percentage_of_nodes_to_score is not None and not (
                0 <= p.percentage_of_nodes_to_score <= 100
            ):
                errs.append(
                    f"profile {p.scheduler_name}: percentageOfNodesToScore out of range"
                )
        if self.pod_initial_backoff_seconds <= 0:
            errs.append("podInitialBackoffSeconds must be greater than 0")
        if self.pod_max_backoff_seconds < self.pod_initial_backoff_seconds:
            errs.append("podMaxBackoffSeconds must be >= podInitialBackoffSeconds")
        le = self.leader_election
        if le.leader_elect and le.renew_deadline >= le.lease_duration:
            errs.append("leaderElection.renewDeadline must be < leaseDuration")
        return errs


def load_config(data: dict) -> SchedulerConfiguration:
    """Decode + default a versioned config document (apis/config/v1 scheme)."""
    if data.get("apiVersion") not in (None, API_VERSION):
        raise ValueError(f"unsupported apiVersion {data.get('apiVersion')!r}")
    if data.get("kind") not in (None, KIND):
        raise ValueError(f"unsupported kind {data.get('kind')!r}")
    cfg = SchedulerConfiguration()
    cfg.parallelism = int(data.get("parallelism", DEFAULT_PARALLELISM))
    cfg.percentage_of_nodes_to_score = int(
        data.get("percentageOfNodesToScore", DEFAULT_PERCENTAGE_OF_NODES_TO_SCORE)
    )
    cfg.feature_gates = dict(data.get("featureGates", {}))
    cfg.pod_initial_backoff_seconds = float(data.get("podInitialBackoffSeconds", 1.0))
    cfg.pod_max_backoff_seconds = float(data.get("podMaxBackoffSeconds", 10.0))
    cfg.health_bind_port = int(data.get("healthBindPort", 0))
    if "profiles" in data:
        cfg.profiles = []
        for p in data["profiles"]:
            plugins = p.get("plugins", {})
            args = {
                entry["name"]: entry.get("args", {})
                for entry in p.get("pluginConfig", [])
            }
            cfg.profiles.append(ProfileConfig(
                scheduler_name=p.get("schedulerName", "default-scheduler"),
                percentage_of_nodes_to_score=p.get("percentageOfNodesToScore"),
                plugins=PluginSet(
                    enabled=list(plugins.get("enabled", [])),
                    disabled=list(plugins.get("disabled", [])),
                ),
                plugin_args=args,
                backend=p.get("backend", "host"),
                wave_size=int(p.get("waveSize", 0)),
            ))
    if "extenders" in data:
        from ..scheduler.extender import ExtenderConfig

        cfg.extenders = [
            ExtenderConfig(
                url_prefix=e["urlPrefix"],
                filter_verb=e.get("filterVerb", ""),
                prioritize_verb=e.get("prioritizeVerb", ""),
                bind_verb=e.get("bindVerb", ""),
                weight=e.get("weight", 1),
                ignorable=e.get("ignorable", False),
                node_cache_capable=e.get("nodeCacheCapable", False),
                managed_resources=tuple(
                    r["name"] for r in e.get("managedResources", [])
                ),
            )
            for e in data["extenders"]
        ]
    if "leaderElection" in data:
        le = data["leaderElection"]
        cfg.leader_election = LeaderElectionConfig(
            leader_elect=le.get("leaderElect", False),
            lease_duration=float(le.get("leaseDurationSeconds", 15)),
            renew_deadline=float(le.get("renewDeadlineSeconds", 10)),
            retry_period=float(le.get("retryPeriodSeconds", 2)),
            resource_name=le.get("resourceName", "kube-scheduler"),
            resource_namespace=le.get("resourceNamespace", "kube-system"),
        )
    errs = cfg.validate()
    if errs:
        raise ValueError("invalid configuration: " + "; ".join(errs))
    return cfg


def load_config_file(path: str) -> SchedulerConfiguration:
    import yaml

    with open(path) as f:
        return load_config(yaml.safe_load(f) or {})
