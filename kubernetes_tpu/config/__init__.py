"""ComponentConfig (pkg/scheduler/apis/config equivalent)."""

from .types import (
    LeaderElectionConfig,
    PluginSet,
    ProfileConfig,
    SchedulerConfiguration,
    load_config,
    load_config_file,
)

__all__ = [
    "LeaderElectionConfig", "PluginSet", "ProfileConfig",
    "SchedulerConfiguration", "load_config", "load_config_file",
]
