"""ResourceQuota: usage accounting + admission enforcement.

Reference: pkg/controller/resourcequota (recompute status.used from live
objects) + plugin/pkg/admission/resourcequota (reject creates that would
exceed hard). Tracked resources: requests.cpu (milli), requests.memory
(MiB), pods, and count/<Kind> object counts — the subset our API models.
"""

from __future__ import annotations

from ..api.quantity import parse_cpu, parse_mem_mib
from .base import Controller

POD_PHASES_COUNTED = ("Pending", "Running")  # terminal pods don't consume


def pod_usage(pod) -> dict[str, int]:
    cpu = sum(parse_cpu(c.requests["cpu"])
              for c in pod.spec.containers if "cpu" in c.requests)
    mem = sum(parse_mem_mib(c.requests["memory"])
              for c in pod.spec.containers if "memory" in c.requests)
    return {"requests.cpu": cpu, "requests.memory": mem, "pods": 1}


def compute_usage(store, namespace: str, tracked: set[str]) -> dict[str, int]:
    used: dict[str, int] = {k: 0 for k in tracked}
    if {"requests.cpu", "requests.memory", "pods"} & tracked:
        # namespace-filtered list: the admission hot path must not deepcopy
        # every pod in the cluster to sum one namespace
        pods, _ = store.list("Pod", namespace=namespace)
        for p in pods:
            if p.status.phase not in POD_PHASES_COUNTED:
                continue
            for k, v in pod_usage(p).items():
                if k in used:
                    used[k] += v
    for key in tracked:
        if key.startswith("count/"):
            kind = key.split("/", 1)[1]
            used[key] = len(store.list(kind, namespace=namespace)[0])
    return used


class QuotaController(Controller):
    """resource_quota_controller.go: keep status.used fresh as objects
    churn, so admission decisions rest on accurate accounting.

    The reference discovers countable kinds dynamically via the
    RESTMapper; here the watch set is the kinds quotas commonly count
    (any event re-enqueues that namespace's quotas)."""

    name = "resourcequota"
    watches = ("ResourceQuota", "Pod", "Service", "PersistentVolumeClaim",
               "ResourceClaim", "Deployment", "Job")

    def key_of(self, kind: str, obj) -> str | None:
        if kind == "ResourceQuota":
            return obj.meta.key
        for rq in self.store.iter_kind("ResourceQuota"):
            if rq.meta.namespace == obj.meta.namespace:
                self.queue.add(rq.meta.key)
        return None

    def reconcile(self, key: str) -> None:
        rq = self.store.try_get("ResourceQuota", key)
        if rq is None:
            return
        used = compute_usage(self.store, rq.meta.namespace, set(rq.hard))
        if used != rq.used:
            rq.used = used
            self.store.update(rq, check_version=False)


def quota_admission(store):
    """Validating admission: a create that would push any tracked resource
    past `hard` is rejected with 403 (the reference's quota admission)."""
    from ..apiserver.server import AdmissionError

    def admit(operation: str, obj) -> None:
        if operation != "CREATE":
            return
        ns = getattr(obj.meta, "namespace", "")
        if not ns:
            return
        kind = getattr(obj, "kind", "")
        for rq in store.iter_kind("ResourceQuota"):
            if rq.meta.namespace != ns:
                continue
            # candidate's increments against this quota
            inc: dict[str, int] = {}
            if kind == "Pod":
                for k, v in pod_usage(obj).items():
                    if k in rq.hard:
                        inc[k] = v
            count_key = f"count/{kind}"
            if count_key in rq.hard:
                inc[count_key] = inc.get(count_key, 0) + 1
            if not inc:
                continue
            # recompute live usage (never trust possibly-stale status for
            # the enforcement decision)
            used = compute_usage(store, ns, set(inc))
            for k, v in inc.items():
                if used.get(k, 0) + v > rq.hard[k]:
                    raise AdmissionError(
                        f"exceeded quota {rq.meta.name}: requested "
                        f"{k}={v}, used {used.get(k, 0)} of {rq.hard[k]}",
                        code=403,
                    )

    # the live-usage check must be atomic with the store commit: the server
    # runs tagged plugins under its per-namespace create lock
    admit.serialize_with_create = True
    return admit
