"""Controller base: informer event handlers -> workqueue -> reconcile loop.

Reference: the universal controller pattern of pkg/controller/* — shared
informers feed keys into a rate-limited workqueue; worker goroutines pop keys
and reconcile actual state toward desired state, requeueing on error. This
base runs single-threaded-deterministic (sync_once) or threaded (run).
"""

from __future__ import annotations

import threading

from ..client.informer import InformerFactory
from ..client.workqueue import WorkQueue
from ..utils import faultinject


class Controller:
    """Subclasses set `watches` (kinds whose events enqueue keys) and
    implement `reconcile(key) -> None` (raise to retry with backoff) and
    `key_of(kind, obj) -> str | None` (None = ignore event).

    Time-driven controllers set `clocked_queue = True`: they get a `clock`
    (injectable) and a workqueue whose delayed-add timers tick on that same
    clock — the shared pattern for schedule-time/TTL/stabilization
    self-requeues."""

    name = "controller"
    watches: tuple[str, ...] = ()
    clocked_queue = False

    def __init__(self, store, informers: InformerFactory | None = None,
                 clock=None):
        from ..utils.clock import Clock

        self.store = store
        self.informers = informers or InformerFactory(store)
        self.clock = clock or Clock()
        self.queue = (WorkQueue(clock=self.clock.now) if self.clocked_queue
                      else WorkQueue())
        self._started = False
        for kind in self.watches:
            self.informers.informer(kind).add_handler(
                self._make_handler(kind)
            )

    def _make_handler(self, kind: str):
        def handler(etype, old, new):
            key = self.key_of(kind, new if new is not None else old)
            if key is not None:
                self.queue.add(key)

        return handler

    # -- to override ---------------------------------------------------------

    def key_of(self, kind: str, obj) -> str | None:
        return obj.meta.key

    def reconcile(self, key: str) -> None:
        raise NotImplementedError

    # -- drive ---------------------------------------------------------------

    def start(self) -> None:
        if not self._started:
            self.informers.start_all()
            self._started = True

    def sync_once(self, max_items: int = 10_000) -> int:
        """Pump informers and drain the queue once; returns reconciles run."""
        self.start()
        self.informers.pump_all()
        n = 0
        for _ in range(max_items):
            key = self.queue.get(timeout=0)
            if key is None:
                break
            try:
                # chaos: a reconcile that never ran (DROP — requeued with
                # backoff, the item is NOT lost) or crashed mid-flight
                # (ERROR — caught below, same backoff path as a real panic)
                if faultinject.fire("controller.reconcile"):
                    self.queue.add_rate_limited(key)
                else:
                    self.reconcile(key)
                    self.queue.forget(key)
            except Exception:  # noqa: BLE001 - controller retries with backoff
                self.queue.add_rate_limited(key)
            finally:
                self.queue.done(key)
            n += 1
            self.informers.pump_all()
        return n

    def run(self, stop_event: threading.Event, workers: int = 1,
            poll: float = 0.02) -> list[threading.Thread]:
        """Threaded mode (the reference's N worker goroutines)."""
        self.start()

        def pump_loop():
            while not stop_event.is_set():
                self.informers.pump_all()
                stop_event.wait(poll)

        def worker():
            while not stop_event.is_set():
                key = self.queue.get(timeout=poll)
                if key is None:
                    continue
                try:
                    # chaos: same contract as sync_once — DROP requeues,
                    # ERROR takes the normal backoff path
                    if faultinject.fire("controller.reconcile"):
                        self.queue.add_rate_limited(key)
                    else:
                        self.reconcile(key)
                        self.queue.forget(key)
                except Exception:  # noqa: BLE001
                    self.queue.add_rate_limited(key)
                finally:
                    self.queue.done(key)

        threads = [threading.Thread(target=pump_loop, daemon=True)]
        threads += [threading.Thread(target=worker, daemon=True) for _ in range(workers)]
        for t in threads:
            t.start()
        return threads


class ControllerManager:
    """cmd/kube-controller-manager — owns the controller set and one shared
    informer factory."""

    def __init__(self, store, controllers: list[Controller] | None = None):
        self.store = store
        self.controllers: list[Controller] = list(controllers or [])

    def add(self, controller: Controller) -> None:
        self.controllers.append(controller)

    def sync_once(self, rounds: int = 10) -> int:
        """Drain every controller to quiescence (deterministic tests)."""
        total = 0
        for _ in range(rounds):
            n = sum(c.sync_once() for c in self.controllers)
            total += n
            if n == 0:
                break
        return total

    def run(self, stop_event: threading.Event) -> None:
        for c in self.controllers:
            c.run(stop_event)
