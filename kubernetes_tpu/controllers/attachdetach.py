"""Attach/detach controller: VolumeAttachment reconciliation.

Reference: pkg/controller/volume/attachdetach/attach_detach_controller.go —
the controller watches pods and PVs, computes the desired set of
(volume, node) attachments from scheduled pods' claim-backed CSI volumes,
creates VolumeAttachment objects for missing ones and deletes them when the
last pod using the volume on that node is gone. The external CSI attacher
then performs the attach and reports status; here the attacher is
in-process (the in-memory dataplane), flipping status["attached"] in the
same reconcile pass so the kubelet's WaitForAttachAndMount can proceed.

In-tree (non-CSI) volumes need no attach — the kubelet mounts them
directly, exactly like the reference's non-attachable plugins.
"""

from __future__ import annotations

from ..api.storage import VolumeAttachment, VolumeAttachmentSpec
from .base import Controller

_CLUSTER = "cluster"


class AttachDetachController(Controller):
    """Whole-cluster desired-state reconciler (the reference's
    desired_state_of_world is also global; per-object keys would just
    re-derive it)."""

    name = "attachdetach"
    watches = ("Pod", "PersistentVolumeClaim", "PersistentVolume",
               "VolumeAttachment")

    def key_of(self, kind: str, obj) -> str | None:
        return _CLUSTER

    def _desired(self) -> dict[str, tuple[str, str, str]]:
        """name -> (pv, node, attacher) for every scheduled pod's bound
        CSI claim volume (desired_state_of_world)."""
        out: dict[str, tuple[str, str, str]] = {}
        for pod in self.store.list_refs("Pod"):
            node = pod.spec.node_name
            if not node or pod.meta.deletion_timestamp is not None:
                continue
            for v in pod.spec.volumes:
                claim = v.claim_name(pod.meta.name)
                if not claim:
                    continue
                pvc = self.store.try_get(
                    "PersistentVolumeClaim", f"{pod.meta.namespace}/{claim}"
                )
                if pvc is None or not pvc.spec.volume_name:
                    continue
                pv = self.store.try_get("PersistentVolume",
                                        pvc.spec.volume_name)
                if pv is None or not pv.spec.csi_driver:
                    continue  # in-tree volumes attach implicitly
                name = VolumeAttachment.expected_name(pv.meta.name, node)
                out[name] = (pv.meta.name, node, pv.spec.csi_driver)
        return out

    def reconcile(self, key: str) -> None:
        from ..api.meta import ObjectMeta
        from ..store.store import AlreadyExistsError, NotFoundError

        desired = self._desired()
        existing = {va.meta.name
                    for va in self.store.list_refs("VolumeAttachment")}
        # attach: create intents for missing pairs
        for name, (pv, node, attacher) in desired.items():
            if name in existing:
                continue
            try:
                self.store.create(VolumeAttachment(
                    meta=ObjectMeta(name=name, namespace=""),
                    spec=VolumeAttachmentSpec(
                        attacher=attacher, node_name=node, pv_name=pv),
                ))
            except AlreadyExistsError:
                pass
        # the in-process attacher: report attach completion
        for name in desired:
            va = self.store.try_get("VolumeAttachment", name)
            if va is not None and not va.status.get("attached"):
                va.status["attached"] = True
                self.store.update(va, check_version=False)
        # detach: drop intents no pod needs anymore
        for name in existing - set(desired):
            try:
                self.store.delete("VolumeAttachment", name)
            except NotFoundError:
                pass
