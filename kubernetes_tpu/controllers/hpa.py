"""HorizontalPodAutoscaler controller.

Reference: pkg/controller/podautoscaler (horizontal.go reconcileAutoscaler
+ replica_calculator.go): desired = ceil(current * actualUtilization /
targetUtilization), clamped to [min, max], with scale-down stabilization —
the applied recommendation is the HIGHEST desired over the stabilization
window, so a brief dip never flaps a deployment down. Utilization is
usage/requests over the target's pods, from PodMetrics objects (the
metrics.k8s.io role; published by the kubelet's stats or the test/bench
harness).
"""

from __future__ import annotations

import math

from ..api.quantity import parse_cpu
from .base import Controller


class HPAController(Controller):
    name = "horizontalpodautoscaler"
    watches = ("HorizontalPodAutoscaler", "PodMetrics")

    # tolerance around target before acting (horizontal.go: 0.1)
    TOLERANCE = 0.1

    clocked_queue = True  # stabilization-expiry self-requeues

    def __init__(self, store, informers=None, clock=None):
        super().__init__(store, informers, clock=clock)
        # hpa key → [(time, desired)] recommendations inside the window
        self._recommendations: dict[str, list[tuple[float, int]]] = {}

    def key_of(self, kind: str, obj) -> str | None:
        if kind == "HorizontalPodAutoscaler":
            return obj.meta.key
        # metrics updates re-evaluate every HPA in that namespace (cheap:
        # HPAs are few); the reference resyncs on a 15s period instead
        for hpa in self.store.iter_kind("HorizontalPodAutoscaler"):
            if hpa.meta.namespace == obj.meta.namespace:
                self.queue.add(hpa.meta.key)
        return None

    def sweep(self) -> None:
        for hpa in self.store.iter_kind("HorizontalPodAutoscaler"):
            self.queue.add(hpa.meta.key)

    def reconcile(self, key: str) -> None:
        hpa = self.store.try_get("HorizontalPodAutoscaler", key)
        if hpa is None:
            self._recommendations.pop(key, None)
            return
        target = self.store.try_get(
            hpa.spec.scale_target_kind,
            f"{hpa.meta.namespace}/{hpa.spec.scale_target_name}",
        )
        if target is None:
            return
        pods = self._target_pods(hpa, target)
        # "current" is the ACTUAL replica count (scale.Status.Replicas in
        # horizontal.go), not spec.replicas: desired = ceil(actual * ratio)
        # stays a fixed point until the new pods (and their metrics) exist,
        # which is what keeps reconcile idempotent between metric samples
        current = len(pods)
        if current == 0:
            return
        utilization, n_sampled = self._utilization(pods)
        now = self.clock.now()
        changed = False
        if hpa.status.current_replicas != current:
            hpa.status.current_replicas = current
            changed = True
        if utilization is None:
            # no metrics yet: never scale on missing data (horizontal.go
            # treats missing metrics conservatively) — and report the
            # blindness instead of a stale confident number
            if hpa.status.current_cpu_utilization_percent is not None:
                hpa.status.current_cpu_utilization_percent = None
                changed = True
            if changed:
                self.store.update(hpa, check_version=False)
            return
        if hpa.status.current_cpu_utilization_percent != utilization:
            hpa.status.current_cpu_utilization_percent = utilization
            changed = True
        target_util = hpa.spec.target_cpu_utilization_percent
        ratio = utilization / target_util if target_util else 1.0
        missing = current - n_sampled
        if missing > 0:
            # replica_calculator.go missing-metric damping: when scaling UP
            # assume missing pods (fresh replicas) use 0%, when scaling
            # DOWN assume they use 100% — never let blind spots amplify
            if ratio > 1.0:
                ratio = (utilization * n_sampled / current) / target_util
            elif ratio < 1.0:
                ratio = ((utilization * n_sampled + 100 * missing)
                         / current) / target_util
        if abs(ratio - 1.0) <= self.TOLERANCE:
            desired = current
        else:
            desired = math.ceil(current * ratio)
        desired = max(hpa.spec.min_replicas,
                      min(hpa.spec.max_replicas, desired))
        # scale-down stabilization: remember this recommendation, apply the
        # window max (scale-UP applies immediately by construction: the max
        # includes the new high recommendation)
        recs = self._recommendations.setdefault(key, [])
        recs.append((now, desired))
        cutoff = now - hpa.spec.scale_down_stabilization_s
        recs[:] = [(t, d) for t, d in recs if t >= cutoff]
        applied = max(d for _, d in recs)
        if applied > desired and recs:
            # pinned above the live recommendation: revisit when the
            # pinning entries leave the window (no metric event will fire
            # for steady usage, so this wake-up is the only path down)
            oldest_pin = min(t for t, d in recs if d == applied)
            self.queue.add_after(
                key, max(0.1, oldest_pin + hpa.spec.scale_down_stabilization_s
                         - now + 0.1)
            )
        if hpa.status.desired_replicas != applied:
            hpa.status.desired_replicas = applied
            changed = True
        # compare against the KNOB we own (scale.Spec.Replicas): comparing
        # against the actual pod count would rewrite the target every
        # reconcile until the workload controller catches up
        if applied != target.spec.replicas:
            target.spec.replicas = applied
            self.store.update(target, check_version=False)
            hpa.status.last_scale_time = now
            changed = True
        if changed:
            self.store.update(hpa, check_version=False)

    # -- helpers -------------------------------------------------------------

    def _target_pods(self, hpa, target) -> list:
        sel = getattr(target.spec, "selector", None)
        if sel is not None and getattr(sel, "match_labels", None):
            labels = dict(sel.match_labels)  # tuple-of-pairs → dict
        else:
            labels = dict(target.spec.template.labels)
        if not labels:
            return []
        from ..api.labels import labels_subset

        return [
            p for p in self.store.pods()
            if p.meta.namespace == hpa.meta.namespace
            and labels_subset(labels, p.meta.labels)
            and not p.is_terminating
        ]

    def _utilization(self, pods) -> tuple[int | None, int]:
        """(mean usage/request percent over pods WITH metrics, sample
        count); (None, 0) if no pod has both a request and a sample."""
        ratios = []
        for p in pods:
            request = sum(
                parse_cpu(c.requests["cpu"])
                for c in p.spec.containers if "cpu" in c.requests
            )
            if request <= 0:
                continue
            m = self.store.try_get("PodMetrics", p.meta.key)
            if m is None:
                continue
            ratios.append(100.0 * m.cpu_usage_milli / request)
        if not ratios:
            return None, 0
        return int(round(sum(ratios) / len(ratios))), len(ratios)
