"""PersistentVolume lifecycle controller: claim↔volume binding outside the
scheduler, dynamic provisioning, reclaim.

Reference: pkg/controller/volume/persistentvolume/pv_controller.go — the
controller that owns Immediate-mode binding (syncUnboundClaim: find the
smallest adequate Available PV, else dynamically provision when the class
has a provisioner), keeps half-finished binds converging (syncBoundClaim),
and reclaims released volumes per persistentVolumeReclaimPolicy
(reclaimVolume: Retain → Released, Delete → delete the PV).

WaitForFirstConsumer claims are explicitly NOT bound here — the scheduler's
volume binder owns them (volume_binding.go PreBind), exactly as the
reference's pv controller skips claims annotated for delayed binding. With
this controller running, a pod using an unbound immediate-mode PVC is no
longer stranded: the controller binds the claim, the PVC update event
requeues the pod (VolumeBinding's EventsToRegister), and scheduling
proceeds.
"""

from __future__ import annotations

from ..api.storage import (
    CLAIM_BOUND,
    CLAIM_PENDING,
    NO_PROVISIONER,
    RECLAIM_DELETE,
    VOLUME_AVAILABLE,
    VOLUME_BOUND,
    VOLUME_RELEASED,
    PersistentVolume,
    PersistentVolumeSpec,
)
from .base import Controller


class PersistentVolumeController(Controller):
    name = "persistentvolume"
    watches = ("PersistentVolumeClaim", "PersistentVolume", "StorageClass")

    def key_of(self, kind: str, obj) -> str | None:
        if kind == "PersistentVolumeClaim":
            return f"pvc:{obj.meta.key}"
        if kind == "PersistentVolume":
            return f"pv:{obj.meta.key}"
        # a new StorageClass can unblock provisioning for pending claims
        return "rescan:"

    def reconcile(self, key: str) -> None:
        what, _, name = key.partition(":")
        if what == "pvc":
            self._sync_claim(name)
        elif what == "pv":
            self._sync_volume(name)
            pv = self.store.try_get("PersistentVolume", name)
            if pv is None:
                return
            if pv.spec.claim_ref:
                # bound/pre-bound volume: only its own claim can care —
                # NOT a global rescan (scheduler PreBind emits thousands
                # of bound-PV events in a WFFC storm; each must be O(1))
                self.queue.add(f"pvc:{pv.spec.claim_ref}")
            else:
                # an Available PV can only satisfy claims of its class
                self._rescan_pending(pv.spec.storage_class_name)
        else:
            self._rescan_pending()

    def _rescan_pending(self, storage_class: str | None = None) -> None:
        for pvc in self.store.iter_kind("PersistentVolumeClaim"):
            if pvc.status.phase != CLAIM_PENDING:
                continue
            if (storage_class is not None
                    and pvc.spec.storage_class_name != storage_class):
                continue
            self.queue.add(f"pvc:{pvc.meta.key}")

    # -- claims (pv_controller.go syncClaim) --------------------------------

    def _sync_claim(self, claim_key: str) -> None:
        pvc = self.store.try_get("PersistentVolumeClaim", claim_key)
        if pvc is None:
            # claim deleted: reclaim any volume still referencing it
            for pv in list(self.store.iter_kind("PersistentVolume")):
                if pv.spec.claim_ref == claim_key:
                    self._sync_volume(pv.meta.key)
            return
        if pvc.spec.volume_name:
            self._sync_prebound_claim(pvc)
            return
        sc = self.store.try_get("StorageClass", pvc.spec.storage_class_name) \
            if pvc.spec.storage_class_name else None
        wffc = sc is not None and sc.is_wait_for_first_consumer
        stale, pv = self._scan_volumes(pvc, match=not wffc)
        for name in stale:
            # a PV still referencing a PREVIOUS instance of this claim key
            # (delete + recreate before we reconciled) is dead — reclaim
            # it, or it stays Bound-with-stale-claimRef forever. This runs
            # for WFFC claims too: the binder refuses stale-uid PVs, so
            # only reclaim can free them.
            self._sync_volume(name)
        if wffc:
            return  # the scheduler's binder owns WFFC claims
        if pv is None and sc is not None and sc.provisioner != NO_PROVISIONER:
            pv = self._provision(pvc, sc)
        if pv is not None:
            self._bind(pv, pvc)

    def _sync_prebound_claim(self, pvc) -> None:
        """volume_name already set (pre-bound by user, or a bind that
        committed the PV half only): converge both halves."""
        pv = self.store.try_get("PersistentVolume", pvc.spec.volume_name)
        if pv is None:
            return  # claim references a missing PV: stays Pending (lost)
        if pv.spec.claim_ref in ("", pvc.meta.key) and (
            not pv.spec.claim_ref_uid
            or pv.spec.claim_ref_uid == pvc.meta.uid
        ):
            self._bind(pv, pvc)
        # else: PV belongs to another claim (instance) — stays Pending

    def _scan_volumes(self, pvc, match: bool = True):
        """ONE pass over PVs serving two roles (pv_controller.go folds both
        into its indexed lookups): collect stale same-key references (uid
        mismatch → reclaim) and, when `match`, find the best available
        volume — smallest Available PV satisfying class/capacity/access
        modes; a PV pre-bound to THIS claim instance wins outright."""
        stale: list[str] = []
        prebound = None
        best = None
        for pv in self.store.iter_kind("PersistentVolume"):
            if pv.spec.claim_ref == pvc.meta.key:
                if (pv.spec.claim_ref_uid
                        and pv.spec.claim_ref_uid != pvc.meta.uid):
                    stale.append(pv.meta.key)
                elif pv.status.phase == VOLUME_AVAILABLE:
                    prebound = pv
                continue
            if not match or pv.status.phase != VOLUME_AVAILABLE:
                continue
            if pv.spec.claim_ref:
                continue
            if pv.spec.storage_class_name != pvc.spec.storage_class_name:
                continue
            if not set(pvc.spec.access_modes) <= set(pv.spec.access_modes):
                continue
            if pv.storage_capacity < pvc.requested_storage:
                continue
            if best is None or pv.storage_capacity < best.storage_capacity:
                best = pv
        return stale, (prebound if prebound is not None else best)

    def _provision(self, pvc, sc):
        """Dynamic provisioning (provisionClaimOperation): mint a PV sized
        to the request, pre-bound to the claim, carrying the class's
        reclaim policy."""
        name = f"pvc-{pvc.meta.uid or pvc.meta.key.replace('/', '-')}"
        existing = self.store.try_get("PersistentVolume", name)
        if existing is not None:
            return existing
        pv = PersistentVolume(spec=PersistentVolumeSpec(
            capacity=dict(pvc.spec.request),
            access_modes=tuple(pvc.spec.access_modes),
            storage_class_name=sc.meta.name,
            claim_ref=pvc.meta.key,
            claim_ref_uid=pvc.meta.uid,
            csi_driver="" if sc.provisioner == NO_PROVISIONER
            else sc.provisioner,
            reclaim_policy=sc.reclaim_policy,
        ))
        pv.meta.name = name
        pv.meta.namespace = ""
        return self.store.create(pv)

    def _bind(self, pv, pvc) -> None:
        """bindVolumeToClaim + bindClaimToVolume: PV half first, claim half
        second; each write skipped when already converged so reconciles
        are idempotent."""
        if (pv.spec.claim_ref != pvc.meta.key
                or pv.spec.claim_ref_uid != pvc.meta.uid
                or pv.status.phase != VOLUME_BOUND):
            pv.spec.claim_ref = pvc.meta.key
            pv.spec.claim_ref_uid = pvc.meta.uid
            pv.status.phase = VOLUME_BOUND
            self.store.update(pv, check_version=False)
        if (pvc.spec.volume_name != pv.meta.name
                or pvc.status.phase != CLAIM_BOUND):
            pvc.spec.volume_name = pv.meta.name
            pvc.status.phase = CLAIM_BOUND
            self.store.update(pvc, check_version=False)

    # -- volumes (pv_controller.go syncVolume / reclaimVolume) --------------

    def _sync_volume(self, name: str) -> None:
        pv = self.store.try_get("PersistentVolume", name)
        if pv is None:
            return
        if not pv.spec.claim_ref:
            if pv.status.phase != VOLUME_AVAILABLE:
                pv.status.phase = VOLUME_AVAILABLE
                self.store.update(pv, check_version=False)
            return
        pvc = self.store.try_get("PersistentVolumeClaim", pv.spec.claim_ref)
        if pvc is not None and (not pv.spec.claim_ref_uid
                                or pvc.meta.uid == pv.spec.claim_ref_uid):
            return  # bound (or pre-bound awaiting _sync_claim)
        # claim is gone — or a DIFFERENT same-named claim took its place
        # (uid mismatch): either way the bound instance is dead, reclaim
        if pv.status.phase == VOLUME_BOUND:
            if pv.spec.reclaim_policy == RECLAIM_DELETE:
                self.store.try_delete("PersistentVolume", name)
                return
            pv.status.phase = VOLUME_RELEASED
            self.store.update(pv, check_version=False)
