"""CronJob controller.

Reference: pkg/controller/cronjob (cronjob_controllerv2.go syncCronJob):
parse the 5-field cron schedule, mint a Job at each due tick respecting
concurrencyPolicy (Allow runs overlap, Forbid defers while one is active,
Replace kills the running one), honor startingDeadlineSeconds for missed
ticks (too-late ticks are spent, not replayed), cap missed-tick scanning
(the reference's "too many missed start times", limit 100), and
garbage-collect finished jobs past the history limits. The controller
self-requeues at the next schedule time through its clock-aligned delayed
workqueue — no external resync needed.

The cron dialect is the standard 5-field core: "*", exact values, ranges
"a-b", steps "*/n" and "a-b/n" (anchored at the range start, as cron
anchors them), and comma lists. Unsupported syntax raises ValueError.
"""

from __future__ import annotations

import functools
import time as _time

from ..api.meta import ObjectMeta, OwnerReference, new_uid
from ..api.workloads import CronJob, Job
from .base import Controller

# (lo, hi) per field: minute, hour, day-of-month, month, day-of-week
_FIELD_RANGES = ((0, 59), (0, 23), (1, 31), (1, 12), (0, 7))

MAX_MISSED_STARTS = 100  # cronjob_controllerv2.go mostRecentScheduleTime cap


def _parse_field(spec: str, lo: int, hi: int) -> frozenset[int]:
    out: set[int] = set()
    for part in spec.split(","):
        step = 1
        if "/" in part:
            part, s = part.split("/", 1)
            if not s.isdigit() or int(s) <= 0:
                raise ValueError(f"bad cron step {s!r}")
            step = int(s)
        if part == "*":
            start, end = lo, hi
        elif "-" in part:
            a, _, b = part.partition("-")
            if not (a.isdigit() and b.isdigit()):
                raise ValueError(f"bad cron range {part!r}")
            start, end = int(a), int(b)
        elif part.isdigit():
            start = int(part)
            # Vixie cron: "N/step" means N..max/step, a bare "N" just N
            end = hi if step > 1 else start
        else:
            raise ValueError(f"unsupported cron field part {part!r}")
        if not (lo <= start <= end <= hi):
            raise ValueError(f"cron value {part!r} outside [{lo},{hi}]")
        # steps anchor at the range start (cron semantics): */5 on
        # day-of-month fires 1,6,11,... — not multiples of 5
        out.update(range(start, end + 1, step))
    return frozenset(out)


@functools.lru_cache(maxsize=1024)
def _parse_schedule(schedule: str):
    """→ (minute, hour, dom, month, dow sets, dom_star, dow_star)."""
    fields = schedule.split()
    if len(fields) != 5:
        raise ValueError(f"bad cron schedule {schedule!r}")
    parsed = [
        _parse_field(f, lo, hi) for f, (lo, hi) in zip(fields, _FIELD_RANGES)
    ]
    # day-of-week: both 0 and 7 mean Sunday
    dow = set(parsed[4])
    if 7 in dow:
        dow.discard(7)
        dow.add(0)
    parsed[4] = frozenset(dow)
    # standard cron: when BOTH dom and dow are restricted, a day matches if
    # EITHER does (Vixie + robfig/cron, which the reference controller uses)
    dom_star = fields[2] == "*"
    dow_star = fields[4] == "*"
    return (*parsed, dom_star, dow_star)


def _day_matches(dom_set, dow_set, dom_star, dow_star, tm) -> bool:
    cron_dow = (tm.tm_wday + 1) % 7  # cron: 0=Sunday; tm_wday: 0=Monday
    dom_ok = tm.tm_mday in dom_set
    dow_ok = cron_dow in dow_set
    if not dom_star and not dow_star:
        return dom_ok or dow_ok
    return dom_ok and dow_ok


def cron_due(schedule: str, t: float) -> bool:
    """True when wall-clock minute `t` matches the 5-field schedule."""
    minute, hour, dom, month, dow, dom_star, dow_star = _parse_schedule(schedule)
    tm = _time.gmtime(t)
    return (tm.tm_min in minute and tm.tm_hour in hour
            and tm.tm_mon in month
            and _day_matches(dom, dow, dom_star, dow_star, tm))


def next_due(schedule: str, after: float,
             horizon_s: int = 5 * 366 * 24 * 3600) -> float | None:
    """First minute boundary strictly after `after` matching the schedule.

    Walks DAYS for the date fields and picks from the minute/hour sets
    directly, so even a once-every-4-years schedule (Feb 29) costs a few
    thousand iterations, not millions of per-minute gmtime calls."""
    minute, hour, dom, month, dow, dom_star, dow_star = _parse_schedule(schedule)
    minutes = sorted(minute)
    hours = sorted(hour)
    t = (int(after) // 60 + 1) * 60
    end = after + horizon_s
    day_start = t - (t % 86400)
    while day_start <= end:
        tm = _time.gmtime(day_start)
        if tm.tm_mon in month and _day_matches(dom, dow, dom_star, dow_star, tm):
            for h in hours:
                for m in minutes:
                    cand = day_start + h * 3600 + m * 60
                    if cand >= t:
                        return float(cand)
        day_start += 86400
    return None


class CronJobController(Controller):
    name = "cronjob"
    watches = ("CronJob", "Job")

    clocked_queue = True  # schedule-time self-requeues ride the clock

    def key_of(self, kind: str, obj) -> str | None:
        if kind == "CronJob":
            return obj.meta.key
        for ref in obj.meta.owner_references:
            if ref.kind == "CronJob" and ref.controller:
                return f"{obj.meta.namespace}/{ref.name}"
        return None

    def sweep(self) -> None:
        """Re-enqueue every cronjob (tests / recovery; steady state relies
        on the schedule-time self-requeue below)."""
        for cj in self.store.iter_kind("CronJob"):
            self.queue.add(cj.meta.key)

    def reconcile(self, key: str) -> None:
        cj = self.store.try_get("CronJob", key)
        if cj is None:
            return
        owned = [j for j in self.store.iter_kind("Job")
                 if j.meta.namespace == cj.meta.namespace
                 and any(r.kind == "CronJob" and r.name == cj.meta.name
                         and r.controller for r in j.meta.owner_references)]
        active = [j for j in owned if not j.status.completed
                  and j.status.failed <= j.spec.backoff_limit]
        self._gc_history(cj, owned)
        changed = self._update_active(cj, active)
        if cj.spec.suspend:
            if changed:
                self.store.update(cj, check_version=False)
            return
        now = self.clock.now()
        fired, last_tick = self._due_time(cj, now)
        if fired is None:
            if last_tick is not None and (
                cj.status.last_schedule_time or 0
            ) < last_tick:
                # too late to start (deadline) — the tick is SPENT, or the
                # scan would rewalk it every reconcile forever
                cj.status.last_schedule_time = last_tick
                changed = True
            if changed:
                self.store.update(cj, check_version=False)
            self._requeue_at_next_tick(cj, now)
            return
        if cj.spec.concurrency_policy == "Forbid" and active:
            # defer WITHOUT stamping: when the running job finishes, its
            # Job event re-reconciles this cronjob and the missed run
            # starts if still inside the starting deadline (reference
            # behavior; a stamped tick would be lost forever)
            if changed:
                self.store.update(cj, check_version=False)
            self._requeue_at_next_tick(cj, now)
            return
        if cj.spec.concurrency_policy == "Replace":
            for j in active:
                self.store.try_delete("Job", j.meta.key)
            active = []
        job = self._mint_job(cj, fired)
        self.store.create(job)
        cj.status.last_schedule_time = fired
        cj.status.active = tuple(j.meta.key for j in active) + (job.meta.key,)
        self.store.update(cj, check_version=False)
        self._requeue_at_next_tick(cj, now)

    # -- helpers -------------------------------------------------------------

    def _requeue_at_next_tick(self, cj: CronJob, now: float) -> None:
        nd = next_due(cj.spec.schedule, now)
        if nd is not None:
            self.queue.add_after(cj.meta.key, nd - now + 0.5)

    def _due_time(self, cj: CronJob, now: float) -> tuple[float | None, float | None]:
        """(tick to fire now | None, most recent tick ≤ now | None).

        Scans forward from last_schedule_time, capped at MAX_MISSED_STARTS
        (the reference gives up similarly); past the cap the scan restarts
        from a recent window so a long-suspended cronjob costs O(1)."""
        last = cj.status.last_schedule_time
        start = last if last is not None else (
            cj.meta.creation_timestamp or now - 60
        )
        fired = None
        due = next_due(cj.spec.schedule, start)
        for _ in range(MAX_MISSED_STARTS):
            if due is None or due > now:
                break
            fired = due
            due = next_due(cj.spec.schedule, due)
        else:
            if due is not None and due <= now:
                # too many missed starts: rescan only the last hour
                fired = None
                due = next_due(cj.spec.schedule, now - 3600)
                for _ in range(61):
                    if due is None or due > now:
                        break
                    fired = due
                    due = next_due(cj.spec.schedule, due)
        if fired is None:
            return None, None
        deadline = cj.spec.starting_deadline_seconds
        if deadline is not None and now - fired > deadline:
            return None, fired
        return fired, fired

    def _mint_job(self, cj: CronJob, due: float) -> Job:
        import copy

        return Job(
            meta=ObjectMeta(
                name=f"{cj.meta.name}-{int(due) // 60}",
                namespace=cj.meta.namespace,
                labels=dict(cj.spec.job_template.template.labels),
                owner_references=[OwnerReference(
                    kind="CronJob", name=cj.meta.name,
                    uid=cj.meta.uid or new_uid(), controller=True,
                )],
            ),
            spec=copy.deepcopy(cj.spec.job_template),
        )

    def _update_active(self, cj: CronJob, active: list[Job]) -> bool:
        want = tuple(sorted(j.meta.key for j in active))
        if tuple(sorted(cj.status.active)) != want:
            cj.status.active = want
            return True
        return False

    def _gc_history(self, cj: CronJob, owned: list[Job]) -> None:
        done = sorted(
            (j for j in owned if j.status.completed),
            key=lambda j: j.status.completion_time or 0,
        )
        failed = sorted(
            (j for j in owned if not j.status.completed
             and j.status.failed > j.spec.backoff_limit),
            key=lambda j: j.meta.creation_timestamp,
        )
        for j in done[: max(0, len(done) - cj.spec.successful_jobs_history_limit)]:
            self.store.try_delete("Job", j.meta.key)
        for j in failed[: max(0, len(failed) - cj.spec.failed_jobs_history_limit)]:
            self.store.try_delete("Job", j.meta.key)
