"""Controller manager layer (cmd/kube-controller-manager + pkg/controller)."""

from .base import Controller, ControllerManager
from .cronjob import CronJobController
from .disruption import DisruptionController
from .hpa import HPAController
from .quota import QuotaController, quota_admission
from .serviceaccount import ServiceAccountController
from .volume import PersistentVolumeController
from .lifecycle import (
    EndpointSliceController,
    GarbageCollector,
    PodGCController,
    NamespaceController,
    NodeLifecycleController,
    ResourceClaimController,
    TTLAfterFinishedController,
)
from .workloads import (
    DaemonSetController,
    DeploymentController,
    JobController,
    ReplicaSetController,
    StatefulSetController,
)


def default_controllers(store, clock=None, ca_cert: str = "",
                        ca_key: str = "") -> list[Controller]:
    """The controller set kube-controller-manager starts by default, all on
    ONE shared informer factory (SharedInformerFactory semantics — each kind
    gets a single watch + cache, fanned out to every controller). The CSR
    signing controller joins only when the cluster CA is provided (the
    reference gates it on --cluster-signing-cert-file the same way)."""
    from ..client.informer import InformerFactory
    from .attachdetach import AttachDetachController
    from .certificates import CSRApprovingController, CSRSigningController
    from .devicetainteviction import DeviceTaintEvictionController

    informers = InformerFactory(store)
    out = [
        AttachDetachController(store, informers),
        CSRApprovingController(store, informers),
        DeviceTaintEvictionController(store, informers),
    ]
    if ca_cert:
        out.append(CSRSigningController(store, informers,
                                        ca_cert=ca_cert, ca_key=ca_key))
    return out + [
        DeploymentController(store, informers),
        ReplicaSetController(store, informers),
        JobController(store, informers, clock=clock),
        GarbageCollector(store, informers),
        NodeLifecycleController(store, informers, clock=clock),
        ResourceClaimController(store, informers),
        EndpointSliceController(store, informers),
        DisruptionController(store, informers),
        StatefulSetController(store, informers),
        DaemonSetController(store, informers),
        NamespaceController(store, informers),
        TTLAfterFinishedController(store, informers, clock=clock),
        CronJobController(store, informers, clock=clock),
        HPAController(store, informers, clock=clock),
        QuotaController(store, informers),
        PodGCController(store, informers),
        PersistentVolumeController(store, informers),
        ServiceAccountController(store, informers),
    ]


__all__ = [
    "Controller", "ControllerManager", "CronJobController",
    "DaemonSetController",
    "DeploymentController", "DisruptionController",
    "EndpointSliceController", "GarbageCollector", "PodGCController", "HPAController",
    "JobController",
    "NamespaceController", "NodeLifecycleController",
    "QuotaController", "ReplicaSetController", "ResourceClaimController",
    "PersistentVolumeController", "ServiceAccountController",
    "StatefulSetController", "TTLAfterFinishedController",
    "default_controllers", "quota_admission",
]
