"""Controller manager layer (cmd/kube-controller-manager + pkg/controller)."""

from .base import Controller, ControllerManager
from .disruption import DisruptionController
from .lifecycle import (
    EndpointSliceController,
    GarbageCollector,
    NodeLifecycleController,
    ResourceClaimController,
)
from .workloads import (
    DaemonSetController,
    DeploymentController,
    JobController,
    ReplicaSetController,
    StatefulSetController,
)


def default_controllers(store, clock=None) -> list[Controller]:
    """The controller set kube-controller-manager starts by default, all on
    ONE shared informer factory (SharedInformerFactory semantics — each kind
    gets a single watch + cache, fanned out to every controller)."""
    from ..client.informer import InformerFactory

    informers = InformerFactory(store)
    return [
        DeploymentController(store, informers),
        ReplicaSetController(store, informers),
        JobController(store, informers),
        GarbageCollector(store, informers),
        NodeLifecycleController(store, informers, clock=clock),
        ResourceClaimController(store, informers),
        EndpointSliceController(store, informers),
        DisruptionController(store, informers),
        StatefulSetController(store, informers),
        DaemonSetController(store, informers),
    ]


__all__ = [
    "Controller", "ControllerManager", "DaemonSetController",
    "DeploymentController", "DisruptionController",
    "EndpointSliceController", "GarbageCollector", "JobController",
    "NodeLifecycleController", "ReplicaSetController",
    "ResourceClaimController", "StatefulSetController",
    "default_controllers",
]
