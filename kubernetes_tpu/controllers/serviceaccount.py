"""ServiceAccount controller: a "default" account in every namespace.

Reference: pkg/controller/serviceaccount/serviceaccounts_controller.go —
ensures each active namespace carries the default ServiceAccount so pods
(whose spec.serviceAccountName is admission-defaulted to "default") always
resolve an identity. Recreates it if deleted; skips terminating
namespaces."""

from __future__ import annotations

from ..api.rbac import ServiceAccount
from .base import Controller


class ServiceAccountController(Controller):
    name = "serviceaccount"
    watches = ("Namespace", "ServiceAccount")

    def key_of(self, kind: str, obj) -> str | None:
        if kind == "Namespace":
            return obj.meta.name
        # a deleted/changed default SA reconciles its namespace
        return obj.meta.namespace if obj.meta.name == "default" else None

    def reconcile(self, namespace: str) -> None:
        ns = self.store.try_get("Namespace", namespace)
        if ns is None or ns.meta.deletion_timestamp is not None:
            return
        if self.store.try_get("ServiceAccount",
                              f"{namespace}/default") is None:
            sa = ServiceAccount()
            sa.meta.name = "default"
            sa.meta.namespace = namespace
            from ..store.store import AlreadyExistsError

            try:
                self.store.create(sa)
            except AlreadyExistsError:
                pass
