"""Device taint eviction controller.

Reference: pkg/controller/devicetainteviction/ (KEP-5055) — watches
ResourceSlices, ResourceClaims and Pods; when a device acquires a
NoExecute taint, every pod whose allocated claim holds that device (and
does not tolerate the taint) is evicted, and the claim is deallocated so
the scheduler can re-allocate it onto untainted devices. The allocation
half of the feature (NoSchedule/NoExecute keeping NEW allocations off
tainted devices) lives in the DRA allocator.
"""

from __future__ import annotations

from ..api.dra import NO_EXECUTE, untolerated_taints
from .base import Controller

_CLUSTER = "cluster"


class DeviceTaintEvictionController(Controller):
    name = "devicetainteviction"
    watches = ("ResourceSlice", "ResourceClaim", "Pod")

    def key_of(self, kind: str, obj) -> str | None:
        # taints/claims interact cluster-wide; one desired-state pass
        return _CLUSTER

    def _noexec_taints(self) -> dict[tuple[str, str, str], tuple]:
        """(driver, scoped pool, device) -> its NoExecute taints. Pool
        names use the allocator's node scoping (<node>/<pool> for
        node-local slices) so keys match AllocationResult entries."""
        out: dict[tuple[str, str, str], tuple] = {}
        for sl in self.store.list_refs("ResourceSlice"):
            pool = sl.pool if sl.all_nodes else f"{sl.node_name}/{sl.pool}"
            for dev in sl.devices:
                ts = tuple(t for t in dev.taints if t.effect == NO_EXECUTE)
                if ts:
                    out[(sl.driver, pool, dev.name)] = ts
        return out

    @staticmethod
    def _tolerations_by_result(claim) -> dict[str, tuple]:
        """AllocationResult request name -> that request's tolerations.
        Matching is PER REQUEST, like the allocator's: one request's
        toleration must not shield a device allocated for another (the
        result name of a prioritized-list winner is <request>/<sub>)."""
        out: dict[str, tuple] = {}
        for req in claim.spec.requests:
            out[req.name] = tuple(req.tolerations)
            for sub in req.first_available:
                out[f"{req.name}/{sub.name}"] = tuple(sub.tolerations)
        return out

    def reconcile(self, key: str) -> None:
        from ..store.store import NotFoundError

        tainted = self._noexec_taints()
        if not tainted:
            return
        for ref in self.store.list_refs("ResourceClaim"):
            alloc = ref.status.allocation
            if alloc is None:
                continue
            by_req = self._tolerations_by_result(ref)
            hit = [
                t
                for d in alloc.devices
                for t in tainted.get((d.driver, d.pool, d.device), ())
                if untolerated_taints([t], by_req.get(d.request, ()),
                                      effects=(NO_EXECUTE,))
            ]
            if not hit:
                continue
            # evict every consumer (the reference deletes the pods), then
            # deallocate so the claim can land on untainted devices
            for pod_key in ref.status.reserved_for:
                try:
                    self.store.delete("Pod", pod_key)
                except NotFoundError:
                    pass
            claim = self.store.get("ResourceClaim", ref.meta.key)
            claim.status.allocation = None
            claim.status.reserved_for = ()
            self.store.update(claim, check_version=False)
