"""Disruption controller: maintains PodDisruptionBudget status.

Reference: pkg/controller/disruption/disruption.go — for every PDB, count
matching healthy pods and publish how many voluntary disruptions the budget
still allows (DisruptionsAllowed). The scheduler's preemption engine reads
ONLY the published status (default_preemption.go:380
filterPodsWithPDBViolation) — this controller is what makes that status
true. DisruptedPods entries record evictions already processed so a slow
cache never double-counts a disruption; stale entries (older than the
2-minute timeout the reference uses) are dropped.
"""

from __future__ import annotations

import time

from ..api.types import PodDisruptionBudget
from .base import Controller

# disruption.go DeletionTimeout: an eviction recorded in DisruptedPods that
# never turned into a delete stops counting against the budget
DISRUPTED_POD_TIMEOUT_S = 120.0


class DisruptionController(Controller):
    name = "disruption"
    watches = ("PodDisruptionBudget", "Pod")

    def _make_handler(self, kind: str):
        if kind != "Pod":
            return super()._make_handler(kind)

        def handler(etype, old, new):
            # BOTH the old and new pod shapes matter: a relabel that stops
            # matching a PDB must still re-reconcile that PDB (its healthy
            # count just dropped) — matching only the new labels would
            # leave disruptions_allowed overstated forever
            for obj in (old, new):
                if obj is not None:
                    self._enqueue_matching_pdbs(obj)

        return handler

    def _enqueue_matching_pdbs(self, pod) -> None:
        """getPdbForPod: every same-namespace PDB whose selector matches."""
        for pdb in self.store.iter_kind("PodDisruptionBudget"):
            if pdb.meta.namespace != pod.meta.namespace:
                continue
            sel = pdb.spec.selector
            if sel is not None and sel.matches(pod.meta.labels):
                self.queue.add(pdb.meta.key)

    def key_of(self, kind: str, obj) -> str | None:
        # only PDB events reach the base handler ("Pod" has its own above)
        return obj.meta.key

    def reconcile(self, key: str) -> None:
        pdb = self.store.try_get("PodDisruptionBudget", key)
        if pdb is None:
            return
        sel = pdb.spec.selector
        matching = []
        if sel is not None:
            for pod in self.store.pods():
                if (pod.meta.namespace == pdb.meta.namespace
                        and sel.matches(pod.meta.labels)):
                    matching.append(pod)
        expected = len(matching)
        # healthy = running (bound) and not terminating (disruption.go
        # countHealthyPods; we have no readiness, bound is our "healthy")
        healthy = sum(1 for p in matching
                      if p.spec.node_name and not p.is_terminating)
        if pdb.spec.min_available is not None:
            desired = min(pdb.spec.min_available, expected)
        elif pdb.spec.max_unavailable is not None:
            desired = max(expected - pdb.spec.max_unavailable, 0)
        else:
            desired = expected  # no budget field: nothing may be disrupted
        now = time.time()
        disrupted = {
            name: ts for name, ts in pdb.status.disrupted_pods.items()
            if now - ts < DISRUPTED_POD_TIMEOUT_S
            and any(p.meta.name == name for p in matching)
        }
        allowed = max(healthy - desired - len(disrupted), 0)
        st = pdb.status
        if (st.disruptions_allowed == allowed and st.current_healthy == healthy
                and st.desired_healthy == desired and st.expected_pods == expected
                and st.disrupted_pods == disrupted):
            return
        st.disruptions_allowed = allowed
        st.current_healthy = healthy
        st.desired_healthy = desired
        st.expected_pods = expected
        st.disrupted_pods = disrupted
        self.store.update(pdb, check_version=False)


__all__ = ["DisruptionController", "PodDisruptionBudget"]
