"""Lifecycle controllers: garbage collection, node lifecycle, taint eviction,
resource-claim cleanup, endpoint slices.

Reference: pkg/controller/garbagecollector/ (ownerReference cascade),
pkg/controller/nodelifecycle/node_lifecycle_controller.go (Lease-staleness ->
NotReady + unreachable taint), pkg/controller/tainteviction/,
pkg/controller/resourceclaim/, pkg/controller/endpointslice/.
"""

from __future__ import annotations

from ..api.labels import labels_subset
from ..api.types import NO_EXECUTE, NodeCondition, Taint
from ..api.workloads import Endpoint, EndpointSlice
from ..api.meta import ObjectMeta
from ..utils import faultinject
from .base import Controller

UNREACHABLE_TAINT = "node.kubernetes.io/unreachable"

# kinds the GC walks (an informer per watched kind; the reference discovers
# these dynamically via the RESTMapper)
GC_KINDS = ("Pod", "ReplicaSet", "Deployment", "Job", "PersistentVolumeClaim",
            "ResourceClaim", "EndpointSlice")


class GarbageCollector(Controller):
    """garbagecollector — delete objects whose controller owner is gone.

    The reference builds a dependency graph; with the store's cheap listing
    the same effect comes from checking each dependent's owners on events.
    """

    name = "garbage-collector"
    watches = GC_KINDS

    def key_of(self, kind: str, obj) -> str | None:
        if not obj.meta.owner_references:
            return None
        return f"{kind}|{obj.meta.key}"

    def _owner_exists(self, namespace: str, ref) -> bool:
        key = f"{namespace}/{ref.name}" if namespace else ref.name
        owner = self.store.try_get(ref.kind, key)
        return owner is not None and (not ref.uid or owner.meta.uid == ref.uid)

    def reconcile(self, key: str) -> None:
        kind, _, obj_key = key.partition("|")
        obj = self.store.try_get(kind, obj_key)
        if obj is None:
            return
        refs = obj.meta.owner_references
        if refs and not any(self._owner_exists(obj.meta.namespace, r) for r in refs):
            self.store.try_delete(kind, obj_key)

    def sweep(self) -> int:
        """Full-resync mark pass (the reference's graph rebuild on sync)."""
        n = 0
        for kind in GC_KINDS:
            for obj in list(self.store.iter_kind(kind)):
                if obj.meta.owner_references:
                    self.queue.add(f"{kind}|{obj.meta.key}")
                    n += 1
        return n


class PodGCController(Controller):
    """podgc (pkg/controller/podgc/gc_controller.go) — three sweeps:

    - ORPHANED pods: bound to a node that no longer exists → delete (the
      kubelet that would run them is gone, nothing else will clean up);
    - TERMINATED pods beyond `terminated_threshold`: oldest finished pods
      deleted first, keeping the newest threshold-many (the reference's
      --terminated-pod-gc-threshold, default 12500);
    - UNSCHEDULED terminating pods: deleted immediately (no kubelet will
      ever finalize them).
    """

    name = "pod-gc"
    watches = ("Pod", "Node")
    TERMINATED_THRESHOLD = 12500

    def __init__(self, store, informers=None, clock=None,
                 terminated_threshold: int | None = None):
        super().__init__(store, informers, clock=clock)
        self.terminated_threshold = (
            self.TERMINATED_THRESHOLD if terminated_threshold is None
            else terminated_threshold)

    def key_of(self, kind: str, obj) -> str | None:
        # any pod/node event triggers one global sweep (the reference runs
        # gc() on a 20s period; event-driven is strictly fresher)
        return "sweep"

    def reconcile(self, key: str) -> None:
        from ..api.types import FAILED, SUCCEEDED

        nodes = {n.meta.name for n in self.store.nodes()}
        terminated = []
        for p in list(self.store.pods()):
            phase = p.status.phase
            if p.spec.node_name and p.spec.node_name not in nodes:
                self.store.try_delete("Pod", p.meta.key)  # orphaned
            elif p.is_terminating and not p.spec.node_name:
                self.store.try_delete("Pod", p.meta.key)  # never ran
            elif phase in (SUCCEEDED, FAILED):
                terminated.append(p)
        excess = len(terminated) - self.terminated_threshold
        if excess > 0:
            terminated.sort(key=lambda p: p.meta.creation_timestamp)
            for p in terminated[:excess]:
                self.store.try_delete("Pod", p.meta.key)


class NodeLifecycleController(Controller):
    """node_lifecycle_controller.go — Lease-staleness drives Ready condition
    and the unreachable NoExecute taint; pods on unreachable nodes are
    evicted (tainteviction collapsed in, as the reference does when
    TaintBasedEvictions became the only path)."""

    name = "node-lifecycle"
    watches = ("Node", "Lease")
    grace_period = 40.0  # node-monitor-grace-period default
    clocked_queue = True  # staleness monitoring self-requeues

    def key_of(self, kind: str, obj) -> str | None:
        if kind == "Lease":
            if obj.meta.namespace != "kube-node-lease":
                return None
            return obj.meta.name
        return obj.meta.name

    def _lease_fresh(self, node_name: str) -> bool:
        lease = self.store.try_get("Lease", f"kube-node-lease/{node_name}")
        if lease is None:
            return False
        return self.clock.now() - lease.spec.renew_time < self.grace_period

    def reconcile(self, key: str) -> None:
        # chaos: the node-health monitor itself degrades — ERROR rides the
        # base class's backoff requeue and DROP skips one pass but keeps
        # the monitor's self-requeue alive; either way tainting/eviction
        # is DELAYED, never abandoned
        if faultinject.fire("controller.lifecycle"):
            self.queue.add_after(key, max(self.grace_period / 2, 0.2))
            return
        node = self.store.try_get("Node", key)
        if node is None:
            return
        fresh = self._lease_fresh(key)
        ready = next(
            (c for c in node.status.conditions if c.type == "Ready"), None
        )
        changed = False
        if ready is None:
            ready = NodeCondition(type="Ready", status="Unknown")
            node.status.conditions.append(ready)
            changed = True
        want_status = "True" if fresh else "Unknown"
        if ready.status != want_status:
            ready.status = want_status
            changed = True
        has_taint = any(t.key == UNREACHABLE_TAINT for t in node.spec.taints)
        if not fresh and not has_taint:
            node.spec.taints = tuple(node.spec.taints) + (
                Taint(key=UNREACHABLE_TAINT, effect=NO_EXECUTE),
            )
            changed = True
        elif fresh and has_taint:
            node.spec.taints = tuple(
                t for t in node.spec.taints if t.key != UNREACHABLE_TAINT
            )
            changed = True
        if changed:
            self.store.update(node, check_version=False)
        if not fresh:
            self._evict_pods(key)
        # continuous health monitoring (the reference's monitorNodeHealth
        # 5s poll): a DEAD kubelet emits no further lease events, so the
        # controller must wake itself to observe the staleness
        self.queue.add_after(key, max(self.grace_period / 2, 0.2))

    def _evict_pods(self, node_name: str) -> None:
        """tainteviction — NoExecute evicts pods lacking a matching
        toleration (tolerationSeconds treated as immediate at reconcile)."""
        taint = Taint(key=UNREACHABLE_TAINT, effect=NO_EXECUTE)
        for pod in self.store.pods():
            if pod.spec.node_name != node_name:
                continue
            if any(tol.tolerates(taint) for tol in pod.spec.tolerations):
                continue
            self.store.try_delete("Pod", pod.meta.key)

    def sweep(self) -> None:
        for node in self.store.nodes():
            self.queue.add(node.meta.name)


class ResourceClaimController(Controller):
    """resourceclaim controller — drop reservedFor entries of deleted pods;
    deallocate a claim once nothing reserves it (allowing reuse)."""

    name = "resourceclaim"
    watches = ("ResourceClaim", "Pod")

    def key_of(self, kind: str, obj) -> str | None:
        if kind == "ResourceClaim":
            return obj.meta.key
        # pod deletions may strand reservations on any claim it referenced
        from ..api.dra import pod_resource_claim_keys

        keys = pod_resource_claim_keys(obj)
        for k in keys[1:]:
            self.queue.add(k)
        return keys[0] if keys else None

    def reconcile(self, key: str) -> None:
        claim = self.store.try_get("ResourceClaim", key)
        if claim is None:
            return
        live = tuple(
            pod_key for pod_key in claim.status.reserved_for
            if self.store.try_get("Pod", pod_key) is not None
        )
        if live != claim.status.reserved_for:
            claim.status.reserved_for = live
            if not live:
                claim.status.allocation = None  # deallocate idle claim
            self.store.update(claim, check_version=False)


class EndpointSliceController(Controller):
    """endpointslice controller — slices per Service tracking ready
    running pods matching the selector, chunked at MAX_ENDPOINTS per slice
    (discovery/v1's maxEndpointsPerSlice default 100: watch fan-out stays
    bounded when a service has thousands of backends)."""

    name = "endpointslice"
    watches = ("Service", "Pod")
    MAX_ENDPOINTS = 100

    def key_of(self, kind: str, obj) -> str | None:
        if kind == "Service":
            return obj.meta.key
        # pods map back to services by label match
        for svc in self.store.iter_kind("Service"):
            if (svc.meta.namespace == obj.meta.namespace
                    and svc.spec.selector
                    and labels_subset(svc.spec.selector, obj.meta.labels)):
                self.queue.add(svc.meta.key)
        return None

    def _owned_slices(self, namespace: str, svc_name: str) -> list:
        return [
            s for s in self.store.iter_kind("EndpointSlice")
            if s.meta.namespace == namespace and s.service_name == svc_name
        ]

    def reconcile(self, key: str) -> None:
        ns, _, svc_name = key.partition("/")
        svc = self.store.try_get("Service", key)
        if svc is None:
            for s in self._owned_slices(ns, svc_name):
                self.store.try_delete("EndpointSlice", s.meta.key)
            return
        from ..api.types import RUNNING

        def pod_ip(p) -> str:
            # prefer the kubelet-reported address; otherwise a stable
            # per-pod address derived from its uid (stable across
            # processes, unlike salted hash()) — churn elsewhere in the
            # cluster must not rewrite this slice's endpoints
            if p.status.pod_ip:
                return p.status.pod_ip
            from ..utils.net import stable_pod_ip

            return stable_pod_ip(p.meta.uid or p.meta.key)

        def pod_ready(p) -> bool:
            # pod readiness = Running AND the kubelet-reported Ready
            # condition isn't False (readiness probes gate it)
            if p.status.phase != RUNNING:
                return False
            cond = next((c for c in p.status.conditions
                         if c.type == "Ready"), None)
            return cond is None or cond.status != "False"

        endpoints = tuple(
            Endpoint(
                addresses=(pod_ip(p),),
                node_name=p.spec.node_name,
                # discovery/v1 conditions: a deleting pod stops being
                # "ready" but keeps "serving" while it still runs, so the
                # proxy's terminating fallback has real producers
                ready=(pod_ready(p)
                       and p.meta.deletion_timestamp is None),
                serving=pod_ready(p),
                terminating=p.meta.deletion_timestamp is not None,
                target_pod=p.meta.key,
            )
            for p in self.store.pods()
            if p.meta.namespace == svc.meta.namespace
            and p.spec.node_name
            and svc.spec.selector
            and labels_subset(svc.spec.selector, p.meta.labels)
        )
        # chunk into slices of MAX_ENDPOINTS (stable order so chunks only
        # churn where membership actually changed)
        ordered = sorted(endpoints, key=lambda e: e.target_pod)
        chunks = [tuple(ordered[i:i + self.MAX_ENDPOINTS])
                  for i in range(0, len(ordered), self.MAX_ENDPOINTS)] or [()]
        want_names = {f"{svc.meta.name}-endpoints-{i}" if i else
                      f"{svc.meta.name}-endpoints"
                      for i in range(len(chunks))}
        for s in self._owned_slices(svc.meta.namespace, svc.meta.name):
            if s.meta.name not in want_names:
                self.store.try_delete("EndpointSlice", s.meta.key)
        for i, chunk in enumerate(chunks):
            name = (f"{svc.meta.name}-endpoints-{i}" if i
                    else f"{svc.meta.name}-endpoints")
            existing = self.store.try_get(
                "EndpointSlice", f"{svc.meta.namespace}/{name}"
            )
            if existing is None:
                self.store.create(EndpointSlice(
                    meta=ObjectMeta(name=name, namespace=svc.meta.namespace),
                    service_name=svc.meta.name,
                    endpoints=chunk,
                    ports=svc.spec.ports,
                ))
            elif (existing.endpoints != chunk
                  or existing.ports != svc.spec.ports):
                existing.endpoints = chunk
                existing.ports = svc.spec.ports
                self.store.update(existing, check_version=False)


class NamespaceController(Controller):
    """namespace lifecycle controller — pkg/controller/namespace: a
    Namespace marked for deletion drains every namespaced object it holds
    (the "content deleter" walking discovered resources), then the
    namespace object itself goes away. Phase mirrors the reference:
    Active → Terminating (deletion_timestamp set) → gone."""

    name = "namespace"
    watches = ("Namespace",)

    @staticmethod
    def drain_kinds() -> list[str]:
        """Every namespaced kind from the registry (the reference's
        discovery-driven content deleter), workload owners first and pods
        last so controllers don't resurrect pods mid-drain. Derived, not
        hand-listed: a new namespaced kind is drained automatically."""
        from ..apiserver.discovery import CLUSTER_SCOPED, all_kinds

        first = ["Deployment", "StatefulSet", "DaemonSet", "ReplicaSet",
                 "Job"]
        last = ["Pod"]
        rest = sorted(k for k in all_kinds()
                      if k not in CLUSTER_SCOPED
                      and k not in first and k not in last)
        return first + rest + last

    def reconcile(self, key: str) -> None:
        ns = self.store.try_get("Namespace", key)
        if ns is None:
            return
        if ns.meta.deletion_timestamp is None:
            return
        if ns.phase != "Terminating":
            ns.phase = "Terminating"
            self.store.update(ns, check_version=False)
        name = ns.meta.name
        remaining = 0
        for kind in self.drain_kinds():
            for obj in self.store.iter_kind(kind):
                if obj.meta.namespace != name:
                    continue
                remaining += 1
                self.store.try_delete(kind, obj.meta.key)
        if remaining:
            # deletes cascade through other controllers/kubelets; re-check
            self.queue.add(key)
            return
        self.store.try_delete("Namespace", key)


class TTLAfterFinishedController(Controller):
    """ttl-after-finished controller — pkg/controller/ttlafterfinished:
    deletes finished Jobs ttlSecondsAfterFinished after completion. Jobs
    whose TTL hasn't elapsed yet are requeued (the reference enqueues with
    a delay; our workqueue re-add plays that role via periodic syncs)."""

    name = "ttlafterfinished"
    watches = ("Job",)

    clocked_queue = True  # TTL-expiry self-requeues ride the clock

    def reconcile(self, key: str) -> None:
        job = self.store.try_get("Job", key)
        if job is None:
            return
        ttl = job.spec.ttl_seconds_after_finished
        if ttl is None or not job.status.completed:
            return
        done_at = job.status.completion_time
        if done_at is None:
            return
        remaining = ttl - (self.clock.now() - done_at)
        if remaining <= 0:
            self.store.try_delete("Job", key)
        else:
            # delayed requeue (the reference enqueueAfter) — a plain add()
            # would busy-spin the worker for the whole TTL window
            self.queue.add_after(key, remaining)
