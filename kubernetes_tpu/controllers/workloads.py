"""Workload controllers: ReplicaSet, Deployment, Job.

Reference: pkg/controller/replicaset/replica_set.go (syncReplicaSet,
manageReplicas), pkg/controller/deployment/ (syncDeployment, rolling.go),
pkg/controller/job/job_controller.go (syncJob). Each reconciles one object
key against the pods it owns (ownerReferences-based adoption, the
ControllerRefManager pattern).
"""

from __future__ import annotations

import hashlib

from ..api.meta import ObjectMeta, OwnerReference
from ..api.types import Pod, PodSpec, SUCCEEDED, FAILED, RUNNING
from ..api.workloads import (
    Deployment,
    ReplicaSet,
    ReplicaSetSpec,
    ReplicaSetStatus,
)
from ..api.labels import LabelSelector
from ..store.store import NotFoundError
from .base import Controller


def _owned_by(obj, owner_uid: str) -> bool:
    return any(r.uid == owner_uid and r.controller for r in obj.meta.owner_references)


def _controller_ref(owner) -> OwnerReference:
    return OwnerReference(
        kind=owner.kind, name=owner.meta.name, uid=owner.meta.uid, controller=True
    )


def _clone_pod_spec(template) -> PodSpec:
    import copy

    return copy.deepcopy(template.spec)


class ReplicaSetController(Controller):
    """replica_set.go — converge owned-pod count to spec.replicas."""

    name = "replicaset"
    watches = ("ReplicaSet", "Pod")

    def key_of(self, kind: str, obj) -> str | None:
        if kind == "ReplicaSet":
            return obj.meta.key
        for ref in obj.meta.owner_references:
            if ref.kind == "ReplicaSet" and ref.controller:
                return f"{obj.meta.namespace}/{ref.name}"
        return None

    def _active_owned_pods(self, rs: ReplicaSet) -> list[Pod]:
        return [
            p for p in self.store.pods()
            if p.meta.namespace == rs.meta.namespace
            and _owned_by(p, rs.meta.uid)
            and p.status.phase not in (SUCCEEDED, FAILED)
            and not p.is_terminating
        ]

    def reconcile(self, key: str) -> None:
        try:
            rs = self.store.get("ReplicaSet", key)
        except NotFoundError:
            return  # GC deletes the orphans
        pods = self._active_owned_pods(rs)
        diff = rs.spec.replicas - len(pods)
        if diff > 0:
            from ..api.meta import new_uid

            for _ in range(diff):
                # generateName semantics: unique suffix, never a collision
                # with a pod that existed before (pod-template-hash pattern)
                suffix = new_uid().rsplit("-", 1)[-1]
                pod = Pod(
                    meta=ObjectMeta(
                        name=f"{rs.meta.name}-{suffix}",
                        namespace=rs.meta.namespace,
                        labels=dict(rs.spec.template.labels),
                        owner_references=[_controller_ref(rs)],
                    ),
                    spec=_clone_pod_spec(rs.spec.template),
                )
                self.store.create(pod)
        elif diff < 0:
            # scale down: prefer unscheduled, then newest (getPodsToDelete rank)
            pods.sort(key=lambda p: (bool(p.spec.node_name), -p.meta.resource_version))
            for p in pods[: -diff]:
                self.store.delete("Pod", p.meta.key)
        new_status = ReplicaSetStatus(
            replicas=max(len(pods) + diff, 0) if diff > 0 else rs.spec.replicas,
            ready_replicas=sum(1 for p in pods if p.status.phase == RUNNING),
            observed_generation=rs.meta.generation,
        )
        # status writes only on change — an unconditional update would emit a
        # MODIFIED event that re-enqueues this key forever
        if new_status != rs.status:
            rs.status = new_status
            self.store.update(rs, check_version=False)


def _template_hash(dep: Deployment) -> str:
    import json

    from ..api.serialization import encode

    payload = json.dumps(encode(dep.spec.template), sort_keys=True)
    return hashlib.sha1(payload.encode()).hexdigest()[:10]


class DeploymentController(Controller):
    """deployment controller — one ReplicaSet per template hash; template
    changes roll by scaling the new RS up and old ones to 0 (the rolling.go
    surge/maxUnavailable dance collapsed to its fixed point, which is what
    the in-process control loop converges to in one pass)."""

    name = "deployment"
    watches = ("Deployment", "ReplicaSet")

    def key_of(self, kind: str, obj) -> str | None:
        if kind == "Deployment":
            return obj.meta.key
        for ref in obj.meta.owner_references:
            if ref.kind == "Deployment" and ref.controller:
                return f"{obj.meta.namespace}/{ref.name}"
        return None

    def reconcile(self, key: str) -> None:
        try:
            dep = self.store.get("Deployment", key)
        except NotFoundError:
            return
        want_hash = _template_hash(dep)
        want_name = f"{dep.meta.name}-{want_hash}"
        owned = [
            rs for rs in self.store.iter_kind("ReplicaSet")
            if rs.meta.namespace == dep.meta.namespace and _owned_by(rs, dep.meta.uid)
        ]
        new_rs = next((rs for rs in owned if rs.meta.name == want_name), None)
        if new_rs is None:
            labels = dict(dep.spec.template.labels)
            labels["pod-template-hash"] = want_hash
            template = type(dep.spec.template)(
                labels=labels, spec=_clone_pod_spec(dep.spec.template)
            )
            new_rs = ReplicaSet(
                meta=ObjectMeta(
                    name=want_name,
                    namespace=dep.meta.namespace,
                    labels=labels,
                    owner_references=[_controller_ref(dep)],
                ),
                spec=ReplicaSetSpec(
                    replicas=dep.spec.replicas,
                    selector=LabelSelector.of(labels),
                    template=template,
                ),
            )
            self.store.create(new_rs)
        elif new_rs.spec.replicas != dep.spec.replicas:
            new_rs.spec.replicas = dep.spec.replicas
            self.store.update(new_rs, check_version=False)
        for rs in owned:
            if rs.meta.name != want_name and rs.spec.replicas != 0:
                rs.spec.replicas = 0
                self.store.update(rs, check_version=False)
        from ..api.workloads import DeploymentStatus

        new_status = DeploymentStatus(
            replicas=dep.spec.replicas,
            updated_replicas=new_rs.spec.replicas,
            ready_replicas=new_rs.status.ready_replicas,
            observed_generation=dep.meta.generation,
        )
        if new_status != dep.status:
            dep.status = new_status
            self.store.update(dep, check_version=False)


class JobController(Controller):
    """job_controller.go syncJob — run `parallelism` pods at a time until
    `completions` have succeeded."""

    name = "job"
    watches = ("Job", "Pod")

    def key_of(self, kind: str, obj) -> str | None:
        if kind == "Job":
            return obj.meta.key
        for ref in obj.meta.owner_references:
            if ref.kind == "Job" and ref.controller:
                return f"{obj.meta.namespace}/{ref.name}"
        return None

    def reconcile(self, key: str) -> None:
        try:
            job = self.store.get("Job", key)
        except NotFoundError:
            return
        owned = [
            p for p in self.store.pods()
            if p.meta.namespace == job.meta.namespace and _owned_by(p, job.meta.uid)
        ]
        succeeded = sum(1 for p in owned if p.status.phase == SUCCEEDED)
        failed = sum(1 for p in owned if p.status.phase == FAILED)
        active = [
            p for p in owned
            if p.status.phase not in (SUCCEEDED, FAILED) and not p.is_terminating
        ]
        import copy

        old_status = copy.copy(job.status)
        job.status.active = len(active)
        job.status.succeeded = succeeded
        job.status.failed = failed
        if succeeded >= job.spec.completions:
            job.status.completed = True
            for p in active:
                self.store.delete("Pod", p.meta.key)
            if job.status != old_status:
                self.store.update(job, check_version=False)
            return
        if failed > job.spec.backoff_limit:
            # terminal failure (job_controller.go syncJob BackoffLimitExceeded):
            # stop replacing pods and tear down the active ones
            for p in active:
                self.store.delete("Pod", p.meta.key)
            if job.status != old_status:
                self.store.update(job, check_version=False)
            return
        want_active = min(
            job.spec.parallelism, job.spec.completions - succeeded
        )
        from ..api.meta import new_uid

        for _ in range(want_active - len(active)):
            pod = Pod(
                meta=ObjectMeta(
                    name=f"{job.meta.name}-{new_uid().rsplit('-', 1)[-1]}",
                    namespace=job.meta.namespace,
                    labels=dict(job.spec.template.labels),
                    owner_references=[_controller_ref(job)],
                ),
                spec=_clone_pod_spec(job.spec.template),
            )
            pod.spec.restart_policy = "Never"
            self.store.create(pod)
        if job.status != old_status:
            self.store.update(job, check_version=False)
