"""Workload controllers: ReplicaSet, Deployment, Job.

Reference: pkg/controller/replicaset/replica_set.go (syncReplicaSet,
manageReplicas), pkg/controller/deployment/ (syncDeployment, rolling.go),
pkg/controller/job/job_controller.go (syncJob). Each reconciles one object
key against the pods it owns (ownerReferences-based adoption, the
ControllerRefManager pattern).
"""

from __future__ import annotations

import hashlib

from ..api.meta import ObjectMeta, OwnerReference
from ..api.types import Pod, PodSpec, SUCCEEDED, FAILED, RUNNING
from ..api.workloads import (
    Deployment,
    ReplicaSet,
    ReplicaSetSpec,
    ReplicaSetStatus,
)
from ..api.labels import LabelSelector
from ..store.store import NotFoundError
from ..utils import faultinject
from .base import Controller


def _owned_by(obj, owner_uid: str) -> bool:
    return any(r.uid == owner_uid and r.controller for r in obj.meta.owner_references)


def _controller_ref(owner) -> OwnerReference:
    return OwnerReference(
        kind=owner.kind, name=owner.meta.name, uid=owner.meta.uid, controller=True
    )


def _clone_pod_spec(template) -> PodSpec:
    import copy

    return copy.deepcopy(template.spec)


class ReplicaSetController(Controller):
    """replica_set.go — converge owned-pod count to spec.replicas."""

    name = "replicaset"
    watches = ("ReplicaSet", "Pod")

    def key_of(self, kind: str, obj) -> str | None:
        if kind == "ReplicaSet":
            return obj.meta.key
        for ref in obj.meta.owner_references:
            if ref.kind == "ReplicaSet" and ref.controller:
                return f"{obj.meta.namespace}/{ref.name}"
        return None

    def _active_owned_pods(self, rs: ReplicaSet) -> list[Pod]:
        return [
            p for p in self.store.pods()
            if p.meta.namespace == rs.meta.namespace
            and _owned_by(p, rs.meta.uid)
            and p.status.phase not in (SUCCEEDED, FAILED)
            and not p.is_terminating
        ]

    def _adopt_orphans(self, rs: ReplicaSet) -> None:
        """ControllerRefManager adoption: a selector-matching pod with no
        controller owner gains this ReplicaSet's controllerRef (so manually
        created or orphaned pods count toward replicas instead of being
        doubled up)."""
        sel = rs.spec.selector
        if sel is None or sel.empty:
            return
        for p in self.store.pods():
            if p.meta.namespace != rs.meta.namespace or p.is_terminating:
                continue
            if p.status.phase in (SUCCEEDED, FAILED):
                continue  # FilterActivePods: finished pods stay orphans
            if any(r.controller for r in p.meta.owner_references):
                continue
            if not sel.matches(p.meta.labels):
                continue
            p.meta.owner_references = list(p.meta.owner_references) + [
                _controller_ref(rs)
            ]
            self.store.update(p, check_version=False)

    def reconcile(self, key: str) -> None:
        # chaos: workload reconciles degrade — ERROR raises directly and
        # DROP is promoted to a raise, so both land on the base class's
        # rate-limited requeue: convergence is delayed, never lost
        # (replica math is re-derived from live state each run)
        if faultinject.fire("controller.workloads"):
            raise faultinject.TransientFault("controller.workloads: dropped")
        try:
            rs = self.store.get("ReplicaSet", key)
        except NotFoundError:
            return  # GC deletes the orphans
        self._adopt_orphans(rs)
        pods = self._active_owned_pods(rs)
        diff = rs.spec.replicas - len(pods)
        if diff > 0:
            from ..api.meta import new_uid

            for _ in range(diff):
                # generateName semantics: unique suffix, never a collision
                # with a pod that existed before (pod-template-hash pattern)
                suffix = new_uid().rsplit("-", 1)[-1]
                pod = Pod(
                    meta=ObjectMeta(
                        name=f"{rs.meta.name}-{suffix}",
                        namespace=rs.meta.namespace,
                        labels=dict(rs.spec.template.labels),
                        owner_references=[_controller_ref(rs)],
                    ),
                    spec=_clone_pod_spec(rs.spec.template),
                )
                self.store.create(pod)
        elif diff < 0:
            # scale down: prefer unscheduled, then newest (getPodsToDelete rank)
            pods.sort(key=lambda p: (bool(p.spec.node_name), -p.meta.resource_version))
            for p in pods[: -diff]:
                self.store.delete("Pod", p.meta.key)
        new_status = ReplicaSetStatus(
            replicas=max(len(pods) + diff, 0) if diff > 0 else rs.spec.replicas,
            ready_replicas=sum(1 for p in pods if p.status.phase == RUNNING),
            observed_generation=rs.meta.generation,
        )
        # status writes only on change — an unconditional update would emit a
        # MODIFIED event that re-enqueues this key forever
        if new_status != rs.status:
            rs.status = new_status
            self.store.update(rs, check_version=False)


REVISION_ANNOTATION = "deployment.kubernetes.io/revision"


def _template_hash(dep: Deployment) -> str:
    import json

    from ..api.serialization import encode

    payload = json.dumps(encode(dep.spec.template), sort_keys=True)
    return hashlib.sha1(payload.encode()).hexdigest()[:10]


class DeploymentController(Controller):
    """deployment controller — one ReplicaSet per template hash; template
    changes roll GRADUALLY per rolling.go: the new RS surges up to
    replicas+maxSurge total, old RSes scale down only as far as
    availability allows (available - (replicas - maxUnavailable)), so a
    roll never dips below the availability floor. Recreate tears the old
    RSes fully down before the new one scales up. "Available" uses the
    same pragmatic definition as the StatefulSet controller: scheduled and
    not terminating (Running when a kubelet reports it)."""

    name = "deployment"
    watches = ("Deployment", "ReplicaSet")

    def key_of(self, kind: str, obj) -> str | None:
        if kind == "Deployment":
            return obj.meta.key
        for ref in obj.meta.owner_references:
            if ref.kind == "Deployment" and ref.controller:
                return f"{obj.meta.namespace}/{ref.name}"
        return None

    def reconcile(self, key: str) -> None:
        if faultinject.fire("controller.workloads"):  # chaos: see ReplicaSet
            raise faultinject.TransientFault("controller.workloads: dropped")
        try:
            dep = self.store.get("Deployment", key)
        except NotFoundError:
            return
        want_hash = _template_hash(dep)
        want_name = f"{dep.meta.name}-{want_hash}"
        owned = [
            rs for rs in self.store.iter_kind("ReplicaSet")
            if rs.meta.namespace == dep.meta.namespace and _owned_by(rs, dep.meta.uid)
        ]
        new_rs = next((rs for rs in owned if rs.meta.name == want_name), None)
        if dep.spec.paused:
            # rollout paused (syncDeployment's paused branch): no new RS,
            # no rolling — but pure scaling still applies: distribute the
            # TOTAL-vs-desired delta across live RSes newest-first (clamped
            # at 0), so a mid-roll pause keeps sum(replicas) == desired
            # instead of inflating the largest RS to desired on its own
            by_newest = sorted(
                owned,
                key=lambda r: int(
                    r.meta.annotations.get(REVISION_ANNOTATION, 0)),
                reverse=True,
            )
            delta = dep.spec.replicas - sum(r.spec.replicas for r in by_newest)
            for rs in by_newest:
                if delta == 0:
                    break
                step = max(delta, -rs.spec.replicas)
                if step:
                    rs.spec.replicas += step
                    self.store.update(rs, check_version=False)
                    delta -= step
            self._write_status(dep, new_rs, owned)
            return
        if new_rs is None:
            labels = dict(dep.spec.template.labels)
            labels["pod-template-hash"] = want_hash
            template = type(dep.spec.template)(
                labels=labels, spec=_clone_pod_spec(dep.spec.template)
            )
            # revision bookkeeping (deployment.kubernetes.io/revision):
            # each new template gets the next revision number; old RSes
            # stay (scaled to 0) as rollback targets
            next_rev = 1 + max(
                (int(rs.meta.annotations.get(REVISION_ANNOTATION, 0))
                 for rs in owned),
                default=0,
            )
            new_rs = ReplicaSet(
                meta=ObjectMeta(
                    name=want_name,
                    namespace=dep.meta.namespace,
                    labels=labels,
                    annotations={REVISION_ANNOTATION: str(next_rev)},
                    owner_references=[_controller_ref(dep)],
                ),
                spec=ReplicaSetSpec(
                    replicas=0,  # the rolling step below surges it up
                    selector=LabelSelector.of(labels),
                    template=template,
                ),
            )
            self.store.create(new_rs)
            if dep.meta.annotations.get(REVISION_ANNOTATION) != str(next_rev):
                dep.meta.annotations[REVISION_ANNOTATION] = str(next_rev)
                self.store.update(dep, check_version=False)
        else:
            # rolling BACK to an existing RS (rollout undo): the reference
            # bumps that RS to a fresh max revision, so history shows the
            # rollback as a new step and a second undo returns to where we
            # came from — a stale annotation would make undo a no-op
            max_rev = max(
                (int(rs.meta.annotations.get(REVISION_ANNOTATION, 0))
                 for rs in owned),
                default=0,
            )
            cur_rev = int(new_rs.meta.annotations.get(REVISION_ANNOTATION, 0))
            if cur_rev < max_rev:
                new_rev = str(max_rev + 1)
                new_rs.meta.annotations[REVISION_ANNOTATION] = new_rev
                dep.meta.annotations[REVISION_ANNOTATION] = new_rev
                self.store.update(dep, check_version=False)
                if new_rs.spec.replicas == dep.spec.replicas:
                    self.store.update(new_rs, check_version=False)
        _deployment_roll(self.store, dep, new_rs,
                         [rs for rs in owned if rs.meta.name != want_name])
        self._write_status(dep, new_rs, owned)

    def _write_status(self, dep, new_rs, owned) -> None:
        from ..api.workloads import DeploymentStatus

        new_status = DeploymentStatus(
            replicas=dep.spec.replicas,
            updated_replicas=new_rs.spec.replicas if new_rs else 0,
            # readiness counts every live RS: mid-roll (or paused mid-roll)
            # part of the pods live in old RSes; rollout-status completion
            # still gates on updated_replicas, so this can't fire early
            ready_replicas=sum(r.status.ready_replicas for r in owned),
            observed_generation=dep.meta.generation,
        )
        if new_status != dep.status:
            dep.status = new_status
            self.store.update(dep, check_version=False)


def _available_pods(store, rs) -> int:
    """Pods of this RS counted as available: scheduled and not terminating
    (Running when a kubelet reports phases) — the pragmatic availability
    the StatefulSet controller uses too."""
    return sum(
        1 for p in store.pods()
        if p.meta.namespace == rs.meta.namespace
        and _owned_by(p, rs.meta.uid)
        and bool(p.spec.node_name)
        and not p.is_terminating
        and p.status.phase not in (SUCCEEDED, FAILED)
    )


def _deployment_roll(store, dep, new_rs, olds) -> None:
    """rolling.go's two moves: surge the new RS, scale old ones down only
    as availability allows."""
    strategy = dep.spec.strategy
    desired = dep.spec.replicas
    if strategy.type == "Recreate":
        # tear old down fully, then bring the new RS up
        for rs in olds:
            if rs.spec.replicas != 0:
                rs.spec.replicas = 0
                store.update(rs, check_version=False)
        old_gone = all(
            _available_pods(store, rs) == 0 and rs.spec.replicas == 0
            for rs in olds
        )
        target = desired if old_gone else new_rs.spec.replicas
        if new_rs.spec.replicas != target:
            new_rs.spec.replicas = target
            store.update(new_rs, check_version=False)
        return
    # RollingUpdate: surge the new RS within replicas+maxSurge total
    # (reconcileNewReplicaSet), then scale old RSes down only as far as
    # availability allows (reconcileOldReplicaSets)
    surge = max(strategy.max_surge,
                1 if strategy.max_unavailable == 0 else 0)
    total = new_rs.spec.replicas + sum(rs.spec.replicas for rs in olds)
    max_total = desired + surge
    if new_rs.spec.replicas < desired and total < max_total:
        new_rs.spec.replicas = min(
            desired, new_rs.spec.replicas + (max_total - total)
        )
        store.update(new_rs, check_version=False)
    elif new_rs.spec.replicas > desired:
        new_rs.spec.replicas = desired
        store.update(new_rs, check_version=False)
    # cleanupUnhealthyReplicas (rolling.go): old replicas that never became
    # available cost nothing to remove — without this, one permanently
    # pending old pod wedges the entire roll at the availability floor
    for rs in sorted(olds, key=lambda r: r.meta.name):
        if rs.spec.replicas == 0:
            continue
        unhealthy = rs.spec.replicas - _available_pods(store, rs)
        if unhealthy > 0:
            rs.spec.replicas = max(0, rs.spec.replicas - unhealthy)
            store.update(rs, check_version=False)
    available = _available_pods(store, new_rs) + sum(
        _available_pods(store, rs) for rs in olds
    )
    min_available = desired - strategy.max_unavailable
    budget = available - min_available
    for rs in sorted(olds, key=lambda r: r.meta.name):
        if budget <= 0:
            break
        if rs.spec.replicas == 0:
            continue
        down = min(rs.spec.replicas, budget)
        rs.spec.replicas -= down
        budget -= down
        store.update(rs, check_version=False)


class JobController(Controller):
    """job_controller.go syncJob — run `parallelism` pods at a time until
    `completions` have succeeded."""

    name = "job"
    watches = ("Job", "Pod")
    clocked_queue = True  # activeDeadlineSeconds wakeups ride the clock

    def key_of(self, kind: str, obj) -> str | None:
        if kind == "Job":
            return obj.meta.key
        for ref in obj.meta.owner_references:
            if ref.kind == "Job" and ref.controller:
                return f"{obj.meta.namespace}/{ref.name}"
        return None

    def reconcile(self, key: str) -> None:
        if faultinject.fire("controller.workloads"):  # chaos: see ReplicaSet
            raise faultinject.TransientFault("controller.workloads: dropped")
        try:
            job = self.store.get("Job", key)
        except NotFoundError:
            return
        owned = [
            p for p in self.store.pods()
            if p.meta.namespace == job.meta.namespace and _owned_by(p, job.meta.uid)
        ]
        succeeded = sum(1 for p in owned if p.status.phase == SUCCEEDED)
        failed = sum(1 for p in owned if p.status.phase == FAILED)
        active = [
            p for p in owned
            if p.status.phase not in (SUCCEEDED, FAILED) and not p.is_terminating
        ]
        import copy

        old_status = copy.copy(job.status)
        job.status.active = len(active)
        job.status.succeeded = succeeded
        job.status.failed = failed
        if job.status.start_time is None:
            job.status.start_time = self.clock.now()
        # batch/v1 activeDeadlineSeconds (job_controller syncJob past-
        # deadline): the whole job fails once it has run too long
        deadline = job.spec.active_deadline_seconds
        if (deadline is not None and not job.status.completed
                and not job.status.failure_reason):
            elapsed = self.clock.now() - job.status.start_time
            if elapsed >= deadline:
                job.status.failure_reason = "DeadlineExceeded"
                for p in active:
                    self.store.delete("Pod", p.meta.key)
                job.status.active = 0
                if job.status != old_status:
                    self.store.update(job, check_version=False)
                return
            # wake exactly at the deadline (clocked delayed queue)
            self.queue.add_after(key, deadline - elapsed + 0.1)
        if job.status.failure_reason:
            return  # terminally failed: never mint replacement pods
        if succeeded >= job.spec.completions:
            job.status.completed = True
            if job.status.completion_time is None:
                job.status.completion_time = self.clock.now()
            for p in active:
                self.store.delete("Pod", p.meta.key)
            if job.status != old_status:
                self.store.update(job, check_version=False)
            return
        if failed > job.spec.backoff_limit:
            # terminal failure (job_controller.go syncJob BackoffLimitExceeded):
            # stop replacing pods and tear down the active ones. The reason
            # is PERMANENT (batch/v1 Failed condition): even if the failed
            # pods are later GC'd, the job must not resurrect
            job.status.failure_reason = "BackoffLimitExceeded"
            for p in active:
                self.store.delete("Pod", p.meta.key)
            job.status.active = 0
            if job.status != old_status:
                self.store.update(job, check_version=False)
            return
        want_active = min(
            job.spec.parallelism, job.spec.completions - succeeded
        )
        from ..api.meta import new_uid

        for _ in range(want_active - len(active)):
            pod = Pod(
                meta=ObjectMeta(
                    name=f"{job.meta.name}-{new_uid().rsplit('-', 1)[-1]}",
                    namespace=job.meta.namespace,
                    labels=dict(job.spec.template.labels),
                    owner_references=[_controller_ref(job)],
                ),
                spec=_clone_pod_spec(job.spec.template),
            )
            pod.spec.restart_policy = "Never"
            self.store.create(pod)
        if job.status != old_status:
            self.store.update(job, check_version=False)


class StatefulSetController(Controller):
    """pkg/controller/statefulset — stable pod identity with ordered
    rollout: pods are named <set>-0 .. <set>-(replicas-1); under the
    default OrderedReady policy ordinal i+1 is only created once ordinal i
    is scheduled-and-running, scale-down removes the highest ordinal first,
    and a deleted ordinal is recreated under the SAME name (the stable
    network identity the reference guarantees via the headless service)."""

    name = "statefulset"
    watches = ("StatefulSet", "Pod")

    def key_of(self, kind: str, obj) -> str | None:
        if kind == "StatefulSet":
            return obj.meta.key
        for ref in obj.meta.owner_references:
            if ref.kind == "StatefulSet" and ref.controller:
                return f"{obj.meta.namespace}/{ref.name}"
        return None

    @staticmethod
    def _ordinal(set_name: str, pod_name: str) -> int | None:
        prefix = f"{set_name}-"
        if not pod_name.startswith(prefix):
            return None
        tail = pod_name[len(prefix):]
        return int(tail) if tail.isdigit() else None

    def _pod_running(self, pod: Pod) -> bool:
        # no kubelet in-process: scheduled == as-running-as-it-gets (the
        # hollow kubelet flips phase when present)
        return bool(pod.spec.node_name) and not pod.is_terminating

    def reconcile(self, key: str) -> None:
        if faultinject.fire("controller.workloads"):  # chaos: see ReplicaSet
            raise faultinject.TransientFault("controller.workloads: dropped")
        try:
            st = self.store.get("StatefulSet", key)
        except NotFoundError:
            return
        from ..api.workloads import StatefulSetStatus

        owned: dict[int, Pod] = {}
        for p in self.store.pods():
            if p.meta.namespace != st.meta.namespace or not _owned_by(p, st.meta.uid):
                continue
            if p.is_terminating:
                continue
            o = self._ordinal(st.meta.name, p.meta.name)
            if o is not None:
                owned[o] = p

        ordered = st.spec.pod_management_policy != "Parallel"
        # scale down highest-ordinal-first (the reference deletes one at a
        # time under OrderedReady; one per reconcile converges the same way)
        excess = sorted((o for o in owned if o >= st.spec.replicas), reverse=True)
        for o in excess:
            self.store.delete("Pod", owned[o].meta.key)
            del owned[o]
            if ordered:
                break

        # create missing ordinals in order; OrderedReady waits for the
        # predecessor to be running before minting the successor
        for o in range(st.spec.replicas):
            if o in owned:
                if ordered and not self._pod_running(owned[o]):
                    break
                continue
            labels = dict(st.spec.template.labels)
            labels["statefulset.kubernetes.io/pod-name"] = f"{st.meta.name}-{o}"
            pod = Pod(
                meta=ObjectMeta(
                    name=f"{st.meta.name}-{o}",
                    namespace=st.meta.namespace,
                    labels=labels,
                    owner_references=[_controller_ref(st)],
                ),
                spec=_clone_pod_spec(st.spec.template),
            )
            self._attach_claims(st, o, pod)
            self.store.create(pod)
            if ordered:
                break  # next ordinal waits for this one to run

        new_status = StatefulSetStatus(
            replicas=len(owned),
            ready_replicas=sum(1 for p in owned.values()
                               if self._pod_running(p)),
            observed_generation=st.meta.generation,
        )
        if new_status != st.status:
            st.status = new_status
            self.store.update(st, check_version=False)

    def _attach_claims(self, st, ordinal: int, pod: Pod) -> None:
        """volumeClaimTemplates → per-ordinal PVC <tpl>-<set>-<ordinal>,
        created once and REUSED by a recreated ordinal (stable storage:
        the PVC deliberately carries no owner ref to the pod; the
        reference keeps it until the set's PVC retention policy says
        otherwise)."""
        import copy

        from ..api.storage import Volume

        for tpl in st.spec.volume_claim_templates:
            claim_name = f"{tpl.meta.name}-{st.meta.name}-{ordinal}"
            claim_key = f"{st.meta.namespace}/{claim_name}"
            if self.store.try_get("PersistentVolumeClaim", claim_key) is None:
                claim = copy.deepcopy(tpl)
                claim.meta.name = claim_name
                claim.meta.namespace = st.meta.namespace
                claim.meta.uid = ""
                claim.meta.resource_version = 0
                claim.meta.owner_references = [_controller_ref(st)]
                self.store.create(claim)
            pod.spec.volumes = tuple(pod.spec.volumes) + (
                Volume(name=tpl.meta.name,
                       persistent_volume_claim=claim_name),
            )


class DaemonSetController(Controller):
    """pkg/controller/daemon — one pod per eligible node. Pods are pinned
    to their node with required node affinity on metadata.name (the modern
    daemon controller delegates placement to the SCHEDULER instead of
    setting spec.nodeName, daemon/daemon_controller.go) and get the
    controller's node.kubernetes.io/unschedulable toleration so cordoned
    nodes keep their daemons."""

    name = "daemonset"
    watches = ("DaemonSet", "Pod", "Node")
    clocked_queue = True  # roll-grace-expiry self-requeues ride the clock
    # a rolling replacement unavailable this long stops counting against
    # the maxUnavailable budget (see reconcile)
    ROLL_STUCK_GRACE_S = 60.0

    def key_of(self, kind: str, obj) -> str | None:
        if kind == "DaemonSet":
            return obj.meta.key
        if kind == "Node":
            # node churn re-reconciles every daemonset
            for ds in self.store.iter_kind("DaemonSet"):
                self.queue.add(ds.meta.key)
            return None
        for ref in obj.meta.owner_references:
            if ref.kind == "DaemonSet" and ref.controller:
                return f"{obj.meta.namespace}/{ref.name}"
        return None

    @staticmethod
    def _daemon_pod_spec(ds, node_name: str) -> PodSpec:
        from ..api.types import (
            Affinity,
            NodeAffinity,
            NodeSelector,
            NodeSelectorRequirement,
            NodeSelectorTerm,
            Toleration,
        )

        spec = _clone_pod_spec(ds.spec.template)
        # ReplaceDaemonSetPodNodeNameNodeAffinity: pin via required node
        # affinity on the node FIELD, scheduled by the scheduler
        spec.affinity = Affinity(node_affinity=NodeAffinity(
            required=NodeSelector(terms=(NodeSelectorTerm(
                match_fields=(NodeSelectorRequirement(
                    key="metadata.name", operator="In", values=(node_name,)
                ),),
            ),)),
        ))
        # AddOrUpdateDaemonPodTolerations: daemons ride out node pressure —
        # unschedulable spec, and the lifecycle controller's unreachable/
        # not-ready NoExecute taints (otherwise a daemon evicted from a
        # flapping node mints a replacement that can never schedule there)
        spec.tolerations = tuple(spec.tolerations) + (
            Toleration(key="node.kubernetes.io/unschedulable",
                       operator="Exists", effect="NoSchedule"),
            Toleration(key="node.kubernetes.io/unreachable",
                       operator="Exists", effect="NoExecute"),
            Toleration(key="node.kubernetes.io/not-ready",
                       operator="Exists", effect="NoExecute"),
        )
        return spec

    def _eligible(self, ds, node) -> bool:
        # template-level node selection: honor the template's nodeSelector
        # (spec.selector is pod OWNERSHIP, handled via owner references)
        tpl_sel = ds.spec.template.spec.node_selector
        if tpl_sel and any(node.meta.labels.get(k) != v for k, v in tpl_sel.items()):
            return False
        return True

    def reconcile(self, key: str) -> None:
        if faultinject.fire("controller.workloads"):  # chaos: see ReplicaSet
            raise faultinject.TransientFault("controller.workloads: dropped")
        try:
            ds = self.store.get("DaemonSet", key)
        except NotFoundError:
            return
        from ..api.workloads import DaemonSetStatus

        nodes = {n.meta.name: n for n in self.store.nodes()}
        eligible = {name for name, n in nodes.items() if self._eligible(ds, n)}
        by_node: dict[str, list[Pod]] = {}
        floating: list[Pod] = []
        for p in self.store.pods():
            if p.meta.namespace != ds.meta.namespace or not _owned_by(p, ds.meta.uid):
                continue
            target = p.meta.annotations.get("daemonset.kubernetes.io/node", "")
            if target:
                by_node.setdefault(target, []).append(p)
            else:
                floating.append(p)
        for p in floating:
            self.store.delete("Pod", p.meta.key)
        from ..api.meta import new_uid

        want_hash = _template_hash(ds)
        for name in sorted(eligible):
            pods = by_node.get(name, [])
            if not pods:
                pod = Pod(
                    meta=ObjectMeta(
                        name=f"{ds.meta.name}-{new_uid().rsplit('-', 1)[-1]}",
                        namespace=ds.meta.namespace,
                        labels=dict(ds.spec.template.labels),
                        annotations={
                            "daemonset.kubernetes.io/node": name,
                            "daemonset.kubernetes.io/template-hash": want_hash,
                        },
                        owner_references=[_controller_ref(ds)],
                    ),
                    spec=self._daemon_pod_spec(ds, name),
                )
                self.store.create(pod)
                # visible to the in-flight budget below: a node whose
                # replacement was minted THIS reconcile is already in
                # flight (otherwise the budget double-spends when a second
                # reconcile runs before the scheduler places the pod)
                by_node[name] = [pod]
            else:
                # at most one daemon per node; extra copies die
                for dup in pods[1:]:
                    self.store.delete("Pod", dup.meta.key)

        # RollingUpdate (daemon/update.go): replace stale-template daemons.
        # - stale AND unavailable daemons delete budget-free (removing them
        #   changes nothing for the node), so a sick node can't wedge the
        #   roll for healthy ones;
        # - the budget for killing AVAILABLE stale daemons is maxUnavailable
        #   minus replacements still in flight (new-hash pods not yet
        #   available) — that's what makes the roll one-node-at-a-time;
        # - a replacement stuck unavailable past ROLL_STUCK_GRACE_S ages out
        #   of the in-flight count (the reference excludes such nodes via
        #   shouldRun fit simulation; the grace approximates it), so the
        #   roll keeps moving. max_unavailable=0 genuinely freezes rolls of
        #   available daemons.
        def pod_available(p) -> bool:
            return bool(p.spec.node_name) and not p.is_terminating

        hash_key = "daemonset.kubernetes.io/template-hash"
        now = self.clock.now()
        in_flight = 0
        next_age_out = None  # earliest grace expiry among in-flight pods
        for name in eligible:
            pods = by_node.get(name, [])[:1]
            if not pods:
                continue
            p = pods[0]
            age = now - p.meta.creation_timestamp
            # negative age = clock skew between store and controller
            # clocks: fail OPEN (not in-flight) so the roll makes progress
            if (p.meta.annotations.get(hash_key) == want_hash
                    and not pod_available(p)
                    and 0 <= age < self.ROLL_STUCK_GRACE_S):
                in_flight += 1
                remain = self.ROLL_STUCK_GRACE_S - age
                if next_age_out is None or remain < next_age_out:
                    next_age_out = remain
        budget = ds.spec.max_unavailable - in_flight
        budget_blocked = False
        for name in sorted(eligible):
            pods = by_node.get(name, [])[:1]
            if not pods:
                continue
            pod = pods[0]
            if pod.meta.annotations.get(hash_key) == want_hash:
                continue
            if not pod_available(pod):
                self.store.delete("Pod", pod.meta.key)  # free
            elif budget > 0:
                self.store.delete("Pod", pod.meta.key)
                budget -= 1
            else:
                budget_blocked = True
        if budget_blocked and next_age_out is not None:
            # stale daemons remain only because replacements hold the
            # budget: wake when the first one ages out of the in-flight
            # count — no unrelated event is needed to resume the roll
            self.queue.add_after(key, next_age_out + 0.1)
        # pods for gone/ineligible nodes are removed
        for name, pods in by_node.items():
            if name not in eligible:
                for p in pods:
                    self.store.delete("Pod", p.meta.key)

        scheduled = sum(
            1 for name in eligible for p in by_node.get(name, [])[:1]
            if p.spec.node_name
        )
        new_status = DaemonSetStatus(
            desired_number_scheduled=len(eligible),
            current_number_scheduled=sum(1 for n in eligible if by_node.get(n)),
            number_ready=scheduled,
        )
        if new_status != ds.status:
            ds.status = new_status
            self.store.update(ds, check_version=False)
