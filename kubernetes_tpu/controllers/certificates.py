"""CSR approval + signing controllers.

Reference: pkg/controller/certificates/ — the approver
(approver/sarapprove.go) auto-approves kubelet client CSRs whose subject
matches the node-identity shape, and the signer (signer/signer.go) mints
certificates from the cluster CA for approved CSRs of the signers it
handles. Both are standard reconcile loops over CertificateSigningRequest
objects.
"""

from __future__ import annotations

from ..api.certificates import (
    CLIENT_SIGNER,
    CONDITION_APPROVED,
    CONDITION_DENIED,
    KUBELET_CLIENT_SIGNER,
)
from .base import Controller


class CSRApprovingController(Controller):
    """Auto-approve kubelet bootstrap CSRs (the sarapprove model, scoped
    to the node-client signer): the CSR must name the kubelet client
    signer and request a system:node identity. Anything else waits for a
    human/admin approval (kubectl certificate approve)."""

    name = "csrapproving"
    watches = ("CertificateSigningRequest",)

    # the exact usage set sarapprove requires for kubelet client certs
    # (kubeletClientUsages, approver/sarapprove.go) — "key encipherment"
    # is optional there too
    _ALLOWED_USAGES = frozenset(
        {"digital signature", "key encipherment", "client auth"})

    def reconcile(self, key: str) -> None:
        csr = self.store.try_get("CertificateSigningRequest", key)
        if csr is None or csr.status.get("conditions"):
            return  # gone, or already approved/denied
        if csr.spec.signer_name != KUBELET_CLIENT_SIGNER:
            return
        usages = set(csr.spec.usages)
        if "client auth" not in usages or usages - self._ALLOWED_USAGES:
            return  # a serving-cert (or over-broad) request never auto-approves
        if not self._node_identity(csr):
            return
        if (csr.spec.username
                and not csr.spec.username.startswith("system:node:")):
            # the requestor-identity half of sarapprove: only a node (or
            # the bootstrap flow acting as one) may request its own cert
            return
        csr.status.setdefault("conditions", []).append({
            "type": CONDITION_APPROVED,
            "reason": "AutoApproved",
            "message": "kubelet bootstrap client certificate",
        })
        self.store.update(csr, check_version=False)

    @staticmethod
    def _node_identity(csr) -> bool:
        """The approver's subject check, EXACT like sarapprove: the CN
        must be system:node:<name> and the Organization must be exactly
        system:nodes (a substring match would approve
        O=system:nodes-attackers)."""
        import re
        import subprocess
        import tempfile

        try:
            with tempfile.NamedTemporaryFile("w", suffix=".csr") as f:
                f.write(csr.spec.request)
                f.flush()
                out = subprocess.run(
                    ["openssl", "req", "-in", f.name, "-noout", "-subject",
                     "-nameopt", "multiline"],
                    capture_output=True, text=True, check=True,
                )
        except Exception:  # noqa: BLE001 - unparseable = not approvable
            return False
        fields: dict[str, list[str]] = {}
        for line in out.stdout.splitlines():
            m = re.match(r"\s*(\w+)\s*=\s*(.*)$", line)
            if m:
                fields.setdefault(m.group(1), []).append(m.group(2).strip())
        cn = fields.get("commonName", [])
        orgs = fields.get("organizationName", [])
        return (len(cn) == 1 and cn[0].startswith("system:node:")
                and len(cn[0]) > len("system:node:")
                and orgs == ["system:nodes"])


class CSRSigningController(Controller):
    """Sign approved CSRs from the cluster CA (signer/signer.go): only the
    signers this controller handles; denied or unapproved CSRs are left
    alone; the minted certificate lands in status.certificate."""

    name = "csrsigning"
    watches = ("CertificateSigningRequest",)
    SIGNERS = (KUBELET_CLIENT_SIGNER, CLIENT_SIGNER)

    def __init__(self, store, informers=None, clock=None,
                 ca_cert: str = "", ca_key: str = ""):
        super().__init__(store, informers, clock=clock)
        self.ca_cert = ca_cert
        self.ca_key = ca_key

    def reconcile(self, key: str) -> None:
        from ..apiserver.certs import sign_csr

        csr = self.store.try_get("CertificateSigningRequest", key)
        if csr is None or not self.ca_cert:
            return
        if csr.spec.signer_name not in self.SIGNERS:
            return
        if csr.status.get("certificate"):
            return
        conds = {c.get("type") for c in csr.status.get("conditions", ())}
        if CONDITION_DENIED in conds or CONDITION_APPROVED not in conds:
            return
        if "SigningFailed" in conds:
            # one failure report per CSR: re-signing on every reconcile
            # would hot-loop (each status update re-triggers the informer)
            # and grow conditions without bound; the admin clears the
            # condition (or recreates the CSR) to retry
            return
        try:
            cert = sign_csr(csr.spec.request, self.ca_cert, self.ca_key)
        except Exception as e:  # noqa: BLE001 - surfaced on the object
            csr.status.setdefault("conditions", []).append({
                "type": "SigningFailed", "message": str(e)[:300],
            })
            self.store.update(csr, check_version=False)
            return
        csr.status["certificate"] = cert
        self.store.update(csr, check_version=False)
