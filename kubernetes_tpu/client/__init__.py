"""Client runtime: informers, workqueue, leader election.

Reference: staging/src/k8s.io/client-go — tools/cache (Reflector, DeltaFIFO,
SharedIndexInformer), util/workqueue, tools/leaderelection. In-process against
the Store, so the reflector is a thin list+watch pump; semantics preserved:
handlers observe a gap-free Add/Update/Delete stream and a local indexed cache.
"""

from .informer import SharedInformer, InformerFactory  # noqa: F401
from .workqueue import WorkQueue  # noqa: F401
