"""Lease-based leader election.

Reference: staging/src/k8s.io/client-go/tools/leaderelection/ —
LeaderElector (tryAcquireOrRenew, renew loop, release on stop) over a
coordination/v1 Lease via resourcelock/leaselock.go. The scheduler wires it
at cmd/kube-scheduler/app/server.go:301-345.

The Lease record's optimistic concurrency comes from the store's
resourceVersion checks — exactly the apiserver mechanism the reference
relies on.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable

from ..api.coordination import Lease, LeaseSpec
from ..api.meta import ObjectMeta
from ..store.store import ConflictError, NotFoundError
from ..utils import faultinject


@dataclass
class LeaderElectionRecord:
    holder_identity: str
    lease_duration: float
    acquire_time: float
    renew_time: float
    transitions: int


@dataclass
class LeaderElector:
    """client-go LeaderElector. run() blocks until stopped; callbacks fire on
    state transitions."""

    store: object
    identity: str
    name: str = "kube-scheduler"
    namespace: str = "kube-system"
    lease_duration: float = 15.0
    renew_deadline: float = 10.0
    retry_period: float = 2.0
    on_started_leading: Callable[[], None] | None = None
    on_stopped_leading: Callable[[], None] | None = None
    on_new_leader: Callable[[str], None] | None = None
    clock: object = None
    _is_leader: bool = field(default=False, init=False)
    _observed_leader: str = field(default="", init=False)
    _stop: threading.Event = field(default_factory=threading.Event, init=False)

    def __post_init__(self):
        if self.clock is None:
            from ..utils.clock import Clock

            self.clock = Clock()

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}" if self.namespace else self.name

    def is_leader(self) -> bool:
        return self._is_leader

    # -- lock plumbing (resourcelock/leaselock.go) ---------------------------

    def _get_lease(self) -> Lease | None:
        try:
            return self.store.get("Lease", self.key)
        except NotFoundError:
            return None

    def try_acquire_or_renew(self) -> bool:
        """leaderelection.go tryAcquireOrRenew — one CAS round.

        The round is a seeded fault point (`lease.renew`): ERROR models a
        flaky coordination write (the round fails, retried next tick),
        LATENCY a renew that lands late, PARTITION a window where every
        renewal is lost — so lease loss and renew storms replay from the
        chaos seed like every other fault."""
        try:
            if faultinject.fire("lease.renew"):
                return False  # renewal lost in a partition window
        except faultinject.SchedulerCrashed:
            raise  # CRASH mode must rip through to the soak driver
        except faultinject.FaultInjected:
            return False  # flaky coordination write: retry next round
        # clock read AFTER the fault point: injected LATENCY makes this the
        # renew that lands late, exercising the stale-lease step-down below
        now = self.clock.now()
        lease = self._get_lease()
        if lease is None:
            lease = Lease(
                meta=ObjectMeta(name=self.name, namespace=self.namespace),
                spec=LeaseSpec(
                    holder_identity=self.identity,
                    lease_duration_seconds=self.lease_duration,
                    acquire_time=now,
                    renew_time=now,
                ),
            )
            try:
                self.store.create(lease)
            except Exception:  # noqa: BLE001 - lost the create race
                return False
            self._became_leader()
            return True

        spec = lease.spec
        if spec.holder_identity != self.identity:
            if spec.holder_identity and not spec.expired(now):
                self._observe(spec.holder_identity)
                return False
            # lease expired (or released): try to take it over
            spec.holder_identity = self.identity
            spec.acquire_time = now
            spec.renew_time = now
            spec.lease_transitions += 1
        elif spec.expired(now):
            # renewal edge: this renew landed AFTER our own lease's
            # deadline (slow write, renew storm, partition). The term is
            # dead — a peer may already have observed the expiry and begun
            # takeover, so silently re-stamping renew_time would keep a
            # stale leader scheduling. Step down FIRST (on_stopped_leading
            # halts the owned work before its next pop), then contend for
            # a FRESH term through the same CAS as any other candidate.
            self._lost_leadership()
            spec.acquire_time = now
            spec.renew_time = now
            spec.lease_transitions += 1
        else:
            spec.renew_time = now
        try:
            self.store.update(lease)  # resourceVersion-checked CAS
        except (ConflictError, NotFoundError):
            return False
        self._became_leader()
        return True

    def release(self) -> None:
        """Give up the lease on clean shutdown (leaderelection.go release)."""
        if not self._is_leader:
            return
        lease = self._get_lease()
        if lease is not None and lease.spec.holder_identity == self.identity:
            lease.spec.holder_identity = ""
            try:
                self.store.update(lease)
            except (ConflictError, NotFoundError):
                pass
        self._lost_leadership()

    # -- state transitions ---------------------------------------------------

    def _became_leader(self) -> None:
        if not self._is_leader:
            self._is_leader = True
            self._observe(self.identity)
            if self.on_started_leading is not None:
                self.on_started_leading()

    def _lost_leadership(self) -> None:
        if self._is_leader:
            self._is_leader = False
            if self.on_stopped_leading is not None:
                self.on_stopped_leading()

    def _observe(self, leader: str) -> None:
        if leader != self._observed_leader:
            self._observed_leader = leader
            if self.on_new_leader is not None:
                self.on_new_leader(leader)

    # -- loops ---------------------------------------------------------------

    def run_once(self) -> bool:
        """One election tick: acquire/renew or detect loss. Returns leader?"""
        ok = self.try_acquire_or_renew()
        if not ok and self._is_leader:
            self._lost_leadership()
        return self._is_leader

    def run(self) -> None:
        """Blocking acquire → renew loop (leaderelection.go Run)."""
        while not self._stop.is_set():
            if self.run_once():
                # leader: renew at retry_period cadence, fail if we can't
                # renew within renew_deadline
                deadline = self.clock.now() + self.renew_deadline
                while not self._stop.is_set():
                    self.clock.sleep(self.retry_period)
                    if self.try_acquire_or_renew():
                        deadline = self.clock.now() + self.renew_deadline
                    elif self.clock.now() > deadline:
                        self._lost_leadership()
                        break
            else:
                self.clock.sleep(self.retry_period)
        self.release()

    def stop(self) -> None:
        self._stop.set()
