"""Rate-limited work queue for controllers.

Reference: client-go util/workqueue — dedup while queued, per-item exponential
backoff on retry (rate_limiting_queue.go). Used by the controller layer;
the scheduler has its own richer 3-tier queue.
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Hashable


class WorkQueue:
    def __init__(
        self,
        base_delay: float = 0.005,
        max_delay: float = 1000.0,
        clock=time.monotonic,
    ):
        self._mu = threading.Condition()
        self._queue: list[Hashable] = []
        self._dirty: set[Hashable] = set()
        self._processing: set[Hashable] = set()
        self._failures: dict[Hashable, int] = {}
        self._delayed: list[tuple[float, int, Hashable]] = []
        self._delayed_pending: dict[Hashable, float] = {}  # earliest wake
        self._seq = 0
        self._base_delay = base_delay
        self._max_delay = max_delay
        self._clock = clock
        self._shutdown = False

    def add(self, item: Hashable) -> None:
        with self._mu:
            if self._shutdown or item in self._dirty:
                return
            self._dirty.add(item)
            if item not in self._processing:
                self._queue.append(item)
                self._mu.notify()

    def add_after(self, item: Hashable, delay: float) -> None:
        """Deliver `item` after `delay`. Dedup to the EARLIEST pending wake
        per item (client-go delayingQueue semantics): controllers re-add
        the same deadline on every reconcile, and without dedup the heap
        grows by one timer per event."""
        with self._mu:
            due = self._clock() + delay
            pending = self._delayed_pending.get(item)
            if pending is not None and pending <= due:
                return
            self._delayed_pending[item] = due
            self._seq += 1
            heapq.heappush(self._delayed, (due, self._seq, item))
            self._mu.notify()

    def add_rate_limited(self, item: Hashable) -> None:
        with self._mu:
            n = self._failures.get(item, 0)
            self._failures[item] = n + 1
        self.add_after(item, min(self._base_delay * (2**n), self._max_delay))

    def forget(self, item: Hashable) -> None:
        with self._mu:
            self._failures.pop(item, None)

    def _flush_delayed_locked(self) -> None:
        now = self._clock()
        while self._delayed and self._delayed[0][0] <= now:
            t, _, item = heapq.heappop(self._delayed)
            if self._delayed_pending.get(item) != t:
                # superseded heap entry: an earlier wake already delivered
                # (or retimed) this item — a stale timer must not deliver
                # a second, spurious copy
                continue
            del self._delayed_pending[item]
            if item not in self._dirty:
                self._dirty.add(item)
                if item not in self._processing:
                    self._queue.append(item)

    def get(self, timeout: float | None = None) -> Hashable | None:
        # the timeout is a LIVENESS bound for the calling worker loop: it
        # must tick on wall clock even when the queue's own clock is an
        # injected fake (a frozen clock would otherwise trap the caller in
        # here forever, deaf to its stop event)
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._mu:
            while True:
                self._flush_delayed_locked()
                if self._queue:
                    item = self._queue.pop(0)
                    self._dirty.discard(item)
                    self._processing.add(item)
                    return item
                if self._shutdown:
                    return None
                wait = None
                if self._delayed:
                    wait = max(0.0, self._delayed[0][0] - self._clock())
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                    wait = remaining if wait is None else min(wait, remaining)
                # fake-clock intervals aren't real durations — cap so the
                # caller stays responsive; with the real clock the wait is
                # event-driven (woken by add/notify), no polling
                if self._clock is not time.monotonic and wait is not None:
                    wait = min(wait, 0.05)
                self._mu.wait(wait)

    def done(self, item: Hashable) -> None:
        with self._mu:
            self._processing.discard(item)
            if item in self._dirty:
                self._queue.append(item)
                self._mu.notify()

    def __len__(self) -> int:
        with self._mu:
            return len(self._queue)

    def shutdown(self) -> None:
        with self._mu:
            self._shutdown = True
            self._mu.notify_all()
