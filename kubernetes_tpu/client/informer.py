"""Shared informers over the Store.

Reference: client-go tools/cache — Reflector (reflector.go) feeds DeltaFIFO
(delta_fifo.go) feeds SharedIndexInformer (shared_informer.go) which fans out
to event handlers and maintains a thread-safe store. Here the Store's watch
log already provides a gap-free ordered stream, so the informer reduces to:
list (sync local cache, emit Adds) + watch (pump events to handlers).

Determinism: `pump()` drains available events synchronously — tests and the
single-threaded scheduler loop call it at well-defined points instead of
racing a background goroutine. `run_background()` gives the threaded mode.
"""

from __future__ import annotations

import threading
from typing import Any, Callable

from ..store.store import Store, Event, ADDED, MODIFIED, DELETED

Handler = Callable[[str, Any, Any], None]  # (event_type, old_obj, new_obj)


class CacheMutationDetected(Exception):
    """An informer-cache object was mutated in place. Informer caches are
    shared read-only state (client-go's contract); a consumer that edits a
    cached object corrupts every other consumer's view. The reference's
    detector (client-go/tools/cache/mutation_detector.go, enabled by
    KUBE_CACHE_MUTATION_DETECTOR) panics the process; we raise."""


def _mutation_detector_enabled() -> bool:
    import os

    return os.environ.get("KUBERNETES_TPU_CACHE_MUTATION_DETECTOR", "") not in (
        "", "0", "false",
    )


class SharedInformer:
    def __init__(self, store: Store, kind: str):
        self._store = store
        self.kind = kind
        self._cache: dict[str, Any] = {}
        self._handlers: list[Handler] = []
        self._watch = None
        self._synced = False
        self._mu = threading.Lock()
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        # mutation detector: pristine deepcopies to compare against
        self._detect = _mutation_detector_enabled()
        self._pristine: dict[str, Any] = {}
        # revision-continuity tracking (partition detection):
        #   _last_rev  — store revision this cache is known current through
        #   _last_seq  — per-kind event seq of the last delivered event
        #                (None = stream without seq support; tracking off)
        # A delivered event jumping the seq by more than one means the
        # stream LOST events (an interior gap); the log holding an event
        # newer than _last_rev after a full pump means the stream is
        # silently stale (a tail gap — the open-partition case).
        self._last_rev = 0
        self._last_seq: int | None = None
        self._gap = False
        self._gap_rev = 0
        self.partitions_detected = 0
        self._partition_observer: Callable[[str, int, float], None] | None = None

    def add_handler(self, handler: Handler) -> None:
        """Register a handler. If already synced, replays Adds for the current
        cache contents (client-go AddEventHandler semantics)."""
        self._handlers.append(handler)
        if self._synced:
            for obj in list(self._cache.values()):
                handler(ADDED, None, obj)

    def start(self) -> None:
        """List + open watch. Emits ADDED for the initial list. A relist
        covers the (heavy-churn) case where the list revision is compacted
        out of the watch window before the watch opens — the reflector's
        "too old resource version" retry."""
        from ..store.store import CompactedError

        while True:
            objs, rev = self._store.list(self.kind)
            try:
                self._watch = self._store.watch(self.kind, from_revision=rev)
                break
            except CompactedError:
                continue
        for obj in objs:
            self._cache[obj.meta.key] = obj
            if self._detect:
                import copy as _copy

                self._pristine[obj.meta.key] = _copy.deepcopy(obj)
            for h in self._handlers:
                h(ADDED, None, obj)
        self._last_rev = rev
        self._last_seq = getattr(self._watch, "start_seq", None)
        self._synced = True

    def has_synced(self) -> bool:
        return self._synced

    def pump(self) -> int:
        """Drain all currently queued watch events; returns count processed."""
        if self._watch is None:
            return 0
        if self._detect:
            self.check_mutations()
        n = 0
        for ev in self._watch.drain():
            seq = getattr(ev, "seq", 0)
            if self._last_seq is not None and seq:
                if seq > self._last_seq + 1 and not self._gap:
                    # interior gap: events between _last_seq and this one
                    # never arrived, even though delivery has resumed
                    self._gap = True
                    self._gap_rev = self._last_rev
                self._last_seq = max(self._last_seq, seq)
            if ev.revision:
                self._last_rev = max(self._last_rev, ev.revision)
            self._dispatch(ev)
            n += 1
        return n

    def resync(self) -> int:
        """Repair lost watch deliveries: diff the local cache against an
        atomic store relist + watch swap; dispatch synthesized events for
        every difference. Returns the number of repairs.

        A dropped delivery (lossy connection, injected watch.deliver fault)
        leaves the cache permanently stale — the event is gone from the
        stream even though it sits in the store's log. client-go answers
        with the reflector's periodic resync; ours is cheaper because
        Store.sync_watch hands back refs and a fresh watch under ONE lock
        acquisition, so there is no replay window to double-deliver."""
        if not self._synced:
            return 0
        # drain the old stream first so the diff only covers true losses
        self.pump()
        sync = getattr(self._store, "sync_watch", None)
        if sync is not None:
            res = sync(self.kind)
            if len(res) == 3:
                refs, new_watch, rev = res
            else:  # pre-revision facade
                refs, new_watch = res
                rev = None
        else:
            # facade without the primitive: non-atomic list+watch; events
            # landing in between replay through the new watch, which is
            # harmless (MODIFIED re-dispatch) but not gap-free in theory
            refs, rev = self._store.list(self.kind)
            new_watch = self._store.watch(self.kind, from_revision=rev)
        old_watch, self._watch = self._watch, new_watch
        if old_watch is not None:
            old_watch.stop()
        # restart the continuity bookmarks from the sync point — captured
        # under the SAME lock as the relist, so neither under- nor
        # overshoots: an earlier value would re-flag the diff-repaired
        # events as a gap forever, a later one would hide real losses
        if rev is not None:
            self._last_rev = rev
        self._last_seq = getattr(new_watch, "start_seq", None)
        self._gap = False
        self._gap_rev = 0
        n = 0
        seen = set()
        for obj in refs:
            key = obj.meta.key
            seen.add(key)
            cached = self._cache.get(key)
            if cached is None:
                self._dispatch(Event(ADDED, obj, obj.meta.resource_version))
                n += 1
            elif (cached.meta.resource_version
                  != obj.meta.resource_version):
                self._dispatch(Event(MODIFIED, obj,
                                     obj.meta.resource_version,
                                     prev_obj=cached))
                n += 1
        for key in [k for k in self._cache if k not in seen]:
            gone = self._cache[key]
            self._dispatch(Event(DELETED, gone,
                                 gone.meta.resource_version))
            n += 1
        return n

    def set_partition_observer(
        self, cb: Callable[[str, int, float], None] | None
    ) -> None:
        """cb(kind, repaired_count, repair_latency_s) fires once per
        detected partition, right after the repairing resync."""
        self._partition_observer = cb

    def detect_and_repair(self) -> int:
        """Partition self-heal: pump, then check revision continuity; on a
        gap, resync immediately and report the repair latency (now minus
        the emit time of the first event the stream lost).

        Detection is exact, not heuristic: watch delivery is synchronous
        under the store lock, so after a full pump any logged event newer
        than `_last_rev` was dropped, and any seq jump seen during the
        pump brackets events that will never arrive. No-gap cost is one
        store revision read. Returns the number of repaired cache entries
        (0 when no gap, and also when the gap's objects were already
        superseded by later deliveries)."""
        if not self._synced or self._watch is None:
            return 0
        self.pump()
        gap_rev: int | None = self._gap_rev if self._gap else None
        if gap_rev is None:
            probe = getattr(self._store, "latest_revision", None)
            if probe is not None and probe(self.kind) > self._last_rev:
                gap_rev = self._last_rev
        if gap_rev is None:
            return 0
        lost_ts: float | None = None
        first = getattr(self._store, "first_event_after", None)
        if first is not None:
            hit = first(self.kind, gap_rev)
            if hit is not None:
                lost_ts = hit[1]
        repaired = self.resync()  # clears _gap, re-bookmarks atomically
        self.partitions_detected += 1
        latency_s = 0.0
        if lost_ts is not None:
            import time as _time

            latency_s = max(_time.perf_counter() - lost_ts, 0.0)
        if self._partition_observer is not None:
            self._partition_observer(self.kind, repaired, latency_s)
        return repaired

    def check_mutations(self) -> None:
        """Compare every cached object against its pristine copy; raises
        CacheMutationDetected on any in-place edit. Called automatically
        per pump when the detector env is set; tests may call directly."""
        for key, obj in self._cache.items():
            pristine = self._pristine.get(key)
            if pristine is not None and obj != pristine:
                raise CacheMutationDetected(
                    f"{self.kind} {key} was mutated in the informer cache"
                )

    def _dispatch(self, ev) -> None:
        import copy as _copy

        key = ev.obj.meta.key
        if ev.type == DELETED:
            old = self._cache.pop(key, None)
            self._pristine.pop(key, None)
            for h in self._handlers:
                h(DELETED, old if old is not None else ev.obj, ev.obj)
        elif key in self._cache:
            old = self._cache[key]
            self._cache[key] = ev.obj
            if self._detect:
                self._pristine[key] = _copy.deepcopy(ev.obj)
            for h in self._handlers:
                h(MODIFIED, old, ev.obj)
        else:
            self._cache[key] = ev.obj
            if self._detect:
                self._pristine[key] = _copy.deepcopy(ev.obj)
            for h in self._handlers:
                h(ADDED, None, ev.obj)

    def run_background(self, poll_interval: float = 0.002) -> None:
        """Threaded pump, for components that want push-style delivery."""
        if self._thread is not None:
            return

        def loop():
            while not self._stop.is_set():
                ev = self._watch.next(timeout=poll_interval)
                if ev is not None:
                    with self._mu:
                        self._dispatch(ev)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._watch is not None:
            self._watch.stop()
        if self._thread is not None:
            self._thread.join(timeout=1)
            self._thread = None

    # local read interface (client-go Lister)
    def get(self, key: str) -> Any | None:
        return self._cache.get(key)

    def list(self) -> list[Any]:
        return list(self._cache.values())

    def keys(self) -> list[str]:
        return list(self._cache.keys())


class InformerFactory:
    """SharedInformerFactory: one informer per kind, shared across components."""

    def __init__(self, store: Store):
        self._store = store
        self._informers: dict[str, SharedInformer] = {}
        self._partition_observer: Callable[[str, int, float], None] | None = None

    def informer(self, kind: str) -> SharedInformer:
        inf = self._informers.get(kind)
        if inf is None:
            inf = SharedInformer(self._store, kind)
            inf.set_partition_observer(self._partition_observer)
            self._informers[kind] = inf
        return inf

    def start_all(self) -> None:
        for inf in self._informers.values():
            if not inf.has_synced():
                inf.start()

    def pump_all(self) -> int:
        return sum(inf.pump() for inf in self._informers.values())

    def stop_all(self) -> None:
        """Tear down every informer's watch stream. The chaos restart
        driver uses this as its stand-in for process death: a crashed
        scheduler's watch connections drop server-side, so the store must
        stop queueing deliveries for a consumer that no longer exists."""
        for inf in self._informers.values():
            inf.stop()

    def resync_all(self) -> int:
        """Diff-repair every informer's cache (see SharedInformer.resync)."""
        return sum(inf.resync() for inf in self._informers.values())

    def detect_and_repair_all(self) -> int:
        """Run every informer's partition detector; resyncs only the
        informers with an actual gap (cheap when the streams are healthy —
        one revision probe per kind)."""
        return sum(inf.detect_and_repair() for inf in self._informers.values())

    def set_partition_observer(
        self, cb: Callable[[str, int, float], None] | None
    ) -> None:
        """Install cb on every existing AND future informer."""
        self._partition_observer = cb
        for inf in self._informers.values():
            inf.set_partition_observer(cb)

    def wait_for_cache_sync(self) -> bool:
        return all(inf.has_synced() for inf in self._informers.values())
