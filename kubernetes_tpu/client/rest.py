"""REST client: the store interface over HTTP against an APIServer.

Reference: staging/src/k8s.io/client-go/rest + kubernetes typed clientsets.
RESTStore implements the same surface as store.Store (create/get/update/
delete/list/watch), so informers, controllers, and the scheduler can run
in a separate process from the API server without code changes — the
client-go role in the reference's distributed control plane (SURVEY.md §5.8).
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request
from collections import deque

from ..api.serialization import decode, encode
from ..store.store import (
    AlreadyExistsError,
    ConflictError,
    Event,
    NotFoundError,
)


class RESTError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(f"{code}: {message}")
        self.code = code


class ApplyConflictError(ConflictError):
    """Server-side apply field-OWNERSHIP conflict (reason
    FieldManagerConflict) — needs --force-conflicts, unlike a plain CAS
    Conflict which just needs a retry."""


def _raise_for(code: int, message: str, reason: str = ""):
    if code == 404:
        raise NotFoundError(message)
    if code == 409:
        if reason == "AlreadyExists":
            raise AlreadyExistsError(message)
        if reason == "FieldManagerConflict":
            raise ApplyConflictError(message)
        raise ConflictError(message)
    raise RESTError(code, message)


class RESTWatch:
    """A streaming watch connection (client-go watch.Interface shape,
    drop-in for store.Watch)."""

    def __init__(self, url: str, headers: dict[str, str] | None = None,
                 binary: bool = False, ssl_context=None):
        self._events: deque[Event] = deque()
        self._cond = threading.Condition()
        self._stopped = False
        self._binary = binary
        req = urllib.request.Request(url, headers=headers or {})
        self._resp = urllib.request.urlopen(  # noqa: S310 - loopback
            req, context=ssl_context
        )
        self._thread = threading.Thread(target=self._reader, daemon=True)
        self._thread.start()

    def _reader(self) -> None:
        try:
            if self._binary:
                self._read_cbor_frames()
            else:
                for line in self._resp:
                    line = line.strip()
                    if not line:
                        continue
                    self._push_frame(json.loads(line))
        except Exception:  # noqa: BLE001 - connection torn down
            pass
        with self._cond:
            self._stopped = True
            self._cond.notify_all()

    def _read_cbor_frames(self) -> None:
        from ..api import cbor

        read = self._resp.read
        while True:
            head = read(4)
            if len(head) < 4:
                return
            n = int.from_bytes(head, "big")
            if n == 0:
                continue  # heartbeat
            payload = b""
            while len(payload) < n:
                chunk = read(n - len(payload))
                if not chunk:
                    return
                payload += chunk
            self._push_frame(cbor.loads(payload))

    def _push_frame(self, frame: dict) -> None:
        ev = Event(frame["type"], decode(frame["object"]),
                   frame.get("revision", 0))
        with self._cond:
            self._events.append(ev)
            self._cond.notify_all()

    def next(self, timeout: float | None = None) -> Event | None:
        with self._cond:
            if not self._events and not self._stopped:
                self._cond.wait(timeout)
            return self._events.popleft() if self._events else None

    def drain(self) -> list[Event]:
        with self._cond:
            out = list(self._events)
            self._events.clear()
            return out

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        # shut the socket down FIRST: close() alone deadlocks against the
        # reader thread blocked inside a buffered read on the same fp
        import socket as _socket

        try:
            sock = self._resp.fp.raw._sock  # noqa: SLF001
            sock.shutdown(_socket.SHUT_RDWR)
        except Exception:  # noqa: BLE001
            pass
        self._thread.join(timeout=2)
        try:
            self._resp.close()
        except Exception:  # noqa: BLE001
            pass

    @property
    def stopped(self) -> bool:
        return self._stopped


class RESTStore:
    """Typed client over the API server; same surface as store.Store."""

    def __init__(self, base_url: str, timeout: float = 10.0,
                 token: str = "", wire_format: str = "json",
                 ca_cert: str | None = None):
        """wire_format="cbor" negotiates the binary serializer both ways
        (request bodies, responses, and watch frames) — the protobuf role
        in the reference's content-type negotiation. ca_cert: PEM bundle
        to verify an HTTPS apiserver against (rest.Config.TLSClientConfig
        CAFile) — required for https:// base URLs."""
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self.token = token  # bearer credential (rest.Config.BearerToken)
        self.wire_format = wire_format
        self._ssl = None
        if ca_cert:
            import ssl as _ssl

            self._ssl = _ssl.create_default_context(cafile=ca_cert)

    # -- plumbing ------------------------------------------------------------

    def _headers(self) -> dict[str, str]:
        if self.wire_format == "cbor":
            headers = {"Content-Type": "application/cbor",
                       "Accept": "application/cbor"}
        else:
            headers = {"Content-Type": "application/json"}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        return headers

    def _encode_body(self, body: dict) -> bytes:
        if self.wire_format == "cbor":
            from ..api import cbor

            return cbor.dumps(body)
        return json.dumps(body).encode()

    def _decode_body(self, raw: bytes, ctype: str) -> dict:
        if not raw:
            return {}
        if "application/cbor" in ctype:
            from ..api import cbor

            return cbor.loads(raw)
        return json.loads(raw.decode())

    def _request(self, method: str, path: str, body: dict | None = None) -> dict:
        return self._request_with_status(method, path, body)[0]

    def _request_with_status(
        self, method: str, path: str, body: dict | None = None
    ) -> tuple[dict, int]:
        data = self._encode_body(body) if body is not None else None
        req = urllib.request.Request(
            f"{self.base_url}{path}", data=data, method=method,
            headers=self._headers(),
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout,
                                        context=self._ssl) as resp:
                return self._decode_body(
                    resp.read(), resp.headers.get("Content-Type") or ""
                ), resp.status
        except urllib.error.HTTPError as e:
            raw = e.read()
            reason = ""
            try:
                status = self._decode_body(
                    raw, e.headers.get("Content-Type") or ""
                )
                message = status.get("message", "")
                reason = status.get("reason", "")
            except (json.JSONDecodeError, ValueError):
                message = raw.decode(errors="replace")
            _raise_for(e.code, message, reason)

    def raw_get(self, path: str) -> dict:
        """GET an arbitrary server path (aggregated APIs under /apis/...,
        discovery documents) — the typed surface below covers only core-v1
        kinds the scheme decodes."""
        return self._request("GET", path)

    # -- store surface -------------------------------------------------------

    def create(self, obj):
        out = self._request("POST", f"/api/v1/{obj.kind}", encode(obj))
        return decode(out)

    def get(self, kind: str, key: str):
        return decode(self._request("GET", f"/api/v1/{kind}/{key}"))

    def try_get(self, kind: str, key: str):
        try:
            return self.get(kind, key)
        except NotFoundError:
            return None

    def contains(self, kind: str, key: str) -> bool:
        """Existence check (Store.contains parity) — over the wire this is
        a GET; the copy-free fast path only exists on the in-process store."""
        return self.try_get(kind, key) is not None

    def update(self, obj, *, check_version: bool = True):
        suffix = "" if check_version else "?force=true"
        out = self._request(
            "PUT", f"/api/v1/{obj.kind}/{obj.meta.key}{suffix}", encode(obj)
        )
        return decode(out)

    def delete(self, kind: str, key: str):
        return decode(self._request("DELETE", f"/api/v1/{kind}/{key}"))

    def patch(self, kind: str, key: str, patch: dict):
        """RFC 7386 JSON merge patch; returns the updated object."""
        out = self._request("PATCH", f"/api/v1/{kind}/{key}", patch)
        return decode(out)

    def apply(self, kind: str, key: str, config: dict,
              field_manager: str, force: bool = False):
        """Server-side apply (fieldmanager): create-or-merge `config` with
        per-field ownership; raises ConflictError when a field is owned by
        another manager (force=True transfers it). Sets
        `last_apply_created` (True when the apply created the object —
        HTTP 201 vs 200) for callers that report it."""
        from urllib.parse import quote

        q = (f"?fieldManager={quote(field_manager, safe='')}"
             + ("&force=true" if force else ""))
        out, code = self._request_with_status(
            "PATCH", f"/api/v1/{kind}/{key}{q}", config
        )
        self.last_apply_created = code == 201
        return decode(out)

    def pod_logs(self, key: str, container: str = "",
                 tail_lines: int | None = None) -> str:
        """GET pods/log subresource (apiserver proxies to the kubelet)."""
        q = []
        if container:
            q.append(f"container={container}")
        if tail_lines is not None:
            q.append(f"tailLines={tail_lines}")
        path = f"/api/v1/Pod/{key}/log" + ("?" + "&".join(q) if q else "")
        req = urllib.request.Request(
            f"{self.base_url}{path}", headers=self._headers())
        try:
            with urllib.request.urlopen(req, timeout=self.timeout,
                                        context=self._ssl) as resp:
                return resp.read().decode()
        except urllib.error.HTTPError as e:
            _raise_for(e.code, e.read().decode(errors="replace"), "")

    def try_delete(self, kind: str, key: str):
        """delete() tolerant of already-gone objects (Store.try_delete)."""
        try:
            return self.delete(kind, key)
        except NotFoundError:
            return None

    @staticmethod
    def _selector_query(label_selector: str, field_selector: str) -> str:
        from urllib.parse import quote

        q = ""
        if label_selector:
            q += f"&labelSelector={quote(label_selector)}"
        if field_selector:
            q += f"&fieldSelector={quote(field_selector)}"
        return q

    def list(self, kind: str, label_selector: str = "",
             field_selector: str = ""):
        sel = self._selector_query(label_selector, field_selector)
        out = self._request("GET", f"/api/v1/{kind}?{sel.lstrip('&')}"
                            if sel else f"/api/v1/{kind}")
        items = [decode(item) for item in out.get("items", [])]
        return items, out.get("metadata", {}).get("resourceVersion", 0)

    def watch(self, kind: str, from_revision: int = 0,
              label_selector: str = "", field_selector: str = "") -> RESTWatch:
        from ..store.store import CompactedError

        sel = self._selector_query(label_selector, field_selector)
        try:
            return RESTWatch(
                f"{self.base_url}/api/v1/{kind}"
                f"?watch=1&resourceVersion={from_revision}{sel}",
                headers=self._headers(),
                binary=self.wire_format == "cbor",
                ssl_context=self._ssl,
            )
        except urllib.error.HTTPError as e:
            if e.code == 410:
                raise CompactedError(from_revision, -1) from e
            raise

    def bind(self, pod_key: str, node_name: str) -> None:
        self._request(
            "POST", f"/api/v1/Pod/{pod_key}/binding", {"target_node": node_name}
        )

    # convenience parity with Store
    def pods(self):
        return self.list("Pod")[0]

    def nodes(self):
        return self.list("Node")[0]

    def iter_kind(self, kind: str):
        return iter(self.list(kind)[0])
