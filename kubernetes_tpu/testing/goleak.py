"""Thread-leak detection for tests: the goleak role.

Reference: test/integration/framework/goleak.go wraps goleak.VerifyNone so
integration suites fail when a component leaks goroutines past shutdown
(used at scheduler_perf.go:693). Threads are our goroutines: the context
manager snapshots live threads on entry and asserts every thread started
inside the block terminated by exit (after a grace period for daemon
threads still winding down).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager


@contextmanager
def assert_no_thread_leaks(grace_s: float = 2.0, allow: tuple[str, ...] = ()):
    """Fail if threads created inside the block outlive it. `allow` names
    thread-name prefixes to ignore (goleak's IgnoreTopFunction)."""
    before = set(threading.enumerate())
    yield
    deadline = time.monotonic() + grace_s
    while True:
        leaked = [
            t for t in threading.enumerate()
            if t not in before and t.is_alive()
            and not any(t.name.startswith(p) for p in allow)
        ]
        if not leaked:
            return
        if time.monotonic() >= deadline:
            names = ", ".join(f"{t.name} (daemon={t.daemon})" for t in leaked)
            raise AssertionError(
                f"{len(leaked)} thread(s) leaked past shutdown: {names}"
            )
        time.sleep(0.01)
