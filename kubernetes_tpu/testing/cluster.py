"""Synthetic cluster builder for benches, graft entry points, and tests.

Mirrors what scheduler_perf's `createNodes`/`createPods` opcodes set up
(test/integration/scheduler_perf/scheduler_perf.go:65-79): a zone-labeled node
fleet plus an initial load of running pods, materialized straight into the
scheduler Cache and a fresh Snapshot.
"""

from __future__ import annotations

from ..api.resource import ResourceNames
from ..scheduler.cache.cache import Cache
from ..scheduler.cache.snapshot import Snapshot
from .wrappers import make_node, make_pod


def synthetic_cluster(
    n_nodes: int,
    n_zones: int = 8,
    init_pods_per_node: int = 0,
    cpu: str = "32",
    mem: str = "64Gi",
    names: ResourceNames | None = None,
):
    """Build (cache, snapshot) for an n_nodes fleet spread over n_zones.

    init_pods_per_node places running filler pods (500m cpu / 512Mi each) so
    scoring sees non-uniform utilization, like scheduler_perf's init pods.
    """
    names = names or ResourceNames()
    cache = Cache(names)
    for i in range(n_nodes):
        cache.add_node(
            make_node(f"node-{i}", cpu=cpu, mem=mem, zone=f"zone-{i % n_zones}")
        )
    for i in range(n_nodes):
        for j in range(init_pods_per_node):
            pod = make_pod(
                f"init-{i}-{j}", cpu="500m", mem="512Mi",
                labels={"app": "init"}, node_name=f"node-{i}",
            )
            cache.add_pod(pod)
    snapshot = cache.update_snapshot(Snapshot())
    return cache, snapshot
