"""Fixture builders, modeled on pkg/scheduler/testing/wrappers.go
(MakePod().Req().NodeAffinityIn()... builder style)."""

from __future__ import annotations

from kubernetes_tpu.api.labels import LabelSelector, Requirement
from kubernetes_tpu.api.meta import ObjectMeta
from kubernetes_tpu.api.types import (
    Affinity,
    Container,
    ContainerPort,
    Node,
    NodeAffinity,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    NodeSpec,
    NodeStatus,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    PodSpec,
    PreferredSchedulingTerm,
    SchedulingGroup,
    Taint,
    Toleration,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
)


def make_pod(
    name: str,
    namespace: str = "default",
    cpu: str | None = None,
    mem: str | None = None,
    requests: dict | None = None,
    labels: dict | None = None,
    node_name: str = "",
    priority: int = 0,
    image: str = "",
    host_ports: tuple[int, ...] = (),
) -> Pod:
    req: dict = {}
    if cpu is not None:
        req["cpu"] = cpu
    if mem is not None:
        req["memory"] = mem
    if requests:
        req.update(requests)
    c = Container(
        name="c",
        image=image,
        requests=req,
        ports=tuple(ContainerPort(container_port=p, host_port=p) for p in host_ports),
    )
    return Pod(
        meta=ObjectMeta(name=name, namespace=namespace, labels=dict(labels or {})),
        spec=PodSpec(containers=[c], node_name=node_name, priority=priority),
    )


def make_node(
    name: str,
    cpu: str = "32",
    mem: str = "64Gi",
    pods: int = 110,
    labels: dict | None = None,
    taints: tuple[Taint, ...] = (),
    unschedulable: bool = False,
    zone: str | None = None,
    extra: dict | None = None,
) -> Node:
    lab = dict(labels or {})
    lab.setdefault("kubernetes.io/hostname", name)
    if zone is not None:
        lab["topology.kubernetes.io/zone"] = zone
    alloc = {"cpu": cpu, "memory": mem, "pods": pods, "ephemeral-storage": "100Gi"}
    if extra:
        alloc.update(extra)
    return Node(
        meta=ObjectMeta(name=name, namespace="", labels=lab),
        spec=NodeSpec(unschedulable=unschedulable, taints=taints),
        status=NodeStatus(capacity=dict(alloc), allocatable=dict(alloc)),
    )


def with_node_affinity_in(pod: Pod, key: str, values: tuple[str, ...]) -> Pod:
    pod.spec.affinity = Affinity(
        node_affinity=NodeAffinity(
            required=NodeSelector(
                terms=(
                    NodeSelectorTerm(
                        match_expressions=(NodeSelectorRequirement(key, "In", values),)
                    ),
                )
            )
        )
    )
    return pod


def with_preferred_node_affinity(pod: Pod, weight: int, key: str, values: tuple[str, ...]) -> Pod:
    na = pod.spec.affinity.node_affinity if pod.spec.affinity else None
    required = na.required if na else None
    preferred = tuple(na.preferred) if na else ()
    pod.spec.affinity = Affinity(
        node_affinity=NodeAffinity(
            required=required,
            preferred=preferred
            + (
                PreferredSchedulingTerm(
                    weight=weight,
                    preference=NodeSelectorTerm(
                        match_expressions=(NodeSelectorRequirement(key, "In", values),)
                    ),
                ),
            ),
        )
    )
    return pod


def with_tolerations(pod: Pod, *tols: Toleration) -> Pod:
    pod.spec.tolerations = tuple(pod.spec.tolerations) + tols
    return pod


def with_spread(
    pod: Pod,
    max_skew: int = 1,
    key: str = "topology.kubernetes.io/zone",
    when: str = "DoNotSchedule",
    selector: LabelSelector | None = None,
) -> Pod:
    if selector is None:
        selector = LabelSelector.of(dict(pod.meta.labels))
    pod.spec.topology_spread_constraints = tuple(pod.spec.topology_spread_constraints) + (
        TopologySpreadConstraint(max_skew, key, when, selector),
    )
    return pod


def with_pod_affinity(pod: Pod, key: str, value: str, topology_key: str, anti: bool = False) -> Pod:
    term = PodAffinityTerm(
        label_selector=LabelSelector.of({key: value}), topology_key=topology_key
    )
    aff = pod.spec.affinity or Affinity()
    if anti:
        pa = aff.pod_anti_affinity or PodAntiAffinity()
        pod.spec.affinity = Affinity(
            node_affinity=aff.node_affinity,
            pod_affinity=aff.pod_affinity,
            pod_anti_affinity=PodAntiAffinity(required=tuple(pa.required) + (term,), preferred=pa.preferred),
        )
    else:
        pa = aff.pod_affinity or PodAffinity()
        pod.spec.affinity = Affinity(
            node_affinity=aff.node_affinity,
            pod_affinity=PodAffinity(required=tuple(pa.required) + (term,), preferred=pa.preferred),
            pod_anti_affinity=aff.pod_anti_affinity,
        )
    return pod


def with_preferred_pod_affinity(
    pod: Pod, weight: int, key: str, value: str, topology_key: str, anti: bool = False
) -> Pod:
    wterm = WeightedPodAffinityTerm(
        weight=weight,
        term=PodAffinityTerm(
            label_selector=LabelSelector.of({key: value}), topology_key=topology_key
        ),
    )
    aff = pod.spec.affinity or Affinity()
    if anti:
        pa = aff.pod_anti_affinity or PodAntiAffinity()
        pod.spec.affinity = Affinity(
            node_affinity=aff.node_affinity,
            pod_affinity=aff.pod_affinity,
            pod_anti_affinity=PodAntiAffinity(
                required=pa.required, preferred=tuple(pa.preferred) + (wterm,)
            ),
        )
    else:
        pa = aff.pod_affinity or PodAffinity()
        pod.spec.affinity = Affinity(
            node_affinity=aff.node_affinity,
            pod_affinity=PodAffinity(required=pa.required, preferred=tuple(pa.preferred) + (wterm,)),
            pod_anti_affinity=aff.pod_anti_affinity,
        )
    return pod


def with_gang(pod: Pod, group_name: str) -> Pod:
    pod.spec.scheduling_group = SchedulingGroup(pod_group_name=group_name)
    return pod


# --- storage fixtures -------------------------------------------------------


def with_pvc(pod: Pod, claim_name: str, volume_name: str | None = None) -> Pod:
    from kubernetes_tpu.api.storage import Volume

    pod.spec.volumes = tuple(pod.spec.volumes) + (
        Volume(name=volume_name or claim_name, persistent_volume_claim=claim_name),
    )
    return pod


def make_pv(
    name: str,
    storage: str = "10Gi",
    storage_class: str = "",
    access_modes: tuple[str, ...] = ("ReadWriteOnce",),
    node_names: tuple[str, ...] = (),
    zone: str | None = None,
    csi_driver: str = "",
):
    """A PersistentVolume; node_names pins it via NodeAffinity on hostname
    (the local-volume pattern), zone adds the well-known zone label."""
    from kubernetes_tpu.api.storage import (
        PersistentVolume,
        PersistentVolumeSpec,
    )

    labels = {}
    if zone is not None:
        labels["topology.kubernetes.io/zone"] = zone
    affinity = None
    if node_names:
        affinity = NodeSelector(
            terms=(
                NodeSelectorTerm(
                    match_expressions=(
                        NodeSelectorRequirement(
                            "kubernetes.io/hostname", "In", tuple(node_names)
                        ),
                    )
                ),
            )
        )
    return PersistentVolume(
        meta=ObjectMeta(name=name, namespace="", labels=labels),
        spec=PersistentVolumeSpec(
            capacity={"storage": storage},
            access_modes=access_modes,
            storage_class_name=storage_class,
            node_affinity=affinity,
            csi_driver=csi_driver,
        ),
    )


def make_pvc(
    name: str,
    namespace: str = "default",
    storage: str = "5Gi",
    storage_class: str = "",
    access_modes: tuple[str, ...] = ("ReadWriteOnce",),
    volume_name: str = "",
    bound: bool = False,
):
    from kubernetes_tpu.api.storage import (
        CLAIM_BOUND,
        CLAIM_PENDING,
        PersistentVolumeClaim,
        PersistentVolumeClaimSpec,
        PersistentVolumeClaimStatus,
    )

    assert not bound or volume_name, "bound=True requires volume_name"
    return PersistentVolumeClaim(
        meta=ObjectMeta(name=name, namespace=namespace),
        spec=PersistentVolumeClaimSpec(
            access_modes=access_modes,
            storage_class_name=storage_class,
            volume_name=volume_name,
            request={"storage": storage},
        ),
        status=PersistentVolumeClaimStatus(
            phase=CLAIM_BOUND if bound else CLAIM_PENDING
        ),
    )


def make_storage_class(
    name: str, provisioner: str = "kubernetes.io/no-provisioner",
    wait_for_first_consumer: bool = True,
):
    from kubernetes_tpu.api.storage import (
        BINDING_IMMEDIATE,
        BINDING_WAIT_FOR_FIRST_CONSUMER,
        StorageClass,
    )

    return StorageClass(
        meta=ObjectMeta(name=name, namespace=""),
        provisioner=provisioner,
        volume_binding_mode=(
            BINDING_WAIT_FOR_FIRST_CONSUMER
            if wait_for_first_consumer
            else BINDING_IMMEDIATE
        ),
    )


def make_csi_node(node_name: str, **driver_limits: int):
    from kubernetes_tpu.api.storage import CSINode, CSINodeDriver

    return CSINode(
        meta=ObjectMeta(name=node_name, namespace=""),
        drivers=tuple(
            CSINodeDriver(name=d.replace("__", "."), allocatable_count=n)
            for d, n in driver_limits.items()
        ),
    )
