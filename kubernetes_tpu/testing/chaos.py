"""Seeded chaos soak: the scale-churn workload under a recorded fault
schedule, asserting convergence.

The harness wires a standard transient-fault schedule into the global
`FaultRegistry` (store conflicts, dispatcher flakes, per-binding bind
errors, a guaranteed burst of device-collect failures, dropped watch
deliveries, create latency), runs a create/schedule/delete churn workload
with the TPU wave pipeline + async dispatcher on, then disarms and drives
the scheduler to convergence. The pass criteria are the degradation
ladder's whole contract:

- every surviving pod is bound (nothing stranded by a dropped event or a
  failed bind — retry, wave isolation, and informer resync absorbed it),
- no leaked cache assumes (reconciliation/failure paths forgot every
  half-applied bind),
- the TPU circuit breaker tripped AND recovered at least once (the
  collect-fault burst is sized to guarantee both),
- the queue is empty.

Everything replays from one seed: the registry's per-spec rng streams are
derived from it, so `python -m kubernetes_tpu.testing.chaos --seed 7`
fails (or passes) identically run after run.
"""

from __future__ import annotations

import dataclasses
import time

from ..store.store import ConflictError, Store
from ..utils import faultinject
from ..utils.faultinject import DROP, ERROR, LATENCY, FaultSpec
from .wrappers import make_node, make_pod


def standard_schedule(registry: faultinject.FaultRegistry) -> None:
    """Register the soak's transient-fault schedule (registry still owns
    arming). Bounded `times` on every spec: the workload must outlive the
    schedule, so convergence is eventually fault-free."""
    # async dispatcher call flakes: absorbed by bounded retry + backoff
    registry.register(FaultSpec(
        "dispatcher.execute", mode=ERROR, transient=True,
        probability=0.15, times=40, message="dispatcher flake"))
    # store write conflicts (the real 409 shape): also retried
    registry.register(FaultSpec(
        "store.update", mode=ERROR, probability=0.2, times=30,
        exc=ConflictError, message="injected conflict"))
    # per-binding failures inside the wave transaction: wave siblings'
    # bindings must land while the victim is retried alone
    registry.register(FaultSpec(
        "store.bind_pod", mode=ERROR, transient=True,
        probability=0.1, times=20, message="bind flake"))
    # guaranteed consecutive device-collect failures: trips the breaker
    # (threshold 3), then one failed probe re-opens it, then exhaustion
    # lets the probe waves through — trip AND recovery are certain
    registry.register(FaultSpec(
        "tpu.collect", mode=ERROR, transient=True,
        start_after=6, times=4, message="device flake"))
    # lossy watch stream: informer resync must repair the cache
    registry.register(FaultSpec(
        "watch.deliver", mode=DROP, probability=0.05, times=50))
    # creation latency: jitters event arrival order
    registry.register(FaultSpec(
        "store.create", mode=LATENCY, probability=0.05, times=20,
        latency_s=0.001))


@dataclasses.dataclass
class SoakReport:
    seed: int
    rounds: int
    created: int = 0
    bound: int = 0
    unbound: int = 0
    leaked_assumes: int = 0
    queue_pending: int = 0
    breaker_trips: int = 0
    breaker_recoveries: int = 0
    faults_fired: int = 0
    retries: int = 0
    resync_repairs: int = 0

    @property
    def ok(self) -> bool:
        return (
            self.unbound == 0
            and self.leaked_assumes == 0
            and self.queue_pending == 0
            and self.breaker_trips >= 1
            and self.breaker_recoveries >= 1
            and self.faults_fired > 0
        )

    def render(self) -> str:
        verdict = "PASS" if self.ok else "FAIL"
        return (
            f"chaos soak [{verdict}] seed={self.seed} rounds={self.rounds}: "
            f"created={self.created} bound={self.bound} "
            f"unbound={self.unbound} leaked_assumes={self.leaked_assumes} "
            f"queue_pending={self.queue_pending} "
            f"breaker_trips={self.breaker_trips} "
            f"breaker_recoveries={self.breaker_recoveries} "
            f"faults_fired={self.faults_fired} retries={self.retries} "
            f"resync_repairs={self.resync_repairs}"
        )


def run_soak(seed: int = 7, rounds: int = 6, pods_per_round: int = 24,
             nodes: int = 32, wave_size: int = 16,
             breaker_cooldown_s: float = 0.05) -> SoakReport:
    """One full seeded soak; leaves the global registry disarmed + reset."""
    from ..scheduler import Profile, Scheduler
    from ..scheduler.metrics import SchedulerMetrics

    report = SoakReport(seed=seed, rounds=rounds)
    registry = faultinject.registry()
    registry.reset(seed=seed)
    standard_schedule(registry)

    store = Store()
    for i in range(nodes):
        store.create(make_node(f"n{i}", cpu="16", mem="32Gi",
                               zone=f"z{i % 4}"))
    sched = Scheduler(
        store,
        profiles=[Profile(backend="tpu", wave_size=wave_size)],
        feature_gates={"SchedulerAsyncAPICalls": True},
        async_api_calls=True,
        metrics=SchedulerMetrics(),
        seed=seed,
    )
    # shrink the breaker cooldown so trip -> probe -> recovery fits inside
    # the soak's wall clock (production default is 1s)
    algo = next(iter(sched.algorithms.values()))
    algo.breaker.cooldown_s = breaker_cooldown_s
    # shrink pod error backoff the same way: injected failures put pods in
    # the error-backoff tier, whose expiry pop-from-backoff never
    # short-circuits (it protects the apiserver) — production windows of
    # 1-10s would dominate the soak's wall clock
    sched.queue._initial_backoff = 0.02
    sched.queue._max_backoff = 0.1
    sched.start()

    registry.arm()
    seq = 0
    try:
        for round_no in range(rounds):
            for _ in range(pods_per_round):
                store.create(make_pod(f"chaos-{seq}", cpu="100m",
                                      mem="64Mi"))
                seq += 1
            sched.schedule_pending()
            # voluntary churn: delete a slice of bound pods
            bound = [p for p in store.pods() if p.spec.node_name]
            for p in bound[: pods_per_round // 4]:
                store.delete("Pod", p.meta.key)
            sched.schedule_pending()
    finally:
        registry.disarm()
    report.created = seq
    report.faults_fired = registry.fired_total

    # fault-free convergence: everything the schedule disturbed must now
    # settle — error backoffs expire, resync repairs dropped deliveries,
    # requeued pods schedule
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        sched.schedule_pending()
        pending = [p for p in store.pods() if not p.spec.node_name]
        active, backoff, unsched = sched.queue.pending_pods()
        if (not pending and sched.cache.assumed_pod_count() == 0
                and active + backoff + unsched == 0):
            break
        time.sleep(0.05)

    pods = store.pods()
    report.bound = sum(1 for p in pods if p.spec.node_name)
    report.unbound = len(pods) - report.bound
    report.leaked_assumes = sched.cache.assumed_pod_count()
    active, backoff, unsched = sched.queue.pending_pods()
    report.queue_pending = active + backoff + unsched
    report.breaker_trips = algo.breaker.trip_count
    report.breaker_recoveries = algo.breaker.recovery_count
    report.retries = sched.api_dispatcher.retries
    report.resync_repairs = sched.informers.resync_all()
    sched.api_dispatcher.close()
    registry.reset()
    return report


def main(argv: list[str] | None = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m kubernetes_tpu.testing.chaos",
        description="Seeded chaos soak for the TPU scheduler "
                    "(deterministic fault schedule, convergence asserted)",
    )
    parser.add_argument("--seed", type=int, default=7)
    parser.add_argument("--rounds", type=int, default=6)
    parser.add_argument("--pods-per-round", type=int, default=24)
    parser.add_argument("--nodes", type=int, default=32)
    parser.add_argument("--wave-size", type=int, default=16)
    args = parser.parse_args(argv)

    report = run_soak(seed=args.seed, rounds=args.rounds,
                      pods_per_round=args.pods_per_round,
                      nodes=args.nodes, wave_size=args.wave_size)
    print(report.render())
    return 0 if report.ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
