"""Seeded chaos soak: the scale-churn workload under a recorded fault
schedule, asserting convergence.

The harness wires a standard transient-fault schedule into the global
`FaultRegistry` (store conflicts, dispatcher flakes, per-binding bind
errors, a guaranteed burst of device-collect failures, dropped watch
deliveries, create latency), runs a create/schedule/delete churn workload
with the TPU wave pipeline + async dispatcher on, then disarms and drives
the scheduler to convergence. The pass criteria are the degradation
ladder's whole contract:

- every surviving pod is bound (nothing stranded by a dropped event or a
  failed bind — retry, wave isolation, and informer resync absorbed it),
- no leaked cache assumes (reconciliation/failure paths forgot every
  half-applied bind),
- the TPU circuit breaker tripped AND recovered at least once (the
  collect-fault burst is sized to guarantee both),
- the queue is empty.

Everything replays from one seed: the registry's per-spec rng streams are
derived from it, so `python -m kubernetes_tpu.testing.chaos --seed 7`
fails (or passes) identically run after run.
"""

from __future__ import annotations

import dataclasses
import math
import random
import time

from ..store.store import ConflictError, Store
from ..utils import faultinject
from ..utils.faultinject import DROP, ERROR, LATENCY, PARTITION, FaultSpec
from .wrappers import make_node, make_pod


def standard_schedule(registry: faultinject.FaultRegistry) -> None:
    """Register the soak's transient-fault schedule (registry still owns
    arming). Bounded `times` on every spec: the workload must outlive the
    schedule, so convergence is eventually fault-free."""
    # async dispatcher call flakes: absorbed by bounded retry + backoff
    registry.register(FaultSpec(
        "dispatcher.execute", mode=ERROR, transient=True,
        probability=0.15, times=40, message="dispatcher flake"))
    # store write conflicts (the real 409 shape): also retried
    registry.register(FaultSpec(
        "store.update", mode=ERROR, probability=0.2, times=30,
        exc=ConflictError, message="injected conflict"))
    # per-binding failures inside the wave transaction: wave siblings'
    # bindings must land while the victim is retried alone
    registry.register(FaultSpec(
        "store.bind_pod", mode=ERROR, transient=True,
        probability=0.1, times=20, message="bind flake"))
    # guaranteed consecutive device-collect failures: trips the breaker
    # (threshold 3), then one failed probe re-opens it, then exhaustion
    # lets the probe waves through — trip AND recovery are certain
    registry.register(FaultSpec(
        "tpu.collect", mode=ERROR, transient=True,
        start_after=6, times=4, message="device flake"))
    # lossy watch stream: informer resync must repair the cache
    registry.register(FaultSpec(
        "watch.deliver", mode=DROP, probability=0.05, times=50))
    # creation latency: jitters event arrival order
    registry.register(FaultSpec(
        "store.create", mode=LATENCY, probability=0.05, times=20,
        latency_s=0.001))


@dataclasses.dataclass
class SoakReport:
    seed: int
    rounds: int
    created: int = 0
    bound: int = 0
    unbound: int = 0
    leaked_assumes: int = 0
    queue_pending: int = 0
    breaker_trips: int = 0
    breaker_recoveries: int = 0
    faults_fired: int = 0
    retries: int = 0
    resync_repairs: int = 0

    @property
    def ok(self) -> bool:
        return (
            self.unbound == 0
            and self.leaked_assumes == 0
            and self.queue_pending == 0
            and self.breaker_trips >= 1
            and self.breaker_recoveries >= 1
            and self.faults_fired > 0
        )

    def render(self) -> str:
        verdict = "PASS" if self.ok else "FAIL"
        return (
            f"chaos soak [{verdict}] seed={self.seed} rounds={self.rounds}: "
            f"created={self.created} bound={self.bound} "
            f"unbound={self.unbound} leaked_assumes={self.leaked_assumes} "
            f"queue_pending={self.queue_pending} "
            f"breaker_trips={self.breaker_trips} "
            f"breaker_recoveries={self.breaker_recoveries} "
            f"faults_fired={self.faults_fired} retries={self.retries} "
            f"resync_repairs={self.resync_repairs}"
        )


def run_soak(seed: int = 7, rounds: int = 6, pods_per_round: int = 24,
             nodes: int = 32, wave_size: int = 16,
             breaker_cooldown_s: float = 0.05) -> SoakReport:
    """One full seeded soak; leaves the global registry disarmed + reset."""
    from ..scheduler import Profile, Scheduler
    from ..scheduler.metrics import SchedulerMetrics

    report = SoakReport(seed=seed, rounds=rounds)
    registry = faultinject.registry()
    registry.reset(seed=seed)
    standard_schedule(registry)

    store = Store()
    for i in range(nodes):
        store.create(make_node(f"n{i}", cpu="16", mem="32Gi",
                               zone=f"z{i % 4}"))
    sched = Scheduler(
        store,
        profiles=[Profile(backend="tpu", wave_size=wave_size)],
        feature_gates={"SchedulerAsyncAPICalls": True},
        async_api_calls=True,
        metrics=SchedulerMetrics(),
        seed=seed,
    )
    # shrink the breaker cooldown so trip -> probe -> recovery fits inside
    # the soak's wall clock (production default is 1s)
    algo = next(iter(sched.algorithms.values()))
    algo.breaker.cooldown_s = breaker_cooldown_s
    # shrink pod error backoff the same way: injected failures put pods in
    # the error-backoff tier, whose expiry pop-from-backoff never
    # short-circuits (it protects the apiserver) — production windows of
    # 1-10s would dominate the soak's wall clock
    sched.queue._initial_backoff = 0.02
    sched.queue._max_backoff = 0.1
    sched.start()

    registry.arm()
    seq = 0
    try:
        for round_no in range(rounds):
            for _ in range(pods_per_round):
                store.create(make_pod(f"chaos-{seq}", cpu="100m",
                                      mem="64Mi"))
                seq += 1
            sched.schedule_pending()
            # voluntary churn: delete a slice of bound pods
            bound = [p for p in store.pods() if p.spec.node_name]
            for p in bound[: pods_per_round // 4]:
                store.delete("Pod", p.meta.key)
            sched.schedule_pending()
    finally:
        registry.disarm()
    report.created = seq
    report.faults_fired = registry.fired_total

    # fault-free convergence: everything the schedule disturbed must now
    # settle — error backoffs expire, resync repairs dropped deliveries,
    # requeued pods schedule
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        sched.schedule_pending()
        pending = [p for p in store.pods() if not p.spec.node_name]
        active, backoff, unsched = sched.queue.pending_pods()
        if (not pending and sched.cache.assumed_pod_count() == 0
                and active + backoff + unsched == 0):
            break
        time.sleep(0.05)

    pods = store.pods()
    report.bound = sum(1 for p in pods if p.spec.node_name)
    report.unbound = len(pods) - report.bound
    report.leaked_assumes = sched.cache.assumed_pod_count()
    active, backoff, unsched = sched.queue.pending_pods()
    report.queue_pending = active + backoff + unsched
    report.breaker_trips = algo.breaker.trip_count
    report.breaker_recoveries = algo.breaker.recovery_count
    report.retries = sched.api_dispatcher.retries
    report.resync_repairs = sched.informers.resync_all()
    sched.api_dispatcher.close()
    registry.reset()
    return report


# -- arrival-trace soak: production-shaped load + the full fleet ---------------


@dataclasses.dataclass
class ArrivalTrace:
    """Seeded, replayable Poisson arrival process with periodic burst
    windows — the millions-of-users load shape (ROADMAP item 3) instead of
    batch-dumping pods. `arrivals()` returns sorted virtual timestamps;
    the same seed replays the same trace, independent of everything else
    (its rng stream is its own, not the fault registry's).

    `shape` selects the rate curve: "burst" (the default — identical draws
    to the original trace, the chaos soaks replay on it), "poisson"
    (constant rate), or "diurnal" (sinusoidal day-curve over
    `diurnal_period` virtual seconds). The rng stream derivation is shared,
    so the same seed at a different shape is a different — but equally
    replayable — trace."""

    seed: int
    pods: int = 96
    rate: float = 120.0        # base arrivals per virtual second
    burst_every: float = 0.5   # a burst window opens each period...
    burst_len: float = 0.1     # ...and lasts this long...
    burst_factor: float = 4.0  # ...at this rate multiple
    shape: str = "burst"       # "burst" | "poisson" | "diurnal"
    diurnal_period: float = 2.0  # virtual seconds per diurnal cycle

    def arrivals(self) -> list[float]:
        rng = random.Random(f"{self.seed}:arrival-trace")
        out: list[float] = []
        t = 0.0
        while len(out) < self.pods:
            if self.shape == "poisson":
                lam = self.rate
            elif self.shape == "diurnal":
                # day-curve: rate swings between 25% and 175% of base
                lam = self.rate * (1.0 + 0.75 * math.sin(
                    2.0 * math.pi * t / self.diurnal_period))
                lam = max(lam, self.rate * 0.25)
            else:  # "burst" — bit-identical to the original formula
                in_burst = (t % self.burst_every) < self.burst_len
                lam = self.rate * (self.burst_factor if in_burst else 1.0)
            t += rng.expovariate(lam)
            out.append(t)
        return out


def trace_schedule(registry: faultinject.FaultRegistry, nodes: int,
                   outage_start_tick: int, outage_ticks: int) -> None:
    """The trace soak's fault schedule: the tentpole trio — a long-lived
    watch-stream PARTITION, a full-fleet kubelet outage window (AZ-outage
    shaped: every sync in [start, start+len) ticks is dropped, so leases
    go stale together), and bind LATENCY riding the new commit seam —
    plus the breaker-burst and light transient flakes from the standard
    schedule so the load shape stays production-like."""
    # long-lived revision-range gap: opens once, swallows a contiguous run
    # of deliveries across every watcher; the informers must detect it
    # from revision continuity — there is no error to react to
    registry.register(FaultSpec(
        "watch.partition", mode=PARTITION, start_after=200, window=400,
        times=1))
    # kubelet death mid-wave: sync visits go round-robin (one per kubelet
    # per tick), so a [start*n, (start+len)*n) visit window is a fleet-wide
    # outage measured in driver ticks
    registry.register(FaultSpec(
        "kubelet.sync", mode=DROP, start_after=outage_start_tick * nodes,
        times=outage_ticks * nodes))
    # injected latency inside the bind transaction: with the
    # prepare/commit seam this sleeps OUTSIDE the store lock, so readers
    # (kubelet relists, controller reconciles) proceed — the soak's
    # wall-clock budget is the regression tripwire
    registry.register(FaultSpec(
        "store.bind_pod", mode=LATENCY, probability=0.15, times=12,
        latency_s=0.02))
    # guaranteed breaker trip + recovery (same shape as standard_schedule)
    registry.register(FaultSpec(
        "tpu.collect", mode=ERROR, transient=True,
        start_after=4, times=4, message="device flake"))
    # light production noise: call flakes, write conflicts, lossy watch
    registry.register(FaultSpec(
        "dispatcher.execute", mode=ERROR, transient=True,
        probability=0.1, times=20, message="dispatcher flake"))
    registry.register(FaultSpec(
        "store.update", mode=ERROR, probability=0.05, times=15,
        exc=ConflictError, message="injected conflict"))
    registry.register(FaultSpec(
        "watch.deliver", mode=DROP, probability=0.03, times=30))


@dataclasses.dataclass
class TraceSoakReport(SoakReport):
    partitions_detected: int = 0
    partition_repairs: int = 0
    partition_repair_latency_s: float = 0.0
    kubelet_outage_drops: int = 0
    nodes_unreachable_seen: int = 0
    evicted: int = 0
    wall_clock_s: float = 0.0
    budget_s: float = 0.0
    # stall attribution (stallprofiler.py): every completed wave must
    # decompose into overlap + named stalls covering >=95% of its wall
    stall_waves: int = 0
    stall_coverage_min: float = 0.0
    stall_flush_events: int = 0

    @property
    def ok(self) -> bool:  # type: ignore[override]
        return (
            SoakReport.ok.fget(self)  # type: ignore[attr-defined]
            and self.partitions_detected >= 1
            and self.partition_repairs >= 1
            and self.kubelet_outage_drops >= 1
            and self.nodes_unreachable_seen >= 1
            # the outage must actually bite (bound pods evicted) AND the
            # cluster must come back (late arrivals bound after recovery)
            and self.evicted >= 1
            and self.bound >= 1
            and self.wall_clock_s <= self.budget_s
            # the profiler must have attributed EVERY wave's wall time
            # (coverage invariant holds under chaos, not just clean runs),
            # and a breaker trip must leave a 'flush' stall footprint —
            # the trip drains the inflight wave, and that drain is a stall
            and self.stall_waves >= 1
            and self.stall_coverage_min >= 0.95
            and (self.breaker_trips < 1 or self.stall_flush_events >= 1)
        )

    def render(self) -> str:
        verdict = "PASS" if self.ok else "FAIL"
        return (
            f"trace soak [{verdict}] seed={self.seed}: "
            f"created={self.created} bound={self.bound} "
            f"unbound={self.unbound} evicted={self.evicted} "
            f"leaked_assumes={self.leaked_assumes} "
            f"queue_pending={self.queue_pending} "
            f"breaker_trips={self.breaker_trips} "
            f"breaker_recoveries={self.breaker_recoveries} "
            f"partitions_detected={self.partitions_detected} "
            f"partition_repairs={self.partition_repairs} "
            f"partition_repair_latency_s="
            f"{self.partition_repair_latency_s:.4f} "
            f"kubelet_outage_drops={self.kubelet_outage_drops} "
            f"nodes_unreachable_seen={self.nodes_unreachable_seen} "
            f"faults_fired={self.faults_fired} retries={self.retries} "
            f"stall_waves={self.stall_waves} "
            f"stall_coverage_min={self.stall_coverage_min:.4f} "
            f"stall_flush_events={self.stall_flush_events} "
            f"wall_clock_s={self.wall_clock_s:.2f} (budget {self.budget_s})"
        )


def run_trace_soak(seed: int = 7, pods: int = 96, nodes: int = 12,
                   wave_size: int = 16, tick_s: float = 0.02,
                   grace_period_s: float = 0.35,
                   outage_start_tick: int = 10, outage_ticks: int = 30,
                   breaker_cooldown_s: float = 0.05,
                   budget_s: float = 60.0) -> TraceSoakReport:
    """Chaos under a production-shaped arrival trace, against the WHOLE
    control loop: every node runs a hollow kubelet (heartbeating a lease),
    the node-lifecycle controller monitors lease staleness, and the fault
    schedule kills the entire kubelet fleet mid-trace, opens a watch
    partition, and injects bind latency. Converges iff the scheduler,
    informers (partition self-heal), lifecycle controller (taint/evict),
    and breaker (trip + recover) all do their jobs — at arrival-trace load,
    not synthetic churn. Leaves the global registry disarmed + reset."""
    from ..controllers.lifecycle import (
        UNREACHABLE_TAINT,
        NodeLifecycleController,
    )
    from ..kubelet.hollow import HollowKubelet
    from ..scheduler import Profile, Scheduler
    from ..scheduler.metrics import SchedulerMetrics

    report = TraceSoakReport(seed=seed, rounds=1, budget_s=budget_s)
    t_start = time.monotonic()
    registry = faultinject.registry()
    registry.reset(seed=seed)
    trace_schedule(registry, nodes=nodes,
                   outage_start_tick=outage_start_tick,
                   outage_ticks=outage_ticks)

    store = Store()
    metrics = SchedulerMetrics()
    sched = Scheduler(
        store,
        profiles=[Profile(backend="tpu", wave_size=wave_size)],
        feature_gates={"SchedulerAsyncAPICalls": True},
        async_api_calls=True,
        metrics=metrics,
        seed=seed,
    )
    algo = next(iter(sched.algorithms.values()))
    algo.breaker.cooldown_s = breaker_cooldown_s
    sched.queue._initial_backoff = 0.02
    sched.queue._max_backoff = 0.1

    # the fleet: EVERY node gets a kubelet — the lifecycle controller
    # taints any node without a fresh lease, so a node without an agent
    # would be evicted as collateral instead of by the injected outage
    kubelets = []
    for i in range(nodes):
        node = make_node(f"tn{i}", cpu="16", mem="32Gi", zone=f"z{i % 4}")
        k = HollowKubelet(store, node)
        k.register()
        kubelets.append(k)
    lifecycle = NodeLifecycleController(store)
    lifecycle.grace_period = grace_period_s
    lifecycle.start()
    lifecycle.sweep()
    sched.start()

    trace = ArrivalTrace(seed=seed, pods=pods)
    arrivals = trace.arrivals()
    # the trace plays out in wall time (leases are wall-clock state); run
    # enough ticks to cover the trace AND the outage + grace expiry
    total_ticks = max(
        int(arrivals[-1] / tick_s) + 1,
        outage_start_tick + outage_ticks + int(grace_period_s / tick_s) + 10,
    )
    registry.arm()
    created = 0
    try:
        for tick in range(total_ticks):
            virtual_now = tick * tick_s
            while created < len(arrivals) and arrivals[created] <= virtual_now:
                store.create(make_pod(f"trace-{created}", cpu="100m",
                                      mem="64Mi"))
                created += 1
            for k in kubelets:
                k.sync_once()
            lifecycle.sync_once()
            sched.schedule_pending()
            unreachable = sum(
                1 for n in store.nodes()
                if any(t.key == UNREACHABLE_TAINT for t in n.spec.taints)
            )
            report.nodes_unreachable_seen = max(
                report.nodes_unreachable_seen, unreachable
            )
            time.sleep(tick_s)
    finally:
        registry.disarm()
    report.created = created
    report.faults_fired = registry.fired_total
    report.kubelet_outage_drops = registry.fired_by_point["kubelet.sync"]

    # fault-free convergence: kubelets heartbeat again, the lifecycle
    # controller un-taints recovered nodes, stranded/backoff pods bind
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        for k in kubelets:
            k.sync_once()
        lifecycle.sync_once()
        sched.schedule_pending()
        pending = [p for p in store.pods() if not p.spec.node_name]
        active, backoff, unsched = sched.queue.pending_pods()
        if (not pending and sched.cache.assumed_pod_count() == 0
                and active + backoff + unsched == 0):
            break
        time.sleep(0.02)

    pods_now = store.pods()
    report.bound = sum(1 for p in pods_now if p.spec.node_name)
    report.unbound = len(pods_now) - report.bound
    report.evicted = created - len(pods_now)
    report.leaked_assumes = sched.cache.assumed_pod_count()
    active, backoff, unsched = sched.queue.pending_pods()
    report.queue_pending = active + backoff + unsched
    report.breaker_trips = algo.breaker.trip_count
    report.breaker_recoveries = algo.breaker.recovery_count
    report.retries = sched.api_dispatcher.retries
    partition_events = list(sched.flight_recorder.partition_events)
    report.partitions_detected = len(partition_events)
    report.partition_repairs = sum(ev[1] for ev in partition_events)
    report.partition_repair_latency_s = max(
        (ev[2] for ev in partition_events), default=0.0
    )
    report.resync_repairs = report.partition_repairs
    # stall attribution under chaos: every retained wave record must carry
    # a >=95%-coverage decomposition, and the guaranteed breaker trip must
    # have stamped at least one 'flush' stall (the trip's pipeline drain)
    profiler = sched.flight_recorder.stall_profiler
    wave_records = sched.flight_recorder.records()
    report.stall_waves = profiler.waves_profiled
    report.stall_coverage_min = min(
        (r.stall_coverage for r in wave_records), default=0.0)
    report.stall_flush_events = profiler.stall_events.get("flush", 0)
    sched.api_dispatcher.close()
    registry.reset()
    report.wall_clock_s = time.monotonic() - t_start
    return report


# -- gang soak: kubelet killed mid-gang, all-or-nothing must hold --------------


def gang_schedule(registry: faultinject.FaultRegistry) -> None:
    """Transient flakes aimed at the gang binding window: per-binding bind
    errors and dispatcher flakes land INSIDE a gang's member-by-member
    bind fan-out, store conflicts hit the status writes. Bounded times, so
    convergence is eventually fault-free."""
    registry.register(FaultSpec(
        "store.bind_pod", mode=ERROR, transient=True,
        probability=0.15, times=12, message="bind flake"))
    registry.register(FaultSpec(
        "dispatcher.execute", mode=ERROR, transient=True,
        probability=0.1, times=20, message="dispatcher flake"))
    registry.register(FaultSpec(
        "store.update", mode=ERROR, probability=0.1, times=15,
        exc=ConflictError, message="injected conflict"))


@dataclasses.dataclass
class GangSoakReport:
    seed: int
    gangs: int
    created: int = 0
    bound: int = 0
    unbound: int = 0
    evicted: int = 0
    recreated: int = 0
    partial_gangs_final: int = 0
    zone_violations: int = 0
    leaked_assumes: int = 0
    queue_pending: int = 0
    device_gang_pods: int = 0
    host_gang_pods: int = 0
    faults_fired: int = 0
    wall_clock_s: float = 0.0

    @property
    def ok(self) -> bool:
        return (
            self.unbound == 0
            and self.partial_gangs_final == 0
            and self.zone_violations == 0
            and self.leaked_assumes == 0
            and self.queue_pending == 0
            # the kill must bite (members evicted + recreated) and the
            # device gang path must have actually carried groups
            and self.evicted >= 1
            and self.recreated >= 1
            and self.device_gang_pods >= 1
            and self.faults_fired > 0
        )

    def render(self) -> str:
        verdict = "PASS" if self.ok else "FAIL"
        return (
            f"gang soak [{verdict}] seed={self.seed} gangs={self.gangs}: "
            f"created={self.created} bound={self.bound} "
            f"unbound={self.unbound} evicted={self.evicted} "
            f"recreated={self.recreated} "
            f"partial_gangs_final={self.partial_gangs_final} "
            f"zone_violations={self.zone_violations} "
            f"leaked_assumes={self.leaked_assumes} "
            f"queue_pending={self.queue_pending} "
            f"device_gang_pods={self.device_gang_pods} "
            f"host_gang_pods={self.host_gang_pods} "
            f"faults_fired={self.faults_fired} "
            f"wall_clock_s={self.wall_clock_s:.2f}"
        )


def run_gang_soak(seed: int = 7, gangs: int = 6, min_count: int = 3,
                  nodes: int = 12, zones: int = 3, rounds: int = 3,
                  kill_round: int = 1, tick_s: float = 0.02,
                  grace_period_s: float = 0.3) -> GangSoakReport:
    """Kubelet killed mid-gang (README "Gang waves" runbook): PodGroups
    with Required/Preferred/no topology arrive in rounds under bind and
    dispatcher flakes; right after one round's waves dispatch (async binds
    still in flight) a node agent hosting a gang member stops
    heartbeating. The lifecycle controller taints + evicts, a minimal
    workload controller recreates the missing members, and after
    fault-free convergence the all-or-nothing contract must hold: every
    gang fully bound, no gang partially placed, Required gangs in exactly
    one zone (the requiredDomain pin re-anchors recreated members into the
    surviving siblings' domain). Leaves the registry disarmed + reset."""
    from ..api.meta import ObjectMeta
    from ..api.types import (
        GangPolicy,
        PodGroup,
        PodGroupSpec,
        SchedulingConstraints,
        TopologyConstraint,
    )
    from ..controllers.lifecycle import NodeLifecycleController
    from ..kubelet.hollow import HollowKubelet
    from ..scheduler import Profile, Scheduler
    from ..scheduler.metrics import SchedulerMetrics
    from .wrappers import with_gang

    ZONE_KEY = "topology.kubernetes.io/zone"
    report = GangSoakReport(seed=seed, gangs=gangs)
    t_start = time.monotonic()
    registry = faultinject.registry()
    registry.reset(seed=seed)
    gang_schedule(registry)

    store = Store()
    sched = Scheduler(
        store,
        profiles=[Profile(backend="tpu", wave_size=8)],
        feature_gates={"GenericWorkload": True,
                       "TopologyAwareWorkloadScheduling": True,
                       "SchedulerAsyncAPICalls": True},
        async_api_calls=True,
        metrics=SchedulerMetrics(),
        seed=seed,
    )
    sched.queue._initial_backoff = 0.02
    sched.queue._max_backoff = 0.1

    kubelets = []
    for i in range(nodes):
        node = make_node(f"gn{i}", cpu="16", mem="32Gi",
                         zone=f"z{i % zones}")
        k = HollowKubelet(store, node)
        k.register()
        kubelets.append(k)
    lifecycle = NodeLifecycleController(store)
    lifecycle.grace_period = grace_period_s
    lifecycle.start()
    lifecycle.sweep()
    sched.start()

    gang_specs: dict[str, tuple[int, str | None]] = {}
    killed: set[str] = set()

    def member_name(gang: str, i: int) -> str:
        return f"{gang}-m{i}"

    def make_member(gang: str, i: int):
        return with_gang(make_pod(member_name(gang, i), cpu="200m",
                                  mem="128Mi"), gang)

    def recreate_missing() -> None:
        """The workload controller's job: evicted gang members come back
        (same name, fresh object) so the gang can re-reach quorum."""
        have = {p.meta.name for p in store.pods()}
        for gang, (size, _mode) in gang_specs.items():
            for i in range(size):
                if member_name(gang, i) not in have:
                    store.create(make_member(gang, i))
                    report.recreated += 1

    def drive(ticks: int) -> None:
        for _ in range(ticks):
            for k in kubelets:
                if k.node_name not in killed:
                    k.sync_once()
            lifecycle.sync_once()
            recreate_missing()
            sched.schedule_pending()
            time.sleep(tick_s)

    registry.arm()
    g = 0
    try:
        for rnd in range(rounds):
            per_round = gangs // rounds + (1 if rnd < gangs % rounds else 0)
            for _ in range(per_round):
                mode = ("Required", "Preferred", None)[g % 3]
                constraints = SchedulingConstraints()
                if mode is not None:
                    constraints = SchedulingConstraints(topology=(
                        TopologyConstraint(key=ZONE_KEY, mode=mode),))
                gang = f"gang-{g}"
                store.create(PodGroup(
                    meta=ObjectMeta(name=gang),
                    spec=PodGroupSpec(
                        policy=GangPolicy(min_count=min_count),
                        constraints=constraints),
                ))
                gang_specs[gang] = (min_count, mode)
                for i in range(min_count):
                    store.create(make_member(gang, i))
                g += 1
            report.created += per_round * min_count
            sched.schedule_pending()
            if rnd == kill_round:
                # mid-gang kubelet kill: async binds of this round's gangs
                # may still be in flight; the victim hosts a gang member
                victim = next(
                    (p.spec.node_name for p in store.pods()
                     if p.spec.scheduling_group is not None
                     and p.spec.node_name), kubelets[0].node_name)
                killed.add(victim)
            drive(ticks=int(grace_period_s / tick_s) + 8)
    finally:
        registry.disarm()
    report.faults_fired = registry.fired_total

    # fault-free convergence: evictions drain, recreated members re-reach
    # quorum, every gang binds whole
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        for k in kubelets:
            if k.node_name not in killed:
                k.sync_once()
        lifecycle.sync_once()
        recreate_missing()
        sched.schedule_pending()
        pending = [p for p in store.pods() if not p.spec.node_name]
        active, backoff, unsched = sched.queue.pending_pods()
        if (not pending and sched.cache.assumed_pod_count() == 0
                and active + backoff + unsched == 0):
            break
        time.sleep(tick_s)

    node_zone = {n.meta.name: n.meta.labels.get(ZONE_KEY)
                 for n in store.nodes()}
    pods_now = {p.meta.name: p for p in store.pods()}
    report.bound = sum(1 for p in pods_now.values() if p.spec.node_name)
    report.unbound = len(pods_now) - report.bound
    total_members = sum(size for size, _ in gang_specs.values())
    report.evicted = report.recreated  # every recreation followed an eviction
    for gang, (size, mode) in gang_specs.items():
        hosts = [pods_now[member_name(gang, i)].spec.node_name
                 for i in range(size) if member_name(gang, i) in pods_now]
        n_bound = sum(1 for h in hosts if h)
        if n_bound not in (0, size):
            report.partial_gangs_final += 1
        if mode == "Required" and n_bound == size:
            if len({node_zone.get(h) for h in hosts}) > 1:
                report.zone_violations += 1
    report.created = max(report.created, total_members)
    report.leaked_assumes = sched.cache.assumed_pod_count()
    active, backoff, unsched = sched.queue.pending_pods()
    report.queue_pending = active + backoff + unsched
    totals = sched.flight_recorder.gang_pod_totals
    report.device_gang_pods = totals.get("device", 0)
    report.host_gang_pods = totals.get("host", 0)
    sched.api_dispatcher.close()
    registry.reset()
    report.wall_clock_s = time.monotonic() - t_start
    return report


# -- restart storm: seeded scheduler crashes mid-traffic -----------------------


# one crash per cycle, rotating through the three mid-flight windows the
# reconcile contract hardens: mid-wave (collected, not finished), inside
# the bind-commit window (store bind landed, queue/cache not settled),
# and mid-gang-permit (every member assumed, nothing dispatched)
CRASH_POINTS = ("loop.wave", "loop.bind_commit", "gang.permit")


@dataclasses.dataclass
class RestartSoakReport:
    seed: int
    cycles: int
    crashes: int = 0
    crash_points: tuple = ()
    created: int = 0
    bound: int = 0
    unbound: int = 0
    double_binds: int = 0
    leaked_assumes: int = 0
    partial_gangs_final: int = 0
    queue_pending: int = 0
    warm_compiles: int = 0
    recoveries: dict = dataclasses.field(default_factory=dict)
    faults_fired: int = 0
    wall_clock_s: float = 0.0

    @property
    def ok(self) -> bool:
        return (
            self.crashes >= self.cycles
            and self.unbound == 0
            and self.double_binds == 0
            and self.leaked_assumes == 0
            and self.partial_gangs_final == 0
            and self.queue_pending == 0
            # every warm-restarted scheduler must re-enter service without
            # compiling anything the warmup phase didn't already lower
            and self.warm_compiles == 0
        )

    def render(self) -> str:
        verdict = "PASS" if self.ok else "FAIL"
        rec = ",".join(f"{k}={v}" for k, v in sorted(self.recoveries.items()))
        return (
            f"restart soak [{verdict}] seed={self.seed} "
            f"cycles={self.cycles}: crashes={self.crashes} "
            f"points={'/'.join(self.crash_points)} "
            f"created={self.created} bound={self.bound} "
            f"unbound={self.unbound} double_binds={self.double_binds} "
            f"leaked_assumes={self.leaked_assumes} "
            f"partial_gangs_final={self.partial_gangs_final} "
            f"queue_pending={self.queue_pending} "
            f"warm_compiles={self.warm_compiles} "
            f"recoveries=[{rec}] faults_fired={self.faults_fired} "
            f"wall_clock_s={self.wall_clock_s:.2f}"
        )


def run_restart_soak(seed: int = 7, cycles: int = 3, pods_per_cycle: int = 24,
                     min_count: int = 3, nodes: int = 16,
                     wave_size: int = 8) -> RestartSoakReport:
    """Seeded restart storm (README "Restart & recovery"): each cycle arms
    ONE seeded CRASH point mid-traffic, lets SchedulerCrashed rip through
    `schedule_pending`, tears the dead scheduler down ungracefully (the
    dispatcher's queued calls fail, its watches drop — no drain, no flush),
    and constructs a fresh warm-started scheduler over the same store.
    After the storm, fault-free convergence must restore every invariant:
    all pods bound exactly once (the store's bind path is the double-bind
    oracle), zero leaked assumes, per-gang all-or-nothing, and a
    compile-free warm restart (`compile_count_since_warm() == 0` on every
    restarted scheduler). Leaves the global registry disarmed + reset."""
    from ..api.meta import ObjectMeta
    from ..api.types import GangPolicy, PodGroup, PodGroupSpec
    from ..scheduler import Profile, Scheduler
    from ..scheduler.metrics import SchedulerMetrics
    from ..utils.faultinject import CRASH, SchedulerCrashed
    from .wrappers import with_gang

    report = RestartSoakReport(seed=seed, cycles=cycles)
    t_start = time.monotonic()
    registry = faultinject.registry()
    registry.reset(seed=seed)

    store = Store()
    for i in range(nodes):
        store.create(make_node(f"rn{i}", cpu="16", mem="32Gi",
                               zone=f"z{i % 4}"))

    # double-bind oracle: every SUCCESSFUL bind lands here; the soak never
    # deletes pods, so any key bound twice is a restart double-placing a
    # pod the crashed incarnation had already placed
    bind_ledger: dict[str, int] = {}
    orig_bind_pods, orig_bind_pod = store.bind_pods, store.bind_pod

    def ledgered_bind_pods(bindings):
        out = orig_bind_pods(bindings)
        for (key, _node), status in zip(bindings, out):
            if status == "bound":
                bind_ledger[key] = bind_ledger.get(key, 0) + 1
        return out

    def ledgered_bind_pod(key, node_name):
        obj = orig_bind_pod(key, node_name)
        bind_ledger[key] = bind_ledger.get(key, 0) + 1
        return obj

    store.bind_pods = ledgered_bind_pods
    store.bind_pod = ledgered_bind_pod

    def make_scheduler(warm: bool) -> Scheduler:
        s = Scheduler(
            store,
            profiles=[Profile(backend="tpu", wave_size=wave_size)],
            feature_gates={"GenericWorkload": True,
                           "SchedulerAsyncAPICalls": True},
            async_api_calls=True,
            metrics=SchedulerMetrics(),
            seed=seed,
            warm_start=warm,
        )
        s.queue._initial_backoff = 0.02
        s.queue._max_backoff = 0.1
        s.start()
        return s

    def crash_teardown(s: Scheduler) -> None:
        """Process death, in-process: no drain, no flush. Queued dispatcher
        calls die with DispatcherClosedError (the lost prepare/commit
        window), watch streams drop. Nothing here is allowed to rescue
        state — that is reconcile's job on the next incarnation."""
        try:
            s.api_dispatcher.close()
        except Exception:  # noqa: BLE001 — the corpse may be inconsistent
            pass
        try:
            s.informers.stop_all()
        except Exception:  # noqa: BLE001
            pass

    def collect_recoveries(s: Scheduler) -> None:
        for kind, n in list(s.flight_recorder.restart_events):
            report.recoveries[kind] = report.recoveries.get(kind, 0) + n

    sched = make_scheduler(warm=False)
    gang_specs: list[tuple[str, int]] = []
    seq = 0
    registry.arm()
    try:
        for cycle in range(cycles):
            point = CRASH_POINTS[cycle % len(CRASH_POINTS)]
            # aim past the visits the storm has already spent at this
            # point; one extra wave-shaped visit for the loop.* points so
            # the crash lands MID-traffic, not on its first wave
            visits = registry.snapshot()["visits"].get(point, 0)
            offset = 1 if point.startswith("loop.") else 0
            registry.register(FaultSpec(
                point, mode=CRASH, times=1, start_after=visits + offset,
                message="restart storm"))

            gang = f"rgang-{cycle}"
            store.create(PodGroup(
                meta=ObjectMeta(name=gang),
                spec=PodGroupSpec(policy=GangPolicy(min_count=min_count)),
            ))
            for i in range(min_count):
                store.create(with_gang(
                    make_pod(f"{gang}-m{i}", cpu="200m", mem="128Mi"), gang))
            gang_specs.append((gang, min_count))
            for _ in range(pods_per_cycle):
                store.create(make_pod(f"restart-{seq}", cpu="100m",
                                      mem="64Mi"))
                seq += 1
            report.created += min_count + pods_per_cycle

            try:
                sched.schedule_pending()
            except SchedulerCrashed:
                report.crashes += 1
                report.crash_points += (point,)
                if sched.warm_start:
                    # this incarnation was warm-started: it must not have
                    # compiled anything between its warmup and its death
                    report.warm_compiles += (
                        sched.flight_recorder.device_telemetry
                        .compile_count_since_warm())
                crash_teardown(sched)
                sched = make_scheduler(warm=True)
                collect_recoveries(sched)
    finally:
        registry.disarm()
    report.faults_fired = registry.fired_total

    # fault-free convergence: the surviving incarnation adopts/finishes
    # everything the storm stranded
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        sched.schedule_pending()
        pending = [p for p in store.pods() if not p.spec.node_name]
        active, backoff, unsched = sched.queue.pending_pods()
        if (not pending and sched.cache.assumed_pod_count() == 0
                and active + backoff + unsched == 0):
            break
        time.sleep(0.02)

    pods_now = {p.meta.name: p for p in store.pods()}
    report.bound = sum(1 for p in pods_now.values() if p.spec.node_name)
    report.unbound = len(pods_now) - report.bound
    report.double_binds = sum(1 for n in bind_ledger.values() if n > 1)
    report.leaked_assumes = sched.cache.assumed_pod_count()
    active, backoff, unsched = sched.queue.pending_pods()
    report.queue_pending = active + backoff + unsched
    for gang, size in gang_specs:
        n_bound = sum(
            1 for i in range(size)
            if (p := pods_now.get(f"{gang}-m{i}")) is not None
            and p.spec.node_name)
        if n_bound not in (0, size):
            report.partial_gangs_final += 1
    if sched.warm_start:
        report.warm_compiles += (
            sched.flight_recorder.device_telemetry.compile_count_since_warm())
    sched.api_dispatcher.close()
    registry.reset()
    report.wall_clock_s = time.monotonic() - t_start
    return report


# -- fleet soak: active-active schedulers, kill one, zero double-binds ---------


def fleet_schedule(registry: faultinject.FaultRegistry, nodes: int,
                   outage_start_tick: int, outage_ticks: int) -> None:
    """The fleet soak's fault ladder: everything the trace soak throws at
    one scheduler — watch partition, fleet-wide kubelet outage, bind
    latency + flakes, conflicts, lossy watch — PLUS seeded lease loss on
    the new `lease.renew` point (a guaranteed renewal-outage burst and one
    coordination-partition window), all against 2-3 concurrent members.
    The CRASH-mode peer kill is registered separately mid-soak (aimed by
    visit count, like the restart storm)."""
    registry.register(FaultSpec(
        "watch.partition", mode=PARTITION, start_after=150, window=250,
        times=1))
    registry.register(FaultSpec(
        "kubelet.sync", mode=DROP, start_after=outage_start_tick * nodes,
        times=outage_ticks * nodes))
    registry.register(FaultSpec(
        "store.bind_pod", mode=LATENCY, probability=0.15, times=10,
        latency_s=0.02))
    registry.register(FaultSpec(
        "store.bind_pod", mode=ERROR, transient=True,
        probability=0.1, times=10, message="bind flake"))
    registry.register(FaultSpec(
        "dispatcher.execute", mode=ERROR, transient=True,
        probability=0.1, times=20, message="dispatcher flake"))
    registry.register(FaultSpec(
        "store.update", mode=ERROR, probability=0.05, times=15,
        exc=ConflictError, message="injected conflict"))
    registry.register(FaultSpec(
        "watch.deliver", mode=DROP, probability=0.03, times=30))
    # seeded lease loss (satellite: lease.renew is FI01-declared): a
    # guaranteed 4-round renewal outage — whoever's renew lands on those
    # visits steps down and must reclaim — then one coordination-partition
    # window where every CAS round inside it is silently lost. Aim low:
    # the point is visited roughly once per held shard per drive tick
    # (~3/tick), so high start_after values would never arm.
    registry.register(FaultSpec(
        "lease.renew", mode=ERROR, transient=True, start_after=6, times=4,
        message="coordination flake"))
    registry.register(FaultSpec(
        "lease.renew", mode=PARTITION, start_after=18, window=5, times=1))


@dataclasses.dataclass
class FleetSoakReport:
    seed: int
    members: int
    created: int = 0
    bound: int = 0
    unbound: int = 0
    evicted: int = 0
    double_binds: int = 0
    leaked_assumes: int = 0
    queue_pending: int = 0
    crashes: int = 0
    failovers: int = 0
    failover_latency_s: float = 0.0
    failover_budget_s: float = 30.0
    shard_adoptions: int = 0
    ownership_overlap: int = 0
    lease_renew_faults: int = 0
    faults_fired: int = 0
    wall_clock_s: float = 0.0
    budget_s: float = 120.0

    @property
    def ok(self) -> bool:
        return (
            self.unbound == 0
            and self.double_binds == 0
            and self.leaked_assumes == 0
            and self.queue_pending == 0
            and self.ownership_overlap == 0
            # the kill must bite AND a survivor must adopt the orphaned
            # shard inside the bounded window, counted on the
            # restart_recoveries{kind="shard_adopt*"} kinds
            and self.crashes >= 1
            and self.failovers >= 1
            and self.failover_latency_s <= self.failover_budget_s
            and self.shard_adoptions >= 1
            and self.lease_renew_faults >= 1
            and self.faults_fired > 0
            and self.wall_clock_s <= self.budget_s
        )

    def render(self) -> str:
        verdict = "PASS" if self.ok else "FAIL"
        return (
            f"fleet soak [{verdict}] seed={self.seed} "
            f"members={self.members}: created={self.created} "
            f"bound={self.bound} unbound={self.unbound} "
            f"evicted={self.evicted} double_binds={self.double_binds} "
            f"leaked_assumes={self.leaked_assumes} "
            f"queue_pending={self.queue_pending} crashes={self.crashes} "
            f"failovers={self.failovers} "
            f"failover_latency_s={self.failover_latency_s:.2f} "
            f"shard_adoptions={self.shard_adoptions} "
            f"ownership_overlap={self.ownership_overlap} "
            f"lease_renew_faults={self.lease_renew_faults} "
            f"faults_fired={self.faults_fired} "
            f"wall_clock_s={self.wall_clock_s:.2f} (budget {self.budget_s})"
        )


def run_fleet_soak(seed: int = 7, members: int = 3, rounds: int = 3,
                   pods_per_round: int = 12, min_count: int = 3,
                   nodes: int = 12, wave_size: int = 8,
                   tick_s: float = 0.05, ticks_per_round: int = 5,
                   grace_period_s: float = 6.0,
                   outage_start_tick: int = 6, outage_ticks: int = 3,
                   lease_duration: float = 4.0, kill_round: int = 1,
                   budget_s: float = 120.0) -> FleetSoakReport:
    """Active-active fleet under the full chaos ladder (ISSUE 19): 2-3
    lease-sharded schedulers over ONE store take kubelet death, a watch
    partition, bind latency/flakes, seeded lease loss, and a CRASH-mode
    peer kill mid-traffic. The drive loop is single-threaded and
    fixed-order (arrivals -> kubelets -> lifecycle -> each alive member:
    elect_once + schedule_pending), so the fault schedule replays
    deterministically from the seed. Asserted after fault-free
    convergence: every surviving pod bound EXACTLY once (the store bind
    path is the double-bind oracle), zero leaked assumes across
    survivors, disjoint shard ownership, and the kill-one failover
    adopted the orphaned shard inside the bounded window with recoveries
    counted on restart_recoveries{kind="shard_adopt*"}. Leaves the
    registry disarmed + reset."""
    from ..api.meta import ObjectMeta
    from ..api.types import GangPolicy, PodGroup, PodGroupSpec
    from ..controllers.lifecycle import NodeLifecycleController
    from ..kubelet.hollow import HollowKubelet
    from ..scheduler import Profile, Scheduler
    from ..scheduler.fleet import FleetMember
    from ..scheduler.metrics import SchedulerMetrics
    from ..utils.faultinject import CRASH, SchedulerCrashed
    from .wrappers import with_gang

    report = FleetSoakReport(seed=seed, members=members, budget_s=budget_s)
    # lease expiry + a couple of full drive rounds is the legal adoption
    # window; anything slower means survivors are not contending
    report.failover_budget_s = lease_duration + 30.0
    t_start = time.monotonic()
    registry = faultinject.registry()
    registry.reset(seed=seed)
    fleet_schedule(registry, nodes=nodes,
                   outage_start_tick=outage_start_tick,
                   outage_ticks=outage_ticks)

    store = Store()

    # double-bind oracle (same as the restart storm): every SUCCESSFUL
    # bind lands here; lifecycle evictions DELETE pods (never recreate a
    # key), so any key with two landed binds is two members both placing
    # a pod only one of them owned
    bind_ledger: dict[str, int] = {}
    orig_bind_pods, orig_bind_pod = store.bind_pods, store.bind_pod

    def ledgered_bind_pods(bindings):
        out = orig_bind_pods(bindings)
        for (key, _node), status in zip(bindings, out):
            if status == "bound":
                bind_ledger[key] = bind_ledger.get(key, 0) + 1
        return out

    def ledgered_bind_pod(key, node_name):
        obj = orig_bind_pod(key, node_name)
        bind_ledger[key] = bind_ledger.get(key, 0) + 1
        return obj

    store.bind_pods = ledgered_bind_pods
    store.bind_pod = ledgered_bind_pod

    kubelets = []
    for i in range(nodes):
        node = make_node(f"fn{i}", cpu="16", mem="32Gi", zone=f"z{i % 4}")
        k = HollowKubelet(store, node)
        k.register()
        kubelets.append(k)
    lifecycle = NodeLifecycleController(store)
    lifecycle.grace_period = grace_period_s
    lifecycle.start()
    lifecycle.sweep()

    fleet: list[FleetMember] = []
    for i in range(members):
        sched = Scheduler(
            store,
            profiles=[Profile(backend="tpu", wave_size=wave_size)],
            feature_gates={"GenericWorkload": True,
                           "SchedulerAsyncAPICalls": True},
            async_api_calls=True,
            metrics=SchedulerMetrics(),
            seed=seed,
        )
        sched.queue._initial_backoff = 0.02
        sched.queue._max_backoff = 0.1
        member = FleetMember(
            sched, members, f"scheduler-{i}", preferred_shard=i,
            lease_duration=lease_duration,
            renew_deadline=lease_duration * 0.66,
            retry_period=tick_s,
        )
        member.start()
        fleet.append(member)
    alive = list(fleet)

    def drive(ticks: int) -> None:
        for _ in range(ticks):
            for k in kubelets:
                k.sync_once()
            lifecycle.sync_once()
            for member in list(alive):
                member.elect_once()
                try:
                    member.scheduler.schedule_pending()
                except SchedulerCrashed:
                    # the peer kill: ungraceful death — no lease release,
                    # no drain. Its shard leases now age toward expiry;
                    # survivors adopt through elect_once.
                    report.crashes += 1
                    member.crash()
                    alive.remove(member)
            time.sleep(tick_s)

    registry.arm()
    seq = 0
    try:
        for rnd in range(rounds):
            if rnd == kill_round:
                # aim a one-shot CRASH just past the visits the fleet has
                # already spent mid-wave, so the kill lands on live
                # traffic — whichever member launches that wave dies
                visits = registry.snapshot()["visits"].get("loop.wave", 0)
                registry.register(FaultSpec(
                    "loop.wave", mode=CRASH, times=1,
                    start_after=visits + 1, message="fleet peer kill"))
            gang = f"fgang-{rnd}"
            store.create(PodGroup(
                meta=ObjectMeta(name=gang),
                spec=PodGroupSpec(policy=GangPolicy(min_count=min_count)),
            ))
            for i in range(min_count):
                store.create(with_gang(
                    make_pod(f"{gang}-m{i}", cpu="200m", mem="128Mi"),
                    gang))
            for _ in range(pods_per_round):
                store.create(make_pod(f"fleet-{seq}", cpu="100m",
                                      mem="64Mi"))
                seq += 1
            report.created += min_count + pods_per_round
            drive(ticks=ticks_per_round)
    finally:
        registry.disarm()
    report.faults_fired = registry.fired_total
    report.lease_renew_faults = registry.fired_by_point["lease.renew"]

    # fault-free convergence: survivors keep electing (the orphaned
    # shard's lease expires INSIDE this loop when the kill came late),
    # kubelets heartbeat again, stranded/backoff/adopted pods bind
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline:
        for k in kubelets:
            k.sync_once()
        lifecycle.sync_once()
        done = True
        for member in alive:
            member.elect_once()
            member.scheduler.schedule_pending()
            active, backoff, unsched = member.scheduler.queue.pending_pods()
            if (member.scheduler.cache.assumed_pod_count()
                    or active + backoff + unsched):
                done = False
        owned = set().union(*(m.owned_shards() for m in alive)) if alive else set()
        pending = [p for p in store.pods() if not p.spec.node_name]
        if done and not pending and len(owned) == members:
            break
        time.sleep(tick_s)

    pods_now = store.pods()
    report.bound = sum(1 for p in pods_now if p.spec.node_name)
    report.unbound = len(pods_now) - report.bound
    report.evicted = report.created - len(pods_now)
    report.double_binds = sum(1 for n in bind_ledger.values() if n > 1)
    for member in alive:
        report.leaked_assumes += member.scheduler.cache.assumed_pod_count()
        active, backoff, unsched = member.scheduler.queue.pending_pods()
        report.queue_pending += active + backoff + unsched
        for kind, n in list(member.scheduler.flight_recorder.restart_events):
            if kind.startswith("shard_adopt"):
                report.shard_adoptions += n
        for ev_ in list(member.scheduler.flight_recorder.fleet_events):
            if ev_[0] == "failover":
                report.failovers += 1
                report.failover_latency_s = max(
                    report.failover_latency_s, ev_[2])
    # disjoint ownership: no shard held by two live members
    seen: dict[int, int] = {}
    for member in alive:
        for s in member.owned_shards():
            seen[s] = seen.get(s, 0) + 1
    report.ownership_overlap = sum(1 for n in seen.values() if n > 1)
    for member in alive:
        member.scheduler.api_dispatcher.close()
    registry.reset()
    report.wall_clock_s = time.monotonic() - t_start
    return report


def main(argv: list[str] | None = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m kubernetes_tpu.testing.chaos",
        description="Seeded chaos soak for the TPU scheduler "
                    "(deterministic fault schedule, convergence asserted)",
    )
    parser.add_argument("--seed", type=int, default=7)
    parser.add_argument("--rounds", type=int, default=6)
    parser.add_argument("--pods-per-round", type=int, default=24)
    parser.add_argument("--nodes", type=int, default=32)
    parser.add_argument("--wave-size", type=int, default=16)
    parser.add_argument("--trace", action="store_true",
                        help="run the arrival-trace soak (watch partition "
                             "+ fleet-wide kubelet outage + bind latency "
                             "under a Poisson/burst arrival trace) instead "
                             "of the scale-churn soak")
    parser.add_argument("--pods", type=int, default=96,
                        help="total arrivals for --trace")
    parser.add_argument("--budget-s", type=float, default=60.0,
                        help="wall-clock budget asserted by --trace")
    parser.add_argument("--gang", action="store_true",
                        help="run the gang soak (kubelet killed mid-gang "
                             "under bind/dispatcher flakes; all-or-nothing "
                             "asserted after convergence) instead of the "
                             "scale-churn soak")
    parser.add_argument("--gangs", type=int, default=6,
                        help="PodGroup count for --gang")
    parser.add_argument("--restart", action="store_true",
                        help="run the restart-storm soak (seeded scheduler "
                             "crashes mid-wave / mid-bind-commit / "
                             "mid-gang-permit, warm restarts over the same "
                             "store; double binds, leaked assumes, partial "
                             "gangs, and post-warmup compiles asserted "
                             "zero) instead of the scale-churn soak")
    parser.add_argument("--cycles", type=int, default=3,
                        help="crash/restart cycles for --restart")
    parser.add_argument("--fleet", action="store_true",
                        help="run the fleet soak (2-3 active-active "
                             "lease-sharded schedulers over one store "
                             "under kubelet death + watch partition + "
                             "bind latency + seeded lease loss + a "
                             "CRASH-mode peer kill; zero double-binds, "
                             "zero leaked assumes, and kill-one shard "
                             "adoption asserted) instead of the "
                             "scale-churn soak")
    parser.add_argument("--members", type=int, default=3,
                        help="fleet size for --fleet")
    args = parser.parse_args(argv)

    # every soak benefits from the persistent jax compilation cache: the
    # restart soak's warm restarts replay lowerings from disk, and repeat
    # chaos runs skip their cold-compile tax entirely
    from ..utils.jaxcache import enable_persistent_cache
    enable_persistent_cache()

    if args.fleet:
        report = run_fleet_soak(seed=args.seed,
                                members=max(2, min(args.members, 3)),
                                nodes=min(args.nodes, 12),
                                wave_size=min(args.wave_size, 8))
    elif args.restart:
        report = run_restart_soak(seed=args.seed, cycles=args.cycles,
                                  nodes=min(args.nodes, 16),
                                  wave_size=min(args.wave_size, 8))
    elif args.gang:
        report = run_gang_soak(seed=args.seed, gangs=args.gangs,
                               nodes=min(args.nodes, 12))
    elif args.trace:
        report = run_trace_soak(seed=args.seed, pods=args.pods,
                                nodes=min(args.nodes, 12),
                                wave_size=args.wave_size,
                                budget_s=args.budget_s)
    else:
        report = run_soak(seed=args.seed, rounds=args.rounds,
                          pods_per_round=args.pods_per_round,
                          nodes=args.nodes, wave_size=args.wave_size)
    print(report.render())
    return 0 if report.ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
