"""In-tree test fixtures, modeled on pkg/scheduler/testing/ in the reference
(wrappers.go builder style + framework_helpers.go synthetic clusters)."""

from .wrappers import (
    make_csi_node,
    make_node,
    make_pod,
    make_pv,
    make_pvc,
    make_storage_class,
    with_gang,
    with_node_affinity_in,
    with_pod_affinity,
    with_preferred_node_affinity,
    with_preferred_pod_affinity,
    with_pvc,
    with_spread,
    with_tolerations,
)
from .cluster import synthetic_cluster

__all__ = [
    "make_csi_node", "make_node", "make_pod", "make_pv", "make_pvc",
    "make_storage_class", "with_gang", "with_node_affinity_in",
    "with_pod_affinity", "with_preferred_node_affinity",
    "with_preferred_pod_affinity", "with_pvc", "with_spread",
    "with_tolerations", "synthetic_cluster",
]
