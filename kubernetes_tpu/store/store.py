"""In-process versioned store with watch streams.

Reference behavior modeled:
- etcd3 store (staging/src/k8s.io/apiserver/pkg/storage/etcd3/store.go):
  monotonically increasing cluster-wide revision; every write bumps it and
  stamps the object's resource_version.
- optimistic concurrency: update with a stale resource_version fails with
  ConflictError (apiserver 409).
- watch (etcd3 watcher + apiserver watch cache): per-(kind) event log with
  list+watch-from-revision semantics so reflectors never miss events.

TPU-first notes: the store is the *control-plane* contract and stays host-side
(SURVEY §2.9 — "the API surface stays host-side"); kernels see only the cache's
tensorized snapshots. Thread-safe via one mutex; watch delivery is synchronous
fan-out into per-watcher deques drained by consumer threads or polls.
"""

from __future__ import annotations

import copy
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Iterator

from ..api.meta import new_uid
from ..utils import faultinject

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"


class ConflictError(Exception):
    pass


class NotFoundError(Exception):
    pass


class AlreadyExistsError(Exception):
    pass


class CompactedError(Exception):
    """The requested watch start revision precedes the compacted log window
    (etcd's ErrCompacted → apiserver 410 Gone). The client must relist."""

    def __init__(self, requested: int, oldest: int):
        super().__init__(
            f"revision {requested} compacted (oldest retained: {oldest})"
        )
        self.requested = requested
        self.oldest = oldest


@dataclass
class Event:
    type: str  # ADDED | MODIFIED | DELETED
    obj: Any
    revision: int
    # wall-clock emit time (time.perf_counter); consumers like the perf
    # harness's throughput collector need true write times, not drain times
    ts: float = 0.0
    # previous object state on MODIFIED (the watch cache's
    # watchCacheEvent.PrevObject): lets selector-filtered watches detect an
    # object transitioning out of (or into) the selector and synthesize
    # DELETED/ADDED, exactly as staging/.../storage/cacher does
    prev_obj: Any = None
    # per-KIND contiguous sequence number stamped at emit (1, 2, 3, ...).
    # Revisions are global across kinds, so a Pod watcher seeing revisions
    # 5, 9, 12 cannot tell a delivery gap from other kinds' writes — seq
    # is what makes the informer's continuity check exact. 0 = synthesized
    # event (resync diff), exempt from continuity tracking.
    seq: int = 0


class Watch:
    """A single watch stream: a deque of events + condition variable.

    Equivalent to a client-go watch.Interface; `stop()` is idempotent.
    """

    def __init__(self, store: "Store", kind: str):
        self._store = store
        self._kind = kind
        self._events: list[Event] = []
        self._cond = threading.Condition()
        self._stopped = False
        # seq of the last event this stream is NOT responsible for
        # delivering (everything before it was covered by the list/replay
        # that opened the stream) — the informer's continuity bookmark
        self.start_seq = 0

    def _push(self, ev: Event) -> None:
        with self._cond:
            if self._stopped:
                return
            self._events.append(ev)
            self._cond.notify_all()

    def next(self, timeout: float | None = None) -> Event | None:
        with self._cond:
            if not self._events:
                self._cond.wait(timeout)
            if self._events:
                return self._events.pop(0)
            return None

    def drain(self) -> list[Event]:
        """Non-blocking: take all queued events."""
        with self._cond:
            evs, self._events = self._events, []
            return evs

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._events.clear()
            self._cond.notify_all()
        self._store._remove_watch(self._kind, self)

    @property
    def stopped(self) -> bool:
        return self._stopped


class Store:
    """Ordered, versioned object store for all kinds."""

    def __init__(self, clock: Callable[[], float] = time.time):
        self._mu = threading.RLock()
        self._revision = 0
        self._objects: dict[str, dict[str, Any]] = {}  # kind -> key -> obj
        self._log: dict[str, list[Event]] = {}  # kind -> event log (watch cache)
        self._watches: dict[str, list[Watch]] = {}
        self._clock = clock
        self._log_cap = 100_000  # bounded watch cache; older events compacted
        # kind → revision of the first retained event after compaction:
        # watches older than this get CompactedError (etcd compaction rev)
        self._compacted_before: dict[str, int] = {}
        # kind → last Event.seq handed out (compaction never rewinds it)
        self._seq: dict[str, int] = {}

    # -- helpers -----------------------------------------------------------

    def _bump(self) -> int:
        self._revision += 1
        return self._revision

    def _kind_of(self, obj: Any) -> str:
        return obj.kind

    def _emit(self, kind: str, ev: Event) -> None:
        ev.seq = self._seq[kind] = self._seq.get(kind, 0) + 1
        log = self._log.setdefault(kind, [])
        log.append(ev)
        if len(log) > self._log_cap:
            del log[: self._log_cap // 2]
            self._compacted_before[kind] = log[0].revision
        for w in self._watches.get(kind, []):
            # per-watcher delivery drop (chaos: a lossy watch connection).
            # _emit runs mid-write under _mu, so an ERROR-mode spec on this
            # point must NOT corrupt the store state — it degrades to a
            # drop; the event stays in the log, so a resync can repair it.
            # watch.partition is the long-lived cousin: a PARTITION spec
            # swallows a contiguous run of deliveries (a revision-RANGE
            # gap), which the informer must detect from revision
            # continuity — there is no per-event error to react to
            try:
                if faultinject.fire("watch.deliver"):
                    continue
                if faultinject.fire("watch.partition"):
                    continue
            except faultinject.FaultInjected:
                continue
            w._push(ev)

    def _remove_watch(self, kind: str, w: Watch) -> None:
        with self._mu:
            ws = self._watches.get(kind)
            if ws and w in ws:
                ws.remove(w)

    # -- CRUD --------------------------------------------------------------

    def create(self, obj: Any, *, copy_return: bool = True) -> Any:
        """copy_return=False skips the defensive copy of the returned
        object and returns None — for bulk loaders (the perf harness) that
        discard it; a deepcopy per created object is measurable at 11k
        objects."""
        faultinject.fire("store.create")  # before _mu: may sleep or raise
        with self._mu:
            kind = self._kind_of(obj)
            objs = self._objects.setdefault(kind, {})
            key = obj.meta.key
            if key in objs:
                raise AlreadyExistsError(f"{kind} {key}")
            obj = copy.deepcopy(obj)
            if not obj.meta.uid:
                obj.meta.uid = new_uid()
            if not obj.meta.creation_timestamp:
                obj.meta.creation_timestamp = self._clock()
            rev = self._bump()
            obj.meta.resource_version = rev
            objs[key] = obj
            # the event SHARES the stored object (informer convention:
            # event objects are read-only, as in client-go's shared caches
            # — bind_pod established the pattern); a deepcopy per create
            # was a measurable slice of bench setup at 11k objects
            self._emit(kind, Event(ADDED, obj, rev, time.perf_counter()))
            return copy.deepcopy(obj) if copy_return else None

    def get(self, kind: str, key: str) -> Any:
        with self._mu:
            obj = self._objects.get(kind, {}).get(key)
            if obj is None:
                raise NotFoundError(f"{kind} {key}")
            return copy.deepcopy(obj)

    def try_get(self, kind: str, key: str) -> Any | None:
        with self._mu:
            obj = self._objects.get(kind, {}).get(key)
            return copy.deepcopy(obj) if obj is not None else None

    def contains(self, kind: str, key: str) -> bool:
        """Copy-free existence check — the scheduler's skipPodSchedule runs
        once per popped pod, where try_get's deepcopy is pure overhead."""
        with self._mu:
            return key in self._objects.get(kind, {})

    def get_ref(self, kind: str, key: str) -> Any | None:
        """The stored object WITHOUT a copy (read-only by the list_refs
        convention) — for per-(pod, node) hot-loop lookups like the CSI
        attach-limit filter, where try_get's deepcopy dominated the whole
        scheduling cycle."""
        with self._mu:
            return self._objects.get(kind, {}).get(key)

    def update(self, obj: Any, *, check_version: bool = True) -> Any:
        """Optimistic-concurrency update; stamps a fresh resource_version."""
        faultinject.fire("store.update")  # before _mu: may sleep or raise
        with self._mu:
            kind = self._kind_of(obj)
            objs = self._objects.setdefault(kind, {})
            key = obj.meta.key
            cur = objs.get(key)
            if cur is None:
                raise NotFoundError(f"{kind} {key}")
            if obj is cur:
                # a caller mutating a reference it got from list_refs()/an
                # event and updating with it would defeat CAS (rv trivially
                # matches) AND corrupt prev_obj (prev would alias the
                # mutated object, hiding selector transitions)
                raise ValueError(
                    f"{kind} {key}: update() called with the stored object "
                    "itself — store reads are read-only; update a copy"
                )
            if check_version and obj.meta.resource_version != cur.meta.resource_version:
                raise ConflictError(
                    f"{kind} {key}: rv {obj.meta.resource_version} != {cur.meta.resource_version}"
                )
            obj = copy.deepcopy(obj)
            obj.meta.uid = cur.meta.uid
            obj.meta.creation_timestamp = cur.meta.creation_timestamp
            rev = self._bump()
            obj.meta.resource_version = rev
            objs[key] = obj
            # event shares the stored object (see create)
            self._emit(kind, Event(MODIFIED, obj, rev,
                                   time.perf_counter(), prev_obj=cur))
            return copy.deepcopy(obj)

    def bind_pod(self, key: str, node_name: str) -> Any:
        """pods/binding subresource (reference: POST pods/<name>/binding,
        registry/core/pod/rest BindingREST): stamp spec.node_name without a
        full-object round trip. One copy total — the emitted event shares
        the new stored object (informer convention: event objects are
        read-only, as in client-go's shared caches)."""
        faultinject.fire("store.bind_pod")  # before _mu: may sleep or raise
        with self._mu:
            objs = self._objects.get("Pod", {})
            cur = objs.get(key)
            if cur is None:
                raise NotFoundError(f"Pod {key}")
            if cur.spec.node_name:
                raise ConflictError(
                    f"pod {key} is already bound to {cur.spec.node_name}"
                )
            obj = copy.deepcopy(cur)
            obj.spec.node_name = node_name
            self._clear_failed_scheduling_condition(obj)
            rev = self._bump()
            obj.meta.resource_version = rev
            objs[key] = obj
            self._emit("Pod", Event(MODIFIED, obj, rev,
                                        time.perf_counter(), prev_obj=cur))
            return obj

    @staticmethod
    def _clear_failed_scheduling_condition(obj) -> None:
        """A bind supersedes any earlier PodScheduled=False condition; a
        stale failure patch racing the bind on another dispatcher worker
        must not leave a bound pod marked unschedulable."""
        for c in obj.status.conditions:
            if c.type == "PodScheduled" and c.status == "False":
                c.status, c.reason, c.message = "True", "", ""

    def bind_pods(self, bindings: list[tuple[str, str]]) -> list[str]:
        """Batched pods/binding for a whole scheduling wave of (pod key,
        node name) pairs — the writeback half of the batched TPU wave (the
        reference's analogue is the async dispatcher draining one binding
        call per pod, backend/api_dispatcher/api_dispatcher.go:32-112; a
        wave is our unit of pipelining, so the transaction is too).
        Returns one of "bound" | "missing" (pod deleted — binding moot) |
        "conflict" (already bound) per pair; failures leave the rest of
        the wave untouched.

        Prepare/commit split: the per-binding fault window (which may
        SLEEP under LATENCY injection) and the deepcopy run with the store
        unlocked, so one slow binding no longer serializes every unrelated
        read/write behind `_mu`. The short commit section re-validates
        each pod against the live store before landing it."""
        out: list[str] = []
        # (out index, key, node_name, object observed at prepare, staged copy)
        prepared: list[tuple[int, str, str, Any, Any]] = []
        for key, node_name in bindings:
            # per-binding injection point: a fault here fails ONE pod's
            # binding while its wave siblings' bindings land — the
            # status string (never an exception) is how wave-level
            # failure isolation reaches _apply_wave_bind_results
            try:
                faultinject.fire("store.bind_pod")
            except faultinject.FaultInjected as e:
                out.append(f"error: {e}")
                continue
            cur = self.get_ref("Pod", key)
            if cur is None:
                out.append("missing")
                continue
            if cur.spec.node_name:
                out.append("conflict")
                continue
            obj = copy.deepcopy(cur)
            obj.spec.node_name = node_name
            self._clear_failed_scheduling_condition(obj)
            out.append("bound")  # provisional; commit may downgrade it
            prepared.append((len(out) - 1, key, node_name, cur, obj))
        self._commit_bindings(prepared, out)
        return out

    def _commit_bindings(
        self,
        prepared: list[tuple[int, str, str, Any, Any]],
        out: list[str],
    ) -> None:
        """Commit section of bind_pods (LOCK04: nothing in here may block
        or fire an injection point — prepare already paid those windows).
        Re-validates each staged pod against the live store: a write that
        raced the unlocked prepare window shows up as an identity change
        on the stored object."""
        with self._mu:
            objs = self._objects.setdefault("Pod", {})
            for idx, key, node_name, cur, obj in prepared:
                now_cur = objs.get(key)
                if now_cur is None:
                    out[idx] = "missing"
                    continue
                if now_cur is not cur:
                    # raced: re-validate and re-stage from the live object
                    if now_cur.spec.node_name:
                        out[idx] = "conflict"
                        continue
                    obj = copy.deepcopy(now_cur)
                    obj.spec.node_name = node_name
                    self._clear_failed_scheduling_condition(obj)
                    cur = now_cur
                rev = self._bump()
                obj.meta.resource_version = rev
                objs[key] = obj
                self._emit("Pod", Event(MODIFIED, obj, rev,
                                        time.perf_counter(), prev_obj=cur))

    def patch_pod_status(self, key: str, condition: Any = None,
                         nominated_node: str | None = None) -> Any | None:
        """Atomic status patch under the store lock (the non-atomic
        get→mutate→update pattern loses against a concurrent bind: a stale
        whole-object write would silently unbind the pod). A failure
        condition (status=False) is dropped when the pod is already bound —
        the bind superseded it. Returns the stored object or None."""
        faultinject.fire("store.patch_pod_status")  # before _mu
        with self._mu:
            objs = self._objects.get("Pod", {})
            cur = objs.get(key)
            if cur is None:
                return None
            obj = copy.deepcopy(cur)
            changed = False
            if condition is not None:
                if not (obj.spec.node_name and condition.status == "False"):
                    for c in obj.status.conditions:
                        if c.type == condition.type:
                            c.status = condition.status
                            c.reason = condition.reason
                            c.message = condition.message
                            break
                    else:
                        obj.status.conditions.append(condition)
                    changed = True
            if nominated_node is not None:
                obj.status.nominated_node_name = nominated_node
                changed = True
            if not changed:
                return cur
            rev = self._bump()
            obj.meta.resource_version = rev
            objs[key] = obj
            self._emit("Pod", Event(MODIFIED, obj, rev,
                                        time.perf_counter(), prev_obj=cur))
            return obj

    def delete(self, kind: str, key: str) -> Any:
        faultinject.fire("store.delete")  # before _mu: may sleep or raise
        with self._mu:
            objs = self._objects.get(kind, {})
            cur = objs.pop(key, None)
            if cur is None:
                raise NotFoundError(f"{kind} {key}")
            rev = self._bump()
            # the popped object is SHARED with past ADDED/MODIFIED events
            # (and thus informer caches) — it must stay frozen; the DELETED
            # event and the caller get one fresh copy stamped with the
            # deletion revision
            out = copy.deepcopy(cur)
            out.meta.resource_version = rev
            self._emit(kind, Event(DELETED, out, rev, time.perf_counter()))
            return out

    def try_delete(self, kind: str, key: str) -> Any | None:
        """delete() for already-might-be-gone objects (controller GC paths
        are full of benign delete races); returns None instead of raising."""
        try:
            return self.delete(kind, key)
        except NotFoundError:
            return None

    def list_refs(self, kind: str) -> list[Any]:
        """The stored objects WITHOUT copies — read-only by the same
        convention as event objects (client-go shared-cache semantics).
        For hot per-cycle listings (the volume binder's PV candidates) the
        per-call deepcopy of list() is the dominant cost at scale."""
        with self._mu:
            return list(self._objects.get(kind, {}).values())

    def list(self, kind: str, namespace: str | None = None) -> tuple[list[Any], int]:
        """Returns (objects, revision) — the revision to start a watch from.
        namespace filters BEFORE the deepcopy: a namespace-scoped consumer
        (quota admission) must not pay for copying the whole cluster."""
        with self._mu:
            objs = [
                copy.deepcopy(o)
                for o in self._objects.get(kind, {}).values()
                if namespace is None or o.meta.namespace == namespace
            ]
            return objs, self._revision

    @property
    def revision(self) -> int:
        with self._mu:
            return self._revision

    def latest_revision(self, kind: str) -> int:
        """Revision of the newest logged event for `kind` (0 = no events
        yet). This is the informer's partition probe: delivery is
        synchronous under `_mu`, so any logged event at revision ≤ R that
        a connected watch has not received after draining was LOST — the
        comparison has no in-flight window and thus no false positives."""
        with self._mu:
            log = self._log.get(kind)
            return log[-1].revision if log else 0

    def first_event_after(self, kind: str, revision: int) -> tuple[int, float] | None:
        """(revision, emit ts) of the oldest retained event for `kind`
        with revision > `revision`, or None. The ts anchors the partition
        repair-latency measurement: gap age = now − first missed emit."""
        import bisect

        with self._mu:
            log = self._log.get(kind, [])
            i = bisect.bisect_right(log, revision, key=lambda e: e.revision)
            if i >= len(log):
                return None
            ev = log[i]
            return ev.revision, ev.ts

    # -- watch -------------------------------------------------------------

    def watch(self, kind: str, from_revision: int = 0) -> Watch:
        """Open a watch; replays logged events with revision > from_revision.

        list() + watch(rev) gives the reflector's gap-free ListAndWatch.
        Replay binary-searches the sorted per-kind log (the cacher's ring-
        buffer lookup, staging/.../storage/cacher) instead of scanning it.
        Raises CompactedError when from_revision predates the retained
        window — events would be silently missing otherwise; the caller
        must relist (410 Gone semantics). from_revision=0 = "from the
        beginning of history", valid only while kind history is uncompacted.
        """
        import bisect

        with self._mu:
            log = self._log.get(kind, [])
            compacted_before = self._compacted_before.get(kind, 0)
            if from_revision < compacted_before - 1:
                raise CompactedError(from_revision, compacted_before)
            w = Watch(self, kind)
            i = bisect.bisect_right(log, from_revision, key=lambda e: e.revision)
            # replayed events keep their original seqs, so the bookmark is
            # the seq just before the first replayed event (or the current
            # counter when nothing replays)
            w.start_seq = log[i].seq - 1 if i < len(log) else self._seq.get(kind, 0)
            for ev in log[i:]:
                w._push(ev)
            self._watches.setdefault(kind, []).append(w)
            return w

    def sync_watch(self, kind: str) -> tuple[list[Any], Watch, int]:
        """Atomic relist + fresh watch under ONE lock acquisition: the refs
        reflect every write up to now and the new watch sees every write
        after — no replay window, no gap, no duplicate. This is the repair
        primitive for dropped watch deliveries (an informer resync): the
        incremental watch(from_revision) path can't help there because the
        lost events are still IN the log — only a state diff recovers them.
        Returned objects follow the list_refs read-only convention.

        The third element is the store revision AT the sync, captured under
        the same lock. The informer's revision-continuity tracker must
        restart its bookmark from exactly this value: anything earlier
        re-flags diff-repaired events as a gap forever (a perpetual
        false-positive partition), anything later hides real losses."""
        with self._mu:
            refs = list(self._objects.get(kind, {}).values())
            w = Watch(self, kind)
            w.start_seq = self._seq.get(kind, 0)
            self._watches.setdefault(kind, []).append(w)
            return refs, w, self._revision

    # -- convenience typed helpers ----------------------------------------

    def pods(self) -> list[Any]:
        return self.list("Pod")[0]

    def nodes(self) -> list[Any]:
        return self.list("Node")[0]

    def iter_kind(self, kind: str) -> Iterator[Any]:
        objs, _ = self.list(kind)
        return iter(objs)
