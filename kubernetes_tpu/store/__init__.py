"""Versioned state store + watch bus — the etcd/apiserver-storage equivalent.

Reference: staging/src/k8s.io/apiserver/pkg/storage/etcd3/store.go (CRUD with
revisions), watcher (event.go), and the watch cache. Single-process and
in-memory: all cluster state lives here; every other component is a stateless
watcher that converges on it (crash-only design, SURVEY §5.3/§5.4).
"""

from .store import Store, Event, ADDED, MODIFIED, DELETED, ConflictError, NotFoundError, AlreadyExistsError  # noqa: F401
