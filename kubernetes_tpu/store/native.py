"""Native store: the C++ etcd-equivalent L0 engine behind the Store surface.

Reference: the reference's L0 is etcd (a native external process) behind
storage.Interface (staging/src/k8s.io/apiserver/pkg/storage/etcd3/store.go).
Here the native engine (native/store_core.cpp) is linked in-process via
ctypes: revisioned KV + gap-free event log + CAS + compaction + durable
snapshot save/load (checkpoint/resume — §5.4 "etcd IS the checkpoint").

NativeStore implements the same surface as store.Store, so every component
(apiserver, informers, scheduler, controllers) runs on it unchanged. Objects
cross the boundary as JSON (api/serialization wire form).
"""

from __future__ import annotations

import copy
import ctypes
import json
import struct
import subprocess
import threading
import time
from pathlib import Path

from ..api.meta import new_uid
from ..api.serialization import decode, encode
from .store import (
    ADDED,
    DELETED,
    MODIFIED,
    AlreadyExistsError,
    ConflictError,
    Event,
    NotFoundError,
    Watch,
)

SC_ERR_NOT_FOUND = -1
SC_ERR_ALREADY_EXISTS = -2
SC_ERR_CONFLICT = -3
_EVENT_TYPES = {0: ADDED, 1: MODIFIED, 2: DELETED}

_NATIVE_DIR = Path(__file__).resolve().parent.parent.parent / "native"
_LIB_PATH = _NATIVE_DIR / "libstore_core.so"
_lib = None
_lib_lock = threading.Lock()


def _build_library() -> Path:
    subprocess.run(
        ["make", "-C", str(_NATIVE_DIR)], check=True, capture_output=True
    )
    return _LIB_PATH


def load_library() -> ctypes.CDLL:
    """Load (building on first use) the native core; raises OSError if the
    toolchain is unavailable — callers fall back to the Python store."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        from ..utils.nativelib import load_native

        lib = load_native("libstore_core.so")  # shared locked loader
        if lib is None:
            raise OSError("native store core unavailable")
        lib.sc_new.restype = ctypes.c_void_p
        lib.sc_free.argtypes = [ctypes.c_void_p]
        lib.sc_buf_free.argtypes = [ctypes.c_char_p]
        lib.sc_revision.argtypes = [ctypes.c_void_p]
        lib.sc_revision.restype = ctypes.c_int64
        lib.sc_put.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_uint32, ctypes.c_int64, ctypes.c_int, ctypes.c_double,
        ]
        lib.sc_put.restype = ctypes.c_int64
        lib.sc_get.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_size_t),
        ]
        lib.sc_get.restype = ctypes.c_int64
        lib.sc_delete.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_size_t),
            ctypes.c_double,
        ]
        lib.sc_delete.restype = ctypes.c_int64
        lib.sc_list.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_size_t),
        ]
        lib.sc_list.restype = ctypes.c_int64
        lib.sc_log_since.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_size_t),
        ]
        lib.sc_log_since.restype = ctypes.c_int64
        lib.sc_compact.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.sc_compact.restype = ctypes.c_int64
        lib.sc_save.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.sc_save.restype = ctypes.c_int64
        lib.sc_load.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.sc_load.restype = ctypes.c_int64
        _lib = lib
        return lib


def native_available() -> bool:
    try:
        load_library()
        return True
    except (OSError, subprocess.CalledProcessError):
        return False


class _Buf:
    """Scoped out-buffer: copies to bytes, frees the malloc'd native side."""

    def __init__(self, lib):
        self.lib = lib
        self.ptr = ctypes.c_void_p()
        self.size = ctypes.c_size_t()

    def __enter__(self):
        return self

    def take(self) -> bytes:
        if not self.ptr:
            return b""
        return ctypes.string_at(self.ptr, self.size.value)

    def __exit__(self, *exc):
        if self.ptr:
            self.lib.sc_buf_free(ctypes.cast(self.ptr, ctypes.c_char_p))


class NativeStore:
    """Store-compatible facade over the native core."""

    def __init__(self, clock=time.time):
        self.lib = load_library()
        self.h = self.lib.sc_new()
        self._clock = clock
        self._mu = threading.RLock()
        self._watches: dict[str, list[Watch]] = {}

    def __del__(self):
        h, self.h = getattr(self, "h", None), None
        if h and getattr(self, "lib", None) is not None:
            self.lib.sc_free(h)

    # -- serialization boundary ---------------------------------------------

    @staticmethod
    def _to_bytes(obj) -> bytes:
        return json.dumps(encode(obj), separators=(",", ":")).encode()

    @staticmethod
    def _from_bytes(raw: bytes):
        return decode(json.loads(raw))

    # -- CRUD ----------------------------------------------------------------

    def create(self, obj):
        with self._mu:
            obj = copy.deepcopy(obj)
            if not obj.meta.uid:
                obj.meta.uid = new_uid()
            if not obj.meta.creation_timestamp:
                obj.meta.creation_timestamp = self._clock()
            kind, key = obj.kind, obj.meta.key
            # two-phase: stamp the revision the put will get (serialized
            # under _mu, so the next revision is deterministic)
            obj.meta.resource_version = self.lib.sc_revision(self.h) + 1
            raw = self._to_bytes(obj)
            ts = time.perf_counter()
            rev = self.lib.sc_put(self.h, kind.encode(), key.encode(), raw,
                                  len(raw), -1, 1, ts)
            if rev == SC_ERR_ALREADY_EXISTS:
                raise AlreadyExistsError(f"{kind} {key}")
            self._emit(kind, Event(ADDED, obj, rev, ts))
            return copy.deepcopy(obj)

    def get(self, kind: str, key: str):
        with _Buf(self.lib) as buf:
            rev = self.lib.sc_get(self.h, kind.encode(), key.encode(),
                                  ctypes.byref(buf.ptr), ctypes.byref(buf.size))
            if rev == SC_ERR_NOT_FOUND:
                raise NotFoundError(f"{kind} {key}")
            return self._from_bytes(buf.take())

    def try_get(self, kind: str, key: str):
        try:
            return self.get(kind, key)
        except NotFoundError:
            return None

    def contains(self, kind: str, key: str) -> bool:
        """Existence check (Store.contains parity). The native core has no
        head-only lookup, so this decodes like get — correctness first; the
        hot-path caller (skipPodSchedule) runs against the Python store."""
        return self.try_get(kind, key) is not None

    def list_refs(self, kind: str):
        """Store.list_refs parity. The native core serializes every read, so
        there are no shared references to hand out — this is list() minus
        the revision, kept so read-only scanners (admission plugins, the
        event GC) work unchanged over this facade."""
        objs, _rev = self.list(kind)
        return objs

    def update(self, obj, *, check_version: bool = True):
        with self._mu:
            kind, key = obj.kind, obj.meta.key
            cur = self.try_get(kind, key)
            if cur is None:
                raise NotFoundError(f"{kind} {key}")
            if check_version and obj.meta.resource_version != cur.meta.resource_version:
                raise ConflictError(
                    f"{kind} {key}: rv {obj.meta.resource_version} != "
                    f"{cur.meta.resource_version}"
                )
            obj = copy.deepcopy(obj)
            obj.meta.uid = cur.meta.uid
            obj.meta.creation_timestamp = cur.meta.creation_timestamp
            expected = cur.meta.resource_version if check_version else -1
            obj.meta.resource_version = self.lib.sc_revision(self.h) + 1
            raw = self._to_bytes(obj)
            ts = time.perf_counter()
            rev = self.lib.sc_put(self.h, kind.encode(), key.encode(), raw,
                                  len(raw), expected, 0, ts)
            if rev == SC_ERR_NOT_FOUND:
                raise NotFoundError(f"{kind} {key}")
            if rev == SC_ERR_CONFLICT:
                raise ConflictError(f"{kind} {key}")
            self._emit(kind, Event(MODIFIED, obj, rev, ts))
            return copy.deepcopy(obj)

    def try_delete(self, kind: str, key: str):
        """delete() tolerant of already-gone objects (Store.try_delete)."""
        try:
            return self.delete(kind, key)
        except NotFoundError:
            return None

    def delete(self, kind: str, key: str):
        with self._mu:
            ts = time.perf_counter()
            with _Buf(self.lib) as buf:
                rev = self.lib.sc_delete(self.h, kind.encode(), key.encode(),
                                         ctypes.byref(buf.ptr),
                                         ctypes.byref(buf.size), ts)
                if rev == SC_ERR_NOT_FOUND:
                    raise NotFoundError(f"{kind} {key}")
                obj = self._from_bytes(buf.take())
            obj.meta.resource_version = rev
            self._emit(kind, Event(DELETED, obj, rev, ts))
            return obj

    def list(self, kind: str):
        with _Buf(self.lib) as buf:
            rev = self.lib.sc_list(self.h, kind.encode(), ctypes.byref(buf.ptr),
                                   ctypes.byref(buf.size))
            raw = buf.take()
        out = []
        off = 0
        while off < len(raw):
            (key_len,) = struct.unpack_from("<I", raw, off)
            off += 4 + key_len
            (val_len,) = struct.unpack_from("<I", raw, off)
            off += 4
            out.append(self._from_bytes(raw[off:off + val_len]))
            off += val_len
        return out, rev

    @property
    def revision(self) -> int:
        return self.lib.sc_revision(self.h)

    # -- watch ---------------------------------------------------------------

    def _emit(self, kind: str, ev: Event) -> None:
        for w in self._watches.get(kind, []):
            w._push(ev)

    def _remove_watch(self, kind: str, w: Watch) -> None:
        with self._mu:
            ws = self._watches.get(kind)
            if ws and w in ws:
                ws.remove(w)

    def watch(self, kind: str, from_revision: int = 0) -> Watch:
        """Replay from the NATIVE log (survives beyond the Python process's
        watch lifetimes), then live-push. If compaction dropped events this
        watch needed (sc_log_since returns -1), fall back to relist
        semantics: synthesize ADDED for the current state — exactly the
        reflector's resync-on-"too old resource version"."""
        with self._mu:
            w = Watch(self, kind)
            with _Buf(self.lib) as buf:
                n = self.lib.sc_log_since(self.h, kind.encode(), from_revision,
                                          ctypes.byref(buf.ptr),
                                          ctypes.byref(buf.size))
                raw = buf.take()
            if n < 0:
                now = time.perf_counter()
                objs, rev = self.list(kind)
                for obj in objs:
                    w._push(Event(ADDED, obj, rev, now))
            else:
                off = 0
                while off < len(raw):
                    etype = raw[off]
                    off += 1
                    (rev,) = struct.unpack_from("<q", raw, off)
                    off += 8
                    (ts,) = struct.unpack_from("<d", raw, off)
                    off += 8
                    (key_len,) = struct.unpack_from("<I", raw, off)
                    off += 4 + key_len
                    (val_len,) = struct.unpack_from("<I", raw, off)
                    off += 4
                    obj = self._from_bytes(raw[off:off + val_len])
                    off += val_len
                    w._push(Event(_EVENT_TYPES[etype], obj, rev, ts))
            self._watches.setdefault(kind, []).append(w)
            return w

    # -- durability (checkpoint/resume) --------------------------------------

    def save(self, path: str) -> None:
        rc = self.lib.sc_save(self.h, str(path).encode())
        if rc != 0:
            raise OSError(f"native store save failed ({rc})")

    def load(self, path: str) -> None:
        rc = self.lib.sc_load(self.h, str(path).encode())
        if rc != 0:
            raise OSError(f"native store load failed ({rc})")

    def compact(self, revision: int) -> int:
        return int(self.lib.sc_compact(self.h, revision))

    # -- convenience parity with Store ---------------------------------------

    def pods(self):
        return self.list("Pod")[0]

    def nodes(self):
        return self.list("Node")[0]

    def iter_kind(self, kind: str):
        return iter(self.list(kind)[0])
