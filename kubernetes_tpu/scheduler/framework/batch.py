"""Opportunistic batching: reuse one scoring pass across identical pods.

Reference: pkg/scheduler/framework/runtime/batch.go:33-229 (maxBatchAge:56,
GetNodeHint:63, StoreScheduleResults:97, batchStateCompatible:162) +
PodSignature from staging/.../framework/signers.go (the Framework.sign_pod
concatenation of per-plugin fragments). Feature OpportunisticBatching,
KEP-5598 (pkg/features/kube_features.go:671).

A signature's cached sorted score list answers "where would an identical pod
go" without re-running Score. The hinted node is re-Filtered (cheap, one
node); while it keeps passing, the whole run of identical pods binds there —
when it fills up, the hint advances down the list. Entries expire after
500 ms and on node-shape cluster events. Freshness uses time.monotonic():
a wall-clock jump (NTP step, suspend/resume) must not make entries
immortal or instantly stale.

TPU note: the device kernel subsumes this for kernel-eligible pods (a wave of
identical pods is one batched lax.scan — SURVEY.md §2.9.5); this host cache
accelerates the long-tail pods the kernel falls back on.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

MAX_BATCH_AGE = 0.5  # seconds (batch.go maxBatchAge:56)

HIT = "hit"
MISS = "miss"
STALE = "stale"
EXHAUSTED = "exhausted"


@dataclass
class _BatchEntry:
    ordered_nodes: list[str]  # node names, best score first
    created: float  # time.monotonic() — never wall clock (see module doc)
    next_index: int = 0  # current hint position


@dataclass
class BatchCache:
    max_age: float = MAX_BATCH_AGE
    entries: dict[str, _BatchEntry] = field(default_factory=dict)
    metrics: object | None = None

    def _record(self, result: str) -> None:
        if self.metrics is not None:
            self.metrics.batch_attempts.inc(result)

    def has_fresh(self, signature: str) -> bool:
        """Cheap pre-check so callers skip PreFilter setup on a sure miss."""
        entry = self.entries.get(signature)
        if entry is None:
            self._record(MISS)
            return False
        if time.monotonic() - entry.created > self.max_age:
            del self.entries[signature]
            self._record(STALE)
            return False
        return True

    def get_node_hint(self, signature: str, filter_fn) -> str | None:
        """batch.go GetNodeHint:63 — the current hint node if it still passes
        Filter; otherwise advance down the list. filter_fn(node_name) -> bool
        runs the real Filter chain against the live snapshot."""
        t0 = time.perf_counter()
        try:
            entry = self.entries.get(signature)
            if entry is None:
                self._record(MISS)
                return None
            if time.monotonic() - entry.created > self.max_age:
                del self.entries[signature]
                self._record(STALE)
                return None
            while entry.next_index < len(entry.ordered_nodes):
                node = entry.ordered_nodes[entry.next_index]
                if filter_fn(node):
                    self._record(HIT)
                    return node
                entry.next_index += 1
            del self.entries[signature]
            self._record(EXHAUSTED)
            return None
        finally:
            if self.metrics is not None:
                self.metrics.get_node_hint_duration.observe(
                    time.perf_counter() - t0
                )

    def store_schedule_results(self, signature: str, ordered_nodes: list[str]) -> None:
        """batch.go StoreScheduleResults:97 — cache the sorted node list from
        a full scoring pass."""
        t0 = time.perf_counter()
        self.entries[signature] = _BatchEntry(list(ordered_nodes), time.monotonic())
        if self.metrics is not None:
            self.metrics.store_schedule_results_duration.observe(
                time.perf_counter() - t0
            )

    def flush(self) -> None:
        """Cluster events that change node shape invalidate every entry
        (BatchCacheFlushed metric in the reference)."""
        self.entries.clear()
