"""Per-cycle typed key/value store.

Reference: staging/src/k8s.io/kube-scheduler/framework/cycle_state.go:45 and
pkg/scheduler/framework/cycle_state.go — plugin-private state flowing through
one scheduling cycle, with skip-sets computed at PreFilter/PreScore and the
gang-cycle flag.
"""

from __future__ import annotations

import copy
from typing import Any


class CycleState:
    def __init__(self) -> None:
        self._storage: dict[str, Any] = {}
        self.skip_filter_plugins: set[str] = set()
        self.skip_score_plugins: set[str] = set()
        self.skip_pre_bind_plugins: set[str] = set()
        self.record_plugin_metrics = False
        self.is_pod_group_scheduling_cycle = False

    def read(self, key: str) -> Any:
        return self._storage.get(key)

    def write(self, key: str, value: Any) -> None:
        self._storage[key] = value

    def delete(self, key: str) -> None:
        self._storage.pop(key, None)

    def clone(self) -> "CycleState":
        c = CycleState()
        # plugin state objects implement clone() if they need COW semantics
        for k, v in self._storage.items():
            c._storage[k] = v.clone() if hasattr(v, "clone") else copy.copy(v)
        c.skip_filter_plugins = set(self.skip_filter_plugins)
        c.skip_score_plugins = set(self.skip_score_plugins)
        c.skip_pre_bind_plugins = set(self.skip_pre_bind_plugins)
        c.record_plugin_metrics = self.record_plugin_metrics
        c.is_pod_group_scheduling_cycle = self.is_pod_group_scheduling_cycle
        return c
