"""Framework runtime — the extension-point state machine ("kernel launcher").

Reference: pkg/scheduler/framework/runtime/framework.go (frameworkImpl :57,
RunPreFilterPlugins :907, RunFilterPlugins :1078, RunScorePlugins :1320 with
its 3 passes, RunPermitPlugins :1923, WaitOnPermit :2034, SignPod :857).

TPU-first divergence: the reference fans each pass out over 16 goroutines
(Parallelizer.Until). Here the host runtime is sequential (it handles the
sparse/rare plugins); dense filter+score work is delegated wholesale to the
TPU backend (models/), which replaces the goroutine fan-out with one
pods x nodes kernel. A framework may carry a `tpu_backend`: when set, plugins
implementing `kernel_spec()` are folded into the device kernel and skipped
host-side (see models/backend.py).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Sequence

from ...api.types import Pod
from ..nodeinfo import NodeInfo
from .cycle_state import CycleState
from .events import ClusterEventWithHint
from .interface import (
    MAX_NODE_SCORE,
    MIN_NODE_SCORE,
    NodePluginScores,
    NodeToStatus,
    PreFilterResult,
    PostFilterResult,
    Status,
    WaitingPod,
    status_of,
)

DEFAULT_PERMIT_TIMEOUT = 600.0  # maxTimeout in RunPermitPlugins


class Framework:
    """One configured profile's plugin pipeline."""

    def __init__(
        self,
        plugins: Sequence[Any],
        weights: dict[str, int] | None = None,
        profile_name: str = "default-scheduler",
        metrics=None,
        clock=None,
    ):
        from ...utils.clock import Clock

        self.profile_name = profile_name
        self.plugins = list(plugins)
        self.weights = dict(weights or {})
        self.metrics = metrics
        self.clock = clock or Clock()
        self.tpu_backend = None  # set by scheduler wiring when backend=tpu

        def having(method: str) -> list[Any]:
            return [p for p in self.plugins if callable(getattr(p, method, None))]

        self.pre_enqueue_plugins = having("pre_enqueue")
        self.queue_sort_plugins = having("less")
        self.pre_filter_plugins = having("pre_filter")
        self.filter_plugins = having("filter")
        self.post_filter_plugins = having("post_filter")
        self.pre_score_plugins = having("pre_score")
        self.score_plugins = having("score")
        self.reserve_plugins = having("reserve") + [
            p for p in having("unreserve") if not callable(getattr(p, "reserve", None))
        ]
        self.permit_plugins = having("permit")
        self.pre_bind_plugins = having("pre_bind")
        self.post_bind_plugins = having("post_bind")
        self.bind_plugins = having("bind")
        self.sign_plugins = having("sign")
        self.placement_generate_plugins = having("generate_placements")
        self.placement_score_plugins = having("score_placement")
        self._waiting_pods: dict[str, WaitingPod] = {}
        self._metric_tick = 1  # 10% plugin-metric sampling LCG state
        # optional UNSAMPLED per-call observer (point, plugin, seconds) —
        # installed transiently by the flight recorder's fallback
        # attribution so host-fallback scoring is attributable per plugin
        self.plugin_observer = None

    # -- queue wiring -------------------------------------------------------

    def queue_sort_less(self, a, b) -> bool:
        if self.queue_sort_plugins:
            return self.queue_sort_plugins[0].less(a, b)
        return a.timestamp < b.timestamp

    def queueing_hint_map(self) -> dict[str, list[ClusterEventWithHint]]:
        m: dict[str, list[ClusterEventWithHint]] = {}
        for p in self.plugins:
            fn = getattr(p, "events_to_register", None)
            if callable(fn):
                m[p.name] = list(fn())
        return m

    # -- timing helper ------------------------------------------------------

    def _timed(self, point: str, plugin: str, fn: Callable[[], Any]) -> Any:
        obs = self.plugin_observer
        if obs is not None:
            # attribution window open (host-fallback path): time EVERY call
            # — the window is rare and short, and regressions there need
            # full per-plugin accounting, not a 10% sample
            t0 = time.perf_counter()
            try:
                return fn()
            finally:
                dt = time.perf_counter() - t0
                obs(point, plugin, dt)
                if self.metrics is not None:
                    self.metrics.observe_plugin(point, plugin, dt)
        if self.metrics is None:
            return fn()
        # sample ~1-in-10 like the reference (pluginMetricsSamplePercent=10,
        # schedule_one.go:50-51,130): two perf_counter calls + a histogram
        # observe per plugin per node per pod is measurable at wave scale.
        # LCG step, not a modulo tick — a deterministic tick aliases with
        # fixed per-pod call patterns and would starve specific plugins of
        # samples forever
        self._metric_tick = (self._metric_tick * 1103515245 + 12345) & 0x7FFFFFFF
        if self._metric_tick % 10:
            return fn()
        t0 = time.perf_counter()
        try:
            return fn()
        finally:
            self.metrics.observe_plugin(point, plugin, time.perf_counter() - t0)

    # -- extension points ---------------------------------------------------

    def run_pre_filter_plugins(
        self, state: CycleState, pod: Pod, nodes: list[NodeInfo]
    ) -> tuple[PreFilterResult | None, Status]:
        """framework.go RunPreFilterPlugins:907 — merge PreFilterResults,
        collect Skip set; UnschedulableAndUnresolvable aborts."""
        result: PreFilterResult | None = None
        skipped: set[str] = set()
        for p in self.pre_filter_plugins:
            r, st = self._timed("PreFilter", p.name, lambda p=p: p.pre_filter(state, pod, nodes))
            st = status_of(st)
            if st.is_skip:
                skipped.add(p.name)
                continue
            if not st.is_success:
                st.plugin = st.plugin or p.name
                return None, st
            if r is not None and not r.all_nodes:
                result = r if result is None else result.merge(r)
                if result.node_names is not None and not result.node_names:
                    return result, Status.unresolvable(
                        "node(s) didn't satisfy plugin(s) "
                        f"[{p.name}] simultaneously", plugin=p.name
                    )
        state.skip_filter_plugins = skipped
        return result, Status()

    def run_filter_plugins(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        """framework.go RunFilterPlugins:1078 — first rejection wins."""
        for p in self.filter_plugins:
            if p.name in state.skip_filter_plugins:
                continue
            st = status_of(
                self._timed("Filter", p.name, lambda p=p: p.filter(state, pod, node_info))
            )
            if not st.is_success:
                st.plugin = st.plugin or p.name
                return st
        return Status()

    def run_filter_plugins_batch(
        self, state: CycleState, pod: Pod, node_infos: list[NodeInfo]
    ) -> list[Status]:
        """run_filter_plugins over a node list in one call. Plugins that
        implement `filter_batch(state, pod, nodes) -> [Status|None]` answer
        all nodes at once (node-independent work runs once per pod — the
        host-side analogue of the dense kernel); others loop per node.
        Identical semantics to per-node run_filter_plugins: plugin order
        preserved, first rejection wins per node. A filter_batch returning
        None falls back to that plugin's per-node filter."""
        statuses: list[Status | None] = [None] * len(node_infos)
        pending = list(range(len(node_infos)))
        for p in self.filter_plugins:
            if p.name in state.skip_filter_plugins or not pending:
                continue
            batch = getattr(p, "filter_batch", None)
            res = None
            if callable(batch):
                nis = [node_infos[i] for i in pending]
                res = self._timed(
                    "Filter", p.name, lambda b=batch, nis=nis: b(state, pod, nis)
                )
            if res is not None and len(res) != len(pending):
                raise ValueError(
                    f"plugin {p.name} filter_batch returned {len(res)} "
                    f"statuses for {len(pending)} nodes"
                )
            if res is not None:
                still = []
                for i, st in zip(pending, res):
                    if st is None:
                        still.append(i)
                        continue
                    st = status_of(st)
                    if st.is_success:
                        still.append(i)
                    else:
                        st.plugin = st.plugin or p.name
                        statuses[i] = st
                pending = still
            else:
                still = []
                for i in pending:
                    st = status_of(self._timed(
                        "Filter", p.name,
                        lambda p=p, i=i: p.filter(state, pod, node_infos[i]),
                    ))
                    if st.is_success:
                        still.append(i)
                    else:
                        st.plugin = st.plugin or p.name
                        statuses[i] = st
                pending = still
        return [st if st is not None else Status() for st in statuses]

    def run_filter_plugins_with_nominated_pods(
        self, state: CycleState, pod: Pod, node_info: NodeInfo, nominated_pod_infos
    ) -> Status:
        """framework.go:1190 — filter twice when higher-priority nominated pods
        exist on the node: once with them assumed, once without."""
        if not nominated_pod_infos:
            return self.run_filter_plugins(state, pod, node_info)
        # pass 1: with nominated pods added
        ni = node_info.clone()
        state_clone = state.clone()
        for npi in nominated_pod_infos:
            ni.add_pod(npi)
            self.run_pre_filter_extension_add_pod(state_clone, pod, npi, ni)
        st = self.run_filter_plugins(state_clone, pod, ni)
        if not st.is_success:
            return st
        # pass 2: without
        return self.run_filter_plugins(state, pod, node_info)

    def run_pre_filter_extension_add_pod(self, state, pod, pod_info_to_add, node_info) -> Status:
        for p in self.pre_filter_plugins:
            if p.name in state.skip_filter_plugins:
                continue
            fn = getattr(p, "add_pod", None)
            if callable(fn):
                st = status_of(fn(state, pod, pod_info_to_add, node_info))
                if not st.is_success:
                    return st
        return Status()

    def run_pre_filter_extension_remove_pod(self, state, pod, pod_info_to_remove, node_info) -> Status:
        for p in self.pre_filter_plugins:
            if p.name in state.skip_filter_plugins:
                continue
            fn = getattr(p, "remove_pod", None)
            if callable(fn):
                st = status_of(fn(state, pod, pod_info_to_remove, node_info))
                if not st.is_success:
                    return st
        return Status()

    def run_post_filter_plugins(
        self, state: CycleState, pod: Pod, node_to_status: NodeToStatus
    ) -> tuple[PostFilterResult | None, Status]:
        """framework.go RunPostFilterPlugins — first success or first error wins;
        all Unschedulable -> combined Unschedulable."""
        statuses = []
        for p in self.post_filter_plugins:
            r, st = self._timed(
                "PostFilter", p.name, lambda p=p: p.post_filter(state, pod, node_to_status)
            )
            st = status_of(st)
            if st.is_success:
                return r, st
            if not st.is_rejected:
                st.plugin = st.plugin or p.name
                return r, st
            statuses.append(st)
        msg = "; ".join(s.message() for s in statuses if s.reasons)
        return None, Status.unschedulable(msg or "no postfilter plugin made progress")

    def run_pre_score_plugins(self, state: CycleState, pod: Pod,
                              nodes: list[NodeInfo],
                              skip: set[str] | frozenset = frozenset()) -> Status:
        """`skip` pre-seeds the score skip set WITHOUT running those
        plugins' pre_score — the hybrid path passes the kernel-covered
        plugins (their scores come from the device, so their host PreScore
        precompute over every node is pure waste)."""
        skipped: set[str] = set(skip)
        for p in self.pre_score_plugins:
            if p.name in skipped:
                continue
            st = status_of(
                self._timed("PreScore", p.name, lambda p=p: p.pre_score(state, pod, nodes))
            )
            if st.is_skip:
                skipped.add(p.name)
                continue
            if not st.is_success:
                st.plugin = st.plugin or p.name
                return st
        state.skip_score_plugins = skipped
        return Status()

    def run_score_plugins(
        self, state: CycleState, pod: Pod, nodes: list[NodeInfo]
    ) -> tuple[list[NodePluginScores], Status]:
        """framework.go RunScorePlugins:1320 — 3 passes: raw score per
        (plugin, node); NormalizeScore per plugin; weight + sum per node.

        The reference runs each pass under Parallelizer.Until over 16
        goroutines; host-side we run them sequentially (this path handles the
        sparse plugins only — dense scoring lives in the TPU kernel).
        """
        active = [p for p in self.score_plugins if p.name not in state.skip_score_plugins]
        all_scores: dict[str, list[tuple[str, int]]] = {ni.name: [] for ni in nodes}
        for p in active:
            raw: list = []
            batch = getattr(p, "score_batch", None)
            if callable(batch):
                vals = self._timed(
                    "Score", p.name, lambda b=batch: b(state, pod, nodes)
                )
                raw = [[ni.name, v] for ni, v in zip(nodes, vals)]
            else:
                for ni in nodes:
                    score, st = self._timed("Score", p.name, lambda p=p, ni=ni: p.score(state, pod, ni))
                    st = status_of(st)
                    if not st.is_success:
                        st.plugin = st.plugin or p.name
                        return [], st
                    raw.append([ni.name, score])
            norm = getattr(p, "normalize_score", None)
            if callable(norm):
                st = status_of(norm(state, pod, raw))
                if not st.is_success:
                    return [], st
            weight = self.weights.get(p.name, 1)
            for name, score in raw:
                if score > MAX_NODE_SCORE or score < MIN_NODE_SCORE:
                    return [], Status.as_error(
                        ValueError(f"plugin {p.name} score {score} out of range"), p.name
                    )
                all_scores[name].append((p.name, score * weight))
        out = []
        for ni in nodes:
            nps = NodePluginScores(name=ni.name, scores=all_scores[ni.name])
            nps.total_score = sum(s for _, s in nps.scores)
            out.append(nps)
        return out, Status()

    def run_reserve_plugins_reserve(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        for p in self.reserve_plugins:
            fn = getattr(p, "reserve", None)
            if not callable(fn):
                continue
            st = status_of(self._timed("Reserve", p.name, lambda fn=fn: fn(state, pod, node_name)))
            if not st.is_success:
                st.plugin = st.plugin or p.name
                return st
        return Status()

    def run_reserve_plugins_unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None:
        for p in reversed(self.reserve_plugins):
            fn = getattr(p, "unreserve", None)
            if callable(fn):
                self._timed("Unreserve", p.name, lambda fn=fn: fn(state, pod, node_name))

    def run_permit_plugins(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        """framework.go RunPermitPlugins:1923 — Wait statuses accumulate into a
        WaitingPod; rejection wins immediately."""
        plugin_timeouts: dict[str, float] = {}
        for p in self.permit_plugins:
            res = self._timed("Permit", p.name, lambda p=p: p.permit(state, pod, node_name))
            st, timeout = res if isinstance(res, tuple) else (res, 0.0)
            st = status_of(st)
            if st.is_success:
                continue
            if st.is_wait:
                plugin_timeouts[p.name] = self.clock.now() + min(
                    timeout or DEFAULT_PERMIT_TIMEOUT, DEFAULT_PERMIT_TIMEOUT
                )
                continue
            st.plugin = st.plugin or p.name
            return st
        if plugin_timeouts:
            self._waiting_pods[pod.meta.key] = WaitingPod(pod, plugin_timeouts)
            return Status.wait()
        return Status()

    def wait_on_permit(self, pod: Pod, max_wait: float | None = None) -> Status:
        """framework.go WaitOnPermit:2034 — block until allowed/rejected/
        timeout. Blocks on the WaitingPod's condition variable (the
        reference blocks on a channel) — deciders wake waiters directly, no
        polling loop burning CPU in every binding thread."""
        wp = self._waiting_pods.get(pod.meta.key)
        if wp is None:
            return Status()
        deadline = min(wp.pending_plugins.values()) if wp.pending_plugins else 0.0
        hard_stop = (self.clock.now() + max_wait) if max_wait is not None else None
        while True:
            now = self.clock.now()
            if wp.decision is not None:
                break
            if now >= deadline:
                self._waiting_pods.pop(pod.meta.key, None)
                return Status.unschedulable("pod rejected: permit wait timeout")
            stop = deadline if hard_stop is None else min(deadline, hard_stop)
            # the clock owns the blocking strategy: a real clock parks on
            # the WaitingPod's condition (woken by allow/reject), a virtual
            # clock advances its own time instead of blocking wall time
            decision = self.clock.wait_for(wp.wait_for_decision, stop - now)
            if decision is not None:
                break
            if hard_stop is not None and self.clock.now() >= hard_stop:
                break
        self._waiting_pods.pop(pod.meta.key, None)
        return wp.decision if wp.decision is not None else Status.wait()

    def waiting_pod(self, key: str) -> WaitingPod | None:
        return self._waiting_pods.get(key)

    def remove_waiting_pod(self, key: str) -> None:
        """Drop a permit waiter without a decision (group-cycle revert)."""
        self._waiting_pods.pop(key, None)

    def iterate_waiting_pods(self):
        return list(self._waiting_pods.values())

    def run_pre_bind_pre_flight(self, state: CycleState, pod: Pod, node_name: str) -> set[str]:
        """Returns pre-bind plugins that will do real work (PreBindPreFlight)."""
        active = set()
        for p in self.pre_bind_plugins:
            fn = getattr(p, "pre_bind_pre_flight", None)
            if callable(fn):
                st = status_of(fn(state, pod, node_name))
                if st.is_skip:
                    continue
            active.add(p.name)
        return active

    def run_pre_bind_plugins(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        for p in self.pre_bind_plugins:
            st = status_of(
                self._timed("PreBind", p.name, lambda p=p: p.pre_bind(state, pod, node_name))
            )
            if not st.is_success:
                st.plugin = st.plugin or p.name
                return st
        return Status()

    def run_bind_plugins(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        """framework.go RunBindPlugins:1756 — first non-Skip plugin binds."""
        if not self.bind_plugins:
            return Status.as_error(RuntimeError("no bind plugin"), "")
        for p in self.bind_plugins:
            st = status_of(
                self._timed("Bind", p.name, lambda p=p: p.bind(state, pod, node_name))
            )
            if st.is_skip:
                continue
            if not st.is_success:
                st.plugin = st.plugin or p.name
            return st
        return Status.skip()

    def run_post_bind_plugins(self, state: CycleState, pod: Pod, node_name: str) -> None:
        for p in self.post_bind_plugins:
            self._timed("PostBind", p.name, lambda p=p: p.post_bind(state, pod, node_name))

    # -- signatures (OpportunisticBatching) ---------------------------------

    def sign_pod(self, pod: Pod) -> str | None:
        """framework.go SignPod:857 — concatenate per-plugin fragments; any
        plugin returning None makes the pod unsignable."""
        frags = []
        for p in self.sign_plugins:
            frag = p.sign(pod)
            if frag is None:
                return None
            frags.append(f"{p.name}={frag}")
        return "|".join(frags) if frags else None

    # -- placements ---------------------------------------------------------

    def run_placement_generate_plugins(self, state, pods, parent_placement):
        placements = [parent_placement]
        for p in self.placement_generate_plugins:
            out, st = p.generate_placements(state, pods, placements)
            st = status_of(st)
            if not st.is_success:
                return placements, st
            if out:
                placements = out
        return placements, Status()

    def run_placement_score_plugins(self, state, pods, placement) -> int:
        total = 0
        for p in self.placement_score_plugins:
            score, st = p.score_placement(state, pods, placement)
            if status_of(st).is_success:
                total += score * self.weights.get(p.name, 1)
        return total
