"""Scheduler-framework public plugin API: status codes and plugin interfaces.

Reference: staging/src/k8s.io/kube-scheduler/framework/interface.go — `Code`
(7 statuses), `Status`, and the extension-point interfaces (PreEnqueue :442,
QueueSort :454, PreFilter :508, Filter :537, PostFilter :566, PreScore :593,
Score :614, Reserve :631, PreBind :647, PostBind :664, Permit :675, Bind :688,
SignPlugin :735, PlacementGenerate :762, PlacementScore :787). Python plugins
implement these by defining the corresponding methods; the runtime discovers
extension points by hasattr (duck typing replaces Go interface assertions).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Any

if TYPE_CHECKING:
    from ..nodeinfo import NodeInfo
    from ...api.types import Pod

MAX_NODE_SCORE = 100
MIN_NODE_SCORE = 0

# --- status codes (interface.go Code) -------------------------------------

SUCCESS = 0
ERROR = 1
UNSCHEDULABLE = 2
UNSCHEDULABLE_AND_UNRESOLVABLE = 3
WAIT = 4
SKIP = 5
PENDING = 6

_CODE_NAMES = {
    SUCCESS: "Success",
    ERROR: "Error",
    UNSCHEDULABLE: "Unschedulable",
    UNSCHEDULABLE_AND_UNRESOLVABLE: "UnschedulableAndUnresolvable",
    WAIT: "Wait",
    SKIP: "Skip",
    PENDING: "Pending",
}


class Status:
    """Plugin result. None is treated as Success everywhere (as in Go)."""

    __slots__ = ("code", "reasons", "plugin", "error")

    def __init__(
        self,
        code: int = SUCCESS,
        reasons: tuple[str, ...] = (),
        plugin: str = "",
        error: Exception | None = None,
    ):
        self.code = code
        self.reasons = reasons
        self.plugin = plugin
        self.error = error

    # constructors mirroring framework.NewStatus / AsStatus
    @classmethod
    def unschedulable(cls, *reasons: str, plugin: str = "") -> "Status":
        return cls(UNSCHEDULABLE, reasons, plugin)

    @classmethod
    def unresolvable(cls, *reasons: str, plugin: str = "") -> "Status":
        return cls(UNSCHEDULABLE_AND_UNRESOLVABLE, reasons, plugin)

    @classmethod
    def as_error(cls, err: Exception, plugin: str = "") -> "Status":
        return cls(ERROR, (str(err),), plugin, err)

    @classmethod
    def skip(cls, plugin: str = "") -> "Status":
        return cls(SKIP, (), plugin)

    @classmethod
    def wait(cls, plugin: str = "") -> "Status":
        return cls(WAIT, (), plugin)

    @classmethod
    def pending(cls, *reasons: str, plugin: str = "") -> "Status":
        return cls(PENDING, reasons, plugin)

    @property
    def is_success(self) -> bool:
        return self.code == SUCCESS

    @property
    def is_skip(self) -> bool:
        return self.code == SKIP

    @property
    def is_wait(self) -> bool:
        return self.code == WAIT

    @property
    def is_rejected(self) -> bool:
        """Unschedulable family (interface.go IsRejected)."""
        return self.code in (UNSCHEDULABLE, UNSCHEDULABLE_AND_UNRESOLVABLE, PENDING)

    @property
    def code_name(self) -> str:
        return _CODE_NAMES.get(self.code, str(self.code))

    def message(self) -> str:
        return "; ".join(self.reasons)

    def __repr__(self) -> str:
        return f"Status({self.code_name}, {self.reasons}, plugin={self.plugin})"


def status_of(s: "Status | None") -> Status:
    return s if s is not None else Status()


# --- results --------------------------------------------------------------


@dataclass
class PreFilterResult:
    """Narrows the candidate node set (interface.go PreFilterResult)."""

    node_names: set[str] | None = None  # None = all nodes

    def merge(self, other: "PreFilterResult") -> "PreFilterResult":
        if self.node_names is None:
            return PreFilterResult(other.node_names)
        if other.node_names is None:
            return PreFilterResult(self.node_names)
        return PreFilterResult(self.node_names & other.node_names)

    @property
    def all_nodes(self) -> bool:
        return self.node_names is None


@dataclass
class PostFilterResult:
    nominated_node_name: str = ""
    nominating_mode: str = "ModeOverride"  # ModeNoop | ModeOverride


@dataclass
class NodeScore:
    name: str
    score: int


@dataclass
class NodePluginScores:
    name: str
    scores: list[tuple[str, int]] = field(default_factory=list)  # (plugin, weighted)
    total_score: int = 0


@dataclass
class NodeToStatus:
    """Per-node filter failure map with an absent-node default.

    Reference: framework/types.go NodeToStatus — preemption needs to know
    whether unlisted nodes were rejected as Unschedulable (retriable by
    removing victims) or UnschedulableAndUnresolvable.
    """

    node_to_status: dict[str, Status] = field(default_factory=dict)
    absent_nodes_status: Status = field(default_factory=lambda: Status(UNSCHEDULABLE_AND_UNRESOLVABLE))

    def get(self, node_name: str) -> Status:
        return self.node_to_status.get(node_name, self.absent_nodes_status)

    def set(self, node_name: str, status: Status) -> None:
        self.node_to_status[node_name] = status

    def aggregate_reasons(self) -> dict[str, int]:
        """reason string -> node count (FitError's message body). Subclasses
        backed by dense kernel rows aggregate vectorized instead of
        materializing a Status per node."""
        reasons: dict[str, int] = {}
        for st in self.node_to_status.values():
            for r in st.reasons:
                reasons[r] = reasons.get(r, 0) + 1
        return reasons

    def nodes_with_code(self, code: int, snapshot) -> list:
        out = []
        for ni in snapshot.list_nodes():
            if self.get(ni.name).code == code:
                out.append(ni)
        return out


class FitError(Exception):
    """Scheduling failed: no node fits (framework/types.go FitError)."""

    def __init__(self, pod, num_all_nodes: int, diagnosis: "Diagnosis"):
        self.pod = pod
        self.num_all_nodes = num_all_nodes
        self.diagnosis = diagnosis
        # message building is LAZY (__str__): a preemption-heavy workload
        # raises a FitError per pod per attempt, and walking every node's
        # status to format a message nobody may read was a top cost
        super().__init__()

    def __str__(self) -> str:
        return self.error_message()

    def error_message(self) -> str:
        reasons = self.diagnosis.node_to_status.aggregate_reasons()
        parts = [f"{n} {r}" for r, n in sorted(reasons.items())]
        return (
            f"0/{self.num_all_nodes} nodes are available: {', '.join(parts) or 'none'}"
        )


@dataclass
class Diagnosis:
    node_to_status: NodeToStatus = field(default_factory=NodeToStatus)
    unschedulable_plugins: set[str] = field(default_factory=set)
    pending_plugins: set[str] = field(default_factory=set)
    pre_filter_msg: str = ""
    post_filter_msg: str = ""


@dataclass
class ScheduleResult:
    suggested_host: str = ""
    evaluated_nodes: int = 0
    feasible_nodes: int = 0
    nominating_info: PostFilterResult | None = None


class Plugin:
    """Base plugin. Subclasses define extension-point methods:

    - pre_enqueue(pod) -> Status
    - less(pod_info_a, pod_info_b) -> bool                       (QueueSort)
    - events_to_register() -> list[ClusterEventWithHint]
    - pre_filter(state, pod, nodes) -> (PreFilterResult|None, Status)
    - pre_filter_extensions() -> self | None  (add_pod/remove_pod)
    - filter(state, pod, node_info) -> Status
    - post_filter(state, pod, node_to_status) -> (PostFilterResult|None, Status)
    - pre_score(state, pod, nodes) -> Status
    - score(state, pod, node_info) -> (int, Status)
    - normalize_score(state, pod, scores) -> Status
    - reserve(state, pod, node_name) -> Status / unreserve(...)
    - permit(state, pod, node_name) -> (Status, timeout_seconds)
    - pre_bind(state, pod, node_name) -> Status
    - pre_bind_pre_flight(state, pod, node_name) -> Status
    - bind(state, pod, node_name) -> Status
    - post_bind(state, pod, node_name) -> None
    - sign(pod) -> str | None                                     (SignPlugin)
    - generate_placements(state, pods, parent) -> (list[Placement], Status)
    - score_placement(state, pods, placement) -> (int, Status)
    """

    name = "Plugin"

    def __repr__(self) -> str:
        return self.name


@dataclass
class WaitingPod:
    """A pod parked at Permit (runtime/waiting_pods_map.go). Deciders
    (allow/reject) signal the condition so WaitOnPermit blocks on a real
    wakeup instead of polling (framework.go:2034 blocks on a channel)."""

    pod: Any
    pending_plugins: dict[str, float] = field(default_factory=dict)  # plugin -> deadline
    decision: Status | None = None

    def __post_init__(self):
        import threading

        self._cond = threading.Condition()

    def allow(self, plugin: str) -> None:
        with self._cond:
            self.pending_plugins.pop(plugin, None)
            if not self.pending_plugins and self.decision is None:
                self.decision = Status()
            self._cond.notify_all()

    def reject(self, plugin: str, msg: str) -> None:
        with self._cond:
            self.decision = Status.unschedulable(msg, plugin=plugin)
            self._cond.notify_all()

    def wait_for_decision(self, timeout: float) -> Status | None:
        """Block until a decision lands or timeout elapses."""
        with self._cond:
            if self.decision is None and timeout > 0:
                self._cond.wait(timeout)
            return self.decision
