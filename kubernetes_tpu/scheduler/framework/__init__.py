"""Scheduler framework: plugin API, cycle state, events, runtime.

Reference: staging/src/k8s.io/kube-scheduler/framework (public API) +
pkg/scheduler/framework/runtime (the plugin runner).
"""

from . import events  # noqa: F401
from .cycle_state import CycleState  # noqa: F401
from .interface import (  # noqa: F401
    Status,
    Plugin,
    PreFilterResult,
    PostFilterResult,
    NodeScore,
    NodePluginScores,
    NodeToStatus,
    Diagnosis,
    FitError,
    ScheduleResult,
    WaitingPod,
    status_of,
    SUCCESS,
    ERROR,
    UNSCHEDULABLE,
    UNSCHEDULABLE_AND_UNRESOLVABLE,
    WAIT,
    SKIP,
    PENDING,
    MAX_NODE_SCORE,
    MIN_NODE_SCORE,
)
