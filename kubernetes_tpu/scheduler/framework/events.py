"""Cluster-event taxonomy driving queueing hints.

Reference: staging/src/k8s.io/kube-scheduler/framework/types.go:33-183 —
ActionType bitmask + EventResource; ClusterEventWithHint at :185-227.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

# ActionType bits (types.go:33)
ADD = 1 << 0
DELETE = 1 << 1
UPDATE_NODE_ALLOCATABLE = 1 << 2
UPDATE_NODE_LABEL = 1 << 3
UPDATE_NODE_TAINT = 1 << 4
UPDATE_NODE_CONDITION = 1 << 5
UPDATE_NODE_ANNOTATION = 1 << 6
UPDATE_POD_LABEL = 1 << 7
UPDATE_POD_SCALE_DOWN = 1 << 8
UPDATE_POD_TOLERATIONS = 1 << 9
UPDATE_POD_SCHEDULING_GATES_ELIMINATED = 1 << 10
UPDATE_POD_GENERATED_RESOURCE_CLAIM = 1 << 11
UPDATE = (
    UPDATE_NODE_ALLOCATABLE
    | UPDATE_NODE_LABEL
    | UPDATE_NODE_TAINT
    | UPDATE_NODE_CONDITION
    | UPDATE_NODE_ANNOTATION
    | UPDATE_POD_LABEL
    | UPDATE_POD_SCALE_DOWN
    | UPDATE_POD_TOLERATIONS
    | UPDATE_POD_SCHEDULING_GATES_ELIMINATED
    | UPDATE_POD_GENERATED_RESOURCE_CLAIM
)
ALL = ADD | DELETE | UPDATE

# EventResource (types.go:124)
POD = "Pod"
ASSIGNED_POD = "AssignedPod"
UNSCHEDULED_POD = "UnscheduledPod"
NODE = "Node"
POD_GROUP = "PodGroup"
PVC = "PersistentVolumeClaim"
PV = "PersistentVolume"
STORAGE_CLASS = "StorageClass"
CSI_NODE = "CSINode"
RESOURCE_CLAIM = "ResourceClaim"
RESOURCE_SLICE = "ResourceSlice"
WILDCARD = "*"


@dataclass(frozen=True)
class ClusterEvent:
    resource: str
    action_type: int
    label: str = ""

    def match(self, other: "ClusterEvent") -> bool:
        """Does a registered event (self) cover a fired event (other)?"""
        res_ok = self.resource == WILDCARD or self.resource == other.resource or (
            self.resource == POD and other.resource in (ASSIGNED_POD, UNSCHEDULED_POD)
        )
        return res_ok and bool(self.action_type & other.action_type)

    def __str__(self) -> str:
        return self.label or f"{self.resource}:{self.action_type}"


# QueueingHint results (types.go QueueingHint)
QUEUE_SKIP = 0
QUEUE = 1

# hint fn: (pod, old_obj, new_obj) -> QUEUE | QUEUE_SKIP (raise -> treated as QUEUE)
QueueingHintFn = Callable[[Any, Any, Any], int]


@dataclass
class ClusterEventWithHint:
    event: ClusterEvent
    queueing_hint_fn: QueueingHintFn | None = None


# Common pre-made events
EVENT_WILDCARD = ClusterEvent(WILDCARD, ALL, "WildCardEvent")
EVENT_UNSCHEDULED_POD_ADD = ClusterEvent(UNSCHEDULED_POD, ADD, "UnscheduledPodAdd")
EVENT_UNSCHEDULED_POD_UPDATE = ClusterEvent(UNSCHEDULED_POD, UPDATE, "UnscheduledPodUpdate")
EVENT_ASSIGNED_POD_ADD = ClusterEvent(ASSIGNED_POD, ADD, "AssignedPodAdd")
EVENT_ASSIGNED_POD_DELETE = ClusterEvent(ASSIGNED_POD, DELETE, "AssignedPodDelete")
EVENT_NODE_ADD = ClusterEvent(NODE, ADD, "NodeAdd")
EVENT_NODE_DELETE = ClusterEvent(NODE, DELETE, "NodeDelete")
EVENT_NODE_ALLOCATABLE = ClusterEvent(NODE, UPDATE_NODE_ALLOCATABLE, "NodeAllocatable")
EVENT_NODE_LABEL = ClusterEvent(NODE, UPDATE_NODE_LABEL, "NodeLabel")
EVENT_NODE_TAINT = ClusterEvent(NODE, UPDATE_NODE_TAINT, "NodeTaint")
EVENT_POD_GROUP_ADD = ClusterEvent(POD_GROUP, ADD, "PodGroupAdd")
