"""Per-pod scheduling + binding cycles.

Reference: pkg/scheduler/schedule_one.go — ScheduleOne:66, schedulingCycle:174,
schedulePod:568, findNodesThatFitPod:626, findNodesThatPassFilters:775,
numFeasibleNodesToFind:862, prioritizeNodes:941, selectHost:1080,
bindingCycle:396, handleSchedulingFailure:1188.

TPU divergence: findNodesThatPassFilters + prioritizeNodes delegate to the
TPU backend (one dense pods x nodes kernel) when the profile carries one and
every non-kernelizable plugin is skippable for the pod; otherwise the host
path below runs. Host path is sequential (no goroutine fan-out) — it exists
for correctness, golden-testing, and the sparse long-tail plugins.
"""

from __future__ import annotations

import os
import random
import time as _time

from ..api.types import Pod
from .framework.cycle_state import CycleState
from .framework.interface import (
    Diagnosis,
    FitError,
    ScheduleResult,
    Status,
    UNSCHEDULABLE,
)
from .framework.runtime import Framework
from .nodeinfo import NodeInfo, PodInfo
from .queue.scheduling_queue import QueuedPodInfo
from ..utils import faultinject
from ..utils.envknob import float_env, int_env
from ..utils.logging import get_logger
from ..utils.tracing import Span, threshold_log_exporter

_log = get_logger("scheduler")

# slow-cycle diagnosis (utiltrace LogIfLong, schedule_one.go:570-571):
# steps are span events, formatted + logged only when the cycle breaches
# the threshold; logs to the legacy "kubernetes_tpu.trace" logger so
# existing scrapers keep matching (utils.tracing is the ONE tracer
# surface — the ledger's exemplar links depend on it)
_SLOW_CYCLE_THRESHOLD_S = 0.1
_slow_cycle_export = threshold_log_exporter(_SLOW_CYCLE_THRESHOLD_S)

MIN_FEASIBLE_NODES_TO_FIND = 100  # schedule_one.go:56
MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND = 5  # schedule_one.go:62

# wave-size cap while the TPU circuit breaker is HALF_OPEN: a recovering
# device probes with small waves instead of being handed a full one (a
# probe failure then strands N pods, not max_pods)
PROBE_WAVE_PODS = int_env("KUBE_TPU_PROBE_WAVE_PODS", 8)

# async-bind completion budget: total seconds a binding cycle waits for the
# dispatcher to land one bind call. Waited in short slices (so a stalled
# dispatcher surfaces in the log before the budget burns down) instead of
# one silent blocking wait that would freeze the pipelined loop's binding
# thread for the whole budget with no diagnosis.
BIND_WAIT_S = float_env("KUBE_TPU_BIND_WAIT_S", 30.0)
_BIND_WAIT_SLICE_S = 5.0


def num_feasible_nodes_to_find(percentage: int, num_all_nodes: int) -> int:
    """Adaptive sampling formula (schedule_one.go:862-888)."""
    if num_all_nodes < MIN_FEASIBLE_NODES_TO_FIND or percentage >= 100:
        return num_all_nodes
    adaptive = percentage
    if adaptive == 0:
        adaptive = 50 - num_all_nodes // 125
        if adaptive < MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND:
            adaptive = MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND
    num = num_all_nodes * adaptive // 100
    if num < MIN_FEASIBLE_NODES_TO_FIND:
        return MIN_FEASIBLE_NODES_TO_FIND
    return num


class SchedulingAlgorithm:
    """schedulePod + helpers, bound to one framework profile."""

    def __init__(
        self,
        framework: Framework,
        percentage_of_nodes_to_score: int = 0,
        rng: random.Random | None = None,
        nominator=None,
        extenders: list | None = None,
    ):
        self.fw = framework
        self.percentage = percentage_of_nodes_to_score
        self.next_start_node_index = 0
        self.rng = rng or random.Random(0)  # seeded: deterministic tie-breaks
        self.nominator = nominator  # queue, for nominated-pod protection
        self.extenders = list(extenders or [])
        self.batch = None  # BatchCache when OpportunisticBatching is on
        self.snapshot = None  # set per cycle by schedule_pod

    # -- filtering -----------------------------------------------------------

    def find_nodes_that_fit_pod(
        self, state: CycleState, pod: Pod, snapshot, nominated_node: str = "",
        pre_filter_done: tuple | None = None,
    ) -> tuple[list[NodeInfo], Diagnosis]:
        all_nodes = snapshot.list_nodes()
        diagnosis = Diagnosis()
        if pre_filter_done is not None:
            # PreFilter already ran this cycle (batch hint path) — rerunning
            # it would double the hot-path setup work
            result, status = pre_filter_done
        else:
            result, status = self.fw.run_pre_filter_plugins(state, pod, all_nodes)
        if not status.is_success:
            if status.is_rejected:
                diagnosis.pre_filter_msg = status.message()
                diagnosis.unschedulable_plugins.add(status.plugin)
                diagnosis.node_to_status.absent_nodes_status = status
                return [], diagnosis
            raise RuntimeError(f"prefilter failed: {status.reasons}")

        # nominated-node fast path (schedule_one.go:718 evaluateNominatedNode)
        if nominated_node:
            ni = snapshot.get(nominated_node)
            if ni is not None:
                feasible = self._filter_one(state, pod, ni, diagnosis)
                if feasible:
                    return [ni], diagnosis

        nodes = all_nodes
        if result is not None and not result.all_nodes:
            nodes = [n for n in all_nodes if n.name in result.node_names]
            diagnosis.node_to_status.absent_nodes_status = Status.unresolvable(
                "node(s) didn't satisfy plugin(s) "
                f"[{', '.join(sorted(diagnosis.unschedulable_plugins)) or 'prefilter'}]"
            )
        feasible = self._find_nodes_that_pass_filters(state, pod, nodes, diagnosis)
        if self.extenders and feasible:
            from .extender import find_nodes_that_pass_extenders

            feasible = find_nodes_that_pass_extenders(
                self.extenders, pod, feasible, diagnosis
            )
        return feasible, diagnosis

    def _filter_one(self, state, pod, ni: NodeInfo, diagnosis: Diagnosis) -> bool:
        nominated = self._nominated_pod_infos(pod, ni)
        st = self.fw.run_filter_plugins_with_nominated_pods(state, pod, ni, nominated)
        if st.is_success:
            return True
        diagnosis.node_to_status.set(ni.name, st)
        if st.plugin:
            diagnosis.unschedulable_plugins.add(st.plugin)
        return False

    def _nominated_pod_infos(self, pod: Pod, ni: NodeInfo) -> list[PodInfo]:
        """Equal-or-higher-priority pods nominated onto this node must be
        assumed during filtering so a preemptor's freed resources aren't
        stolen (schedule_one.go:1190 addNominatedPods)."""
        if self.nominator is None:
            return []
        out = []
        for key in self.nominator.nominated_pods_for_node(ni.name):
            if key == pod.meta.key:
                continue
            npi = self.nominator.nominated_pod_info(key)
            if npi is not None and npi.pod.spec.priority >= pod.spec.priority:
                out.append(npi)
        return out

    def _find_nodes_that_pass_filters(
        self, state, pod, nodes: list[NodeInfo], diagnosis: Diagnosis
    ) -> list[NodeInfo]:
        """findNodesThatPassFilters:775 — rotate start index for fairness,
        stop at numFeasibleNodesToFind (early exit)."""
        num_all = len(nodes)
        num_to_find = num_feasible_nodes_to_find(self.percentage, num_all)
        feasible: list[NodeInfo] = []
        start = self.next_start_node_index % num_all if num_all else 0
        evaluated = 0
        for i in range(num_all):
            ni = nodes[(start + i) % num_all]
            evaluated += 1
            if self._filter_one(state, pod, ni, diagnosis):
                feasible.append(ni)
                if len(feasible) >= num_to_find:
                    break
        self.next_start_node_index = (start + evaluated) % num_all if num_all else 0
        return feasible

    # -- scoring ---------------------------------------------------------------

    def prioritize_nodes(
        self, state: CycleState, pod: Pod, nodes: list[NodeInfo]
    ) -> list:
        """prioritizeNodes:941 — PreScore + 3-pass Score; returns
        NodePluginScores list."""
        if not self.fw.score_plugins and not self.fw.pre_score_plugins:
            from .framework.interface import NodePluginScores

            return [NodePluginScores(name=n.name, total_score=1) for n in nodes]
        st = self.fw.run_pre_score_plugins(state, pod, nodes)
        if not st.is_success:
            raise RuntimeError(f"prescore failed: {st.reasons}")
        scores, st = self.fw.run_score_plugins(state, pod, nodes)
        if not st.is_success:
            raise RuntimeError(f"score failed: {st.reasons}")
        if self.extenders:
            from .extender import extender_scores

            ext = extender_scores(self.extenders, pod, nodes)
            if ext:
                for nps in scores:
                    bonus = ext.get(nps.name, 0)
                    if bonus:
                        nps.scores.append(("extenders", bonus))
                        nps.total_score += bonus
        return scores

    def select_host(self, node_scores: list, count: int = 1) -> tuple[str, list]:
        """selectHost:1080 — heap-select top `count`, random tie-break among
        max-score nodes (seeded rng makes it reproducible)."""
        if not node_scores:
            raise ValueError("empty priority list")
        best = max(s.total_score for s in node_scores)
        winners = [s for s in node_scores if s.total_score == best]
        chosen = winners[self.rng.randrange(len(winners))] if len(winners) > 1 else winners[0]
        ordered = sorted(node_scores, key=lambda s: -s.total_score)
        return chosen.name, ordered

    # -- schedulePod ------------------------------------------------------------

    def schedule_pod(self, state: CycleState, pod: Pod, snapshot) -> ScheduleResult:
        """schedulePod:568 — the complete algorithm for one pod."""
        if snapshot.num_nodes() == 0:
            raise FitError(pod, 0, Diagnosis())
        # opportunistic batching (findNodesThatFitPod:654 GetNodeHint): an
        # identical pod signed earlier this batch window reuses its sorted
        # score list — only the hinted node is re-Filtered
        signature = None
        pre_filter_done = None
        if self.batch is not None and not pod.status.nominated_node_name:
            signature = self.fw.sign_pod(pod)
            # only pay the hint-path PreFilter when a fresh entry exists —
            # otherwise the full path below runs PreFilter exactly once
            if signature is not None and self.batch.has_fresh(signature):
                hinted, pre_filter_done = self._try_node_hint(
                    state, pod, snapshot, signature
                )
                if hinted is not None:
                    return ScheduleResult(
                        suggested_host=hinted, evaluated_nodes=1, feasible_nodes=1
                    )

        # nominated-node fast path: a preemptor retries its nomination first
        # (schedule_one.go:718 evaluateNominatedNode)
        nominated = pod.status.nominated_node_name
        feasible, diagnosis = self.find_nodes_that_fit_pod(
            state, pod, snapshot, nominated_node=nominated,
            pre_filter_done=pre_filter_done,
        )
        if not feasible:
            raise FitError(pod, snapshot.num_nodes(), diagnosis)
        if len(feasible) == 1:
            return ScheduleResult(
                suggested_host=feasible[0].name,
                evaluated_nodes=1 + len(diagnosis.node_to_status.node_to_status),
                feasible_nodes=1,
            )
        scores = self.prioritize_nodes(state, pod, feasible)
        host, ordered = self.select_host(scores)
        if signature is not None:
            self.batch.store_schedule_results(
                signature, [s.name for s in ordered]
            )
        return ScheduleResult(
            suggested_host=host,
            evaluated_nodes=len(feasible) + len(diagnosis.node_to_status.node_to_status),
            feasible_nodes=len(feasible),
        )

    def _try_node_hint(self, state, pod, snapshot, signature: str):
        """Run PreFilter (CycleState must be populated for the Filter
        re-check and the later Reserve/PreBind), then consult the batch
        cache. Returns (hinted_node | None, pre_filter_done) so a miss hands
        its PreFilter work to the full path instead of rerunning it."""
        all_nodes = snapshot.list_nodes()
        result, status = self.fw.run_pre_filter_plugins(state, pod, all_nodes)
        if not status.is_success:
            return None, (result, status)

        def filter_fn(node_name: str) -> bool:
            ni = snapshot.get(node_name)
            if ni is None:
                return False
            return self._filter_one(state, pod, ni, Diagnosis())

        return self.batch.get_node_hint(signature, filter_fn), (result, status)


class ScheduleOneLoop:
    """The per-pod cycle driver: pop → schedule → assume/reserve/permit → bind.

    Reference: ScheduleOne:66 + schedulingCycle:174 + bindingCycle:396. The
    binding cycle can run inline (deterministic tests) or on a thread pool
    (pipeline parallelism pod N+1 scheduling overlaps pod N binding — §2.9.2).
    """

    # fleet ownership predicate on the pop side (installed by
    # scheduler/fleet.py, the sole writer — kubesched-lint FLEET01):
    # catches pods whose shard lease moved after queue admission
    shard_filter = None

    def __init__(
        self,
        cache,
        queue,
        profiles: dict[str, Framework],
        algorithms: dict[str, SchedulingAlgorithm],
        store,
        snapshot,
        metrics=None,
        async_binding: bool = False,
        event_recorder=None,
        names=None,
        api_cacher=None,
        pod_group_cycles: bool = True,
        recorder=None,
    ):
        from ..api.resource import ResourceNames
        # lazy: the tpu package import pulls in the backend (which imports
        # this module); the recorder module itself is dependency-free
        from .tpu.flightrecorder import FlightRecorder

        self.names = names or ResourceNames()
        self.cache = cache
        self.queue = queue
        self.profiles = profiles
        self.algorithms = algorithms
        self.store = store
        self.snapshot = snapshot
        self.metrics = metrics
        self.async_binding = async_binding
        self.event_recorder = event_recorder
        self.api_cacher = api_cacher  # SchedulerAsyncAPICalls path
        self.pod_group_cycles = pod_group_cycles
        self._binding_threads: list = []
        # wall-clock seconds per pipeline phase (batched wave path), reported
        # by bench.py — the in-process analogue of the reference's
        # FrameworkExtensionPointDuration histograms (metrics.go:340).
        # The wave flight recorder owns the stopwatches; phase_profile
        # aliases its phase_totals dict (same object), so the harness's
        # snapshot-delta protocol and direct accumulation sites both read
        # and write recorder-sourced numbers.
        self.recorder = recorder if recorder is not None else FlightRecorder(
            metrics=metrics
        )
        self.phase_profile = self.recorder.phase_totals
        # the launched-but-unprocessed batched wave: (algo, InflightWave).
        # While its kernel runs on device, the host processes the PREVIOUS
        # wave's results — the TPU-native form of the reference's
        # scheduling/binding pipeline parallelism (schedule_one.go:146)
        self._inflight_wave: tuple | None = None
        # streaming-waves knobs (README "Streaming waves"): depth <= 1
        # degrades the pipeline to the serial loop (launch then complete
        # immediately — same code path, so the golden triple covers both);
        # env is read at construction so tests can flip it per instance
        from .tpu.wavecontroller import WaveSizeController

        self.pipeline_depth = max(
            1, int_env("KUBE_TPU_PIPELINE_DEPTH", 2)
        )
        # gang waves (README "Gang waves"): whole-PodGroup device placement
        # instead of the per-placement host dry-run loop; env-gated so
        # parity tests and the chaos soak can pin either path per instance
        self.gang_waves = os.environ.get("KUBE_TPU_GANG_WAVES", "1") != "0"
        # adaptive wave sizing: queue depth decides the next wave's pow2
        # target within the caller's max_pods cap (the breaker's HALF_OPEN
        # probe break below stays authoritative over both)
        self.wave_controller = WaveSizeController()
        # async wave-bind completions: dispatcher worker threads only append
        # here; the scheduling thread drains. Keeping ALL queue/cache/carry
        # mutation on the scheduling thread avoids check-then-act races on
        # the pipeline's coherence flags.
        import collections

        self._wave_completions: "collections.deque[tuple]" = collections.deque()
        # correlation tokens for per-wave event aggregation: one token per
        # bound wave so the recorder can fold the wave's Scheduled spam into
        # a single aggregate past its spill threshold
        self._wave_event_seq = 0

    def framework_for_pod(self, pod: Pod) -> Framework | None:
        return self.profiles.get(pod.spec.scheduler_name)

    def _skip_pod_schedule(self, fw: Framework, pod: Pod) -> bool:
        """skipPodSchedule:546 — deleted or already-assumed pods; in a
        fleet, also pods whose shard this member no longer holds (the
        lease moved between queue admission and this pop)."""
        sf = self.shard_filter
        if sf is not None and not sf(pod):
            return True
        if pod.is_terminating:
            return True
        if not self.store.contains("Pod", pod.meta.key):
            return True
        if self.cache.is_assumed_pod(pod):
            return True
        return False

    # -- one iteration -----------------------------------------------------------

    def schedule_one(self, timeout: float | None = 0.05) -> bool:
        """Pop and schedule one pod; returns False when queue empty."""
        qpi = self.queue.pop(timeout=timeout)
        if qpi is None:
            return False
        self.schedule_pod_info(qpi)
        return True

    def schedule_pod_info(self, qpi: QueuedPodInfo) -> None:
        pod = qpi.pod
        fw = self.framework_for_pod(pod)
        if fw is None:
            self.queue.done(qpi.key, qpi.inflight_token)
            return
        if self._skip_pod_schedule(fw, pod):
            self.queue.done(qpi.key, qpi.inflight_token)
            return
        # whole-gang cycle (ScheduleOne, schedule_one.go:77: SchedulingGroup
        # + GenericWorkload gate routes to scheduleOnePodGroup)
        if pod.spec.scheduling_group is not None and self.pod_group_cycles:
            sp = Span(name="SchedulingPodGroup", start=_time.perf_counter(),
                      attributes={"pod": pod.meta.key})
            self.schedule_pod_group(qpi, fw)
            sp.end = _time.perf_counter()
            _slow_cycle_export(sp)
            return

        sp = Span(name="Scheduling", start=_time.perf_counter(),
                  attributes={"pod": pod.meta.key,
                              "scheduler": fw.profile_name})
        # ledger: a host-path cycle is this pod's "wave" admission
        ledger = self.recorder.pod_ledger
        ledger.stamp(pod.meta.key, "wave_admission")
        state = CycleState()
        scheduling_cycle = self.queue.moved_count
        result, status = self._scheduling_cycle(state, fw, qpi)
        sp.event("Computing pod placement done" if status.is_success
                 else "Scheduling attempt failed")
        if not status.is_success:
            self._handle_scheduling_failure(fw, qpi, status, scheduling_cycle)
            sp.event("Failure handled (requeue + condition)")
            sp.end = _time.perf_counter()
            _slow_cycle_export(sp)
            return
        ledger.stamp(pod.meta.key, "kernel_verdict")
        self._dispatch_binding(state, fw, qpi, result)
        sp.event("Binding dispatched")
        sp.end = _time.perf_counter()
        _slow_cycle_export(sp)

    def _dispatch_binding(self, state, fw: Framework, qpi: QueuedPodInfo,
                          result: ScheduleResult) -> None:
        """Run the binding cycle inline or on a thread. A pod parked at
        Permit (gang quorum wait) MUST bind on a thread even in sync mode:
        the scheduling loop has to keep scheduling its siblings or quorum
        never arrives (reference: bindingCycle is always a goroutine,
        schedule_one.go:146)."""
        must_thread = fw.waiting_pod(qpi.pod.meta.key) is not None
        if self.async_binding or must_thread:
            import threading

            t = threading.Thread(
                target=self._binding_cycle, args=(state, fw, qpi, result), daemon=True
            )
            self._binding_threads.append(t)
            t.start()
        else:
            self._binding_cycle(state, fw, qpi, result)

    # -- batched wave -------------------------------------------------------------

    def schedule_wave(self, max_pods: int = 256, timeout: float | None = 0.0) -> int:
        """Pop a run of wave-eligible pods and schedule them in ONE device
        program (TPUBackend.run_batched), then run the normal per-pod
        assume/reserve/permit/bind cycle for each winner.

        Decisions are bit-identical to popping the same pods one at a time
        (the scan carries assumes between pods and draws the host selectHost
        tie-break from the algorithm's rng). Ineligible pods — gang members,
        claim/extender pods, nominated pods, non-TPU profiles — end the wave
        and go through the per-pod path, preserving queue order semantics.

        Returns the number of pods processed (0 = queue empty)."""
        from .tpu.backend import TPUSchedulingAlgorithm

        wave: list[QueuedPodInfo] = []
        wave_algo = None
        trailer: QueuedPodInfo | None = None
        # adaptive wave sizing: the queue's active depth (deterministic —
        # pure informer/store state) picks the next wave's pow2 target
        # within the caller's cap; a 3-pod trickle gets an 8-slot program,
        # a dumped backlog still fills max_pods
        active, _, _ = self.queue.pending_pods()
        target = self.wave_controller.next_size(active, cap=max_pods)
        clipped = self.wave_controller.last_clipped
        with self.recorder.phase("pop"):
            while len(wave) < target:
                qpi = self.queue.pop(
                    timeout=timeout if not wave and not trailer else 0.0
                )
                if qpi is None:
                    break
                pod = qpi.pod
                fw = self.framework_for_pod(pod)
                if fw is None:
                    self.queue.done(qpi.key, qpi.inflight_token)
                    continue
                if self._skip_pod_schedule(fw, pod):
                    self.queue.done(qpi.key, qpi.inflight_token)
                    continue
                algo = self.algorithms.get(fw.profile_name)
                # ORDER MATTERS: wave_eligible has side effects for claim
                # pods (binder assume + plan stash), so every other
                # precondition — including the same-profile check — must
                # pass first, or a trailer pod would leak an assumed PV with
                # no revert path
                eligible = (
                    isinstance(algo, TPUSchedulingAlgorithm)
                    and pod.spec.scheduling_group is None
                    and (wave_algo is None or algo is wave_algo)
                    and algo.wave_eligible(pod)
                )
                if not eligible:
                    trailer = qpi
                    break
                wave_algo = algo
                wave.append(qpi)
                self.recorder.pod_ledger.stamp(pod.meta.key, "wave_admission")
                breaker = getattr(algo, "breaker", None)
                if (breaker is not None and len(wave) >= PROBE_WAVE_PODS
                        and breaker.probing()):
                    # HALF_OPEN: probe the recovering device with a small
                    # wave; the rest of the queue waits for the verdict
                    break

        if not wave:
            # nothing to prep a successor from: whatever is in flight sat
            # (and drains now) because the queue ran dry — the stall
            # profiler attributes its open gap to queue_empty
            infl = self._inflight_wave
            if infl is not None or trailer is not None:
                self.recorder.stall_profiler.mark_gap(
                    infl[1].record if infl is not None else None,
                    "queue_empty")
            processed = self._flush_wave_pipeline()
            if trailer is not None:
                self.schedule_pod_info(trailer)
                processed += 1
            return processed

        # partial waves are PADDED with inactive slots to the next pow2
        # bucket (floor 8, cap max_pods): the device sees a bounded set of
        # program shapes — a fresh XLA compile per odd tail size costs
        # seconds, dead scan steps cost microseconds — while small trickle
        # waves still use small programs instead of a full max_pods scan
        pad_to = 8
        while pad_to < len(wave):
            pad_to <<= 1
        processed = self._pipeline_wave(wave_algo, wave, min(pad_to, max_pods))
        if clipped:
            # the controller wanted more slots than the per-call cap
            # allowed (the ticked trace regime's one-wave-per-tick gate):
            # the launched wave will sit in flight while the clipped
            # backlog waits for the next tick — attribute its gap
            infl = self._inflight_wave
            if infl is not None:
                self.recorder.stall_profiler.mark_gap(
                    infl[1].record, "capacity_gate")
        if trailer is not None:
            # the trailer (gang/claim/nominated pod) must run strictly after
            # the wave that preceded it in queue order
            infl = self._inflight_wave
            if infl is not None:
                self.recorder.stall_profiler.mark_gap(
                    infl[1].record, "flush")
            processed += self._flush_wave_pipeline()
            self.schedule_pod_info(trailer)
            processed += 1
        return processed

    def _pipeline_wave(self, algo, wave: list, pad_to: int) -> int:
        """Launch this wave's kernel (non-blocking, chained on the device
        carry), then process the PREVIOUS wave's results while it runs.
        Returns pods fully processed this call (the previous wave's count)."""
        from ..ops import FallbackNeeded
        from .tpu.backend import NeedResync

        processed = self._drain_wave_completions()
        infl = self._inflight_wave
        if infl is not None and (
            infl[0] is not algo or infl[1].pad != pad_to or infl[1].poisoned
        ):
            # incompatible in-flight wave (different profile, different
            # program shape — the tie-word frame sizing assumes equal pads —
            # or a poisoned carry): drain before launching
            self.recorder.stall_profiler.mark_gap(infl[1].record, "flush")
            processed += self._flush_wave_pipeline()

        breaker = getattr(algo, "breaker", None)
        if breaker is not None and not breaker.allow_device_wave():
            # breaker OPEN (or probes exhausted): skip the device launch
            # entirely — drain whatever is in flight (strict queue order)
            # and run the wave per-pod; while the breaker is cooling,
            # schedule_pod's device_blocked() check routes each pod to the
            # host tier
            infl = self._inflight_wave
            self.recorder.stall_profiler.mark_gap(
                infl[1].record if infl is not None else None, "flush")
            processed += self._flush_wave_pipeline()
            with self.recorder.phase("finish"), self.recorder.\
                    fallback_attribution(self.framework_for_pod(wave[0].pod)):
                for qpi in wave:
                    algo.revert_wave_plan(qpi.pod)
                    self.schedule_pod_info(qpi)
            return processed + len(wave)

        with self.recorder.phase("snapshot"):
            self.cache.update_snapshot(self.snapshot)
        pods = [qpi.pod for qpi in wave]
        fl = None
        flake: Exception | None = None
        for attempt in (0, 1):
            try:
                with self.recorder.phase("kernel"):
                    fl = algo.backend.launch_batched(
                        pods, self.snapshot, rng=algo.rng, pad_to=pad_to
                    )
                break
            except NeedResync:
                # drain the pipeline (its phases self-account), re-upload
                # from host truth, retry once
                infl = self._inflight_wave
                self.recorder.stall_profiler.mark_gap(
                    infl[1].record if infl is not None else None, "flush")
                processed += self._flush_wave_pipeline()
                algo.backend.invalidate_carry()
                with self.recorder.phase("snapshot"):
                    self.cache.update_snapshot(self.snapshot)
            except FallbackNeeded as e:
                if getattr(e, "device_flake", False):
                    flake = e
                break
        if fl is None:
            # not kernelizable (stale vocab etc.) or injected launch flake:
            # strict queue order — whatever is in flight precedes these pods
            if breaker is not None:
                if flake is not None:
                    breaker.record_failure(str(flake))
                else:
                    # no device verdict either way (resync exhaustion,
                    # benign fallback): release a half-open probe slot
                    breaker.record_benign()
            infl = self._inflight_wave
            self.recorder.stall_profiler.mark_gap(
                infl[1].record if infl is not None else None, "flush")
            processed += self._flush_wave_pipeline()
            algo.fallback_count += len(wave)
            with self.recorder.phase("finish"), self.recorder.\
                    fallback_attribution(self.framework_for_pod(wave[0].pod)):
                for qpi in wave:
                    algo.revert_wave_plan(qpi.pod)
                    self.schedule_pod_info(qpi)
            return processed + len(wave)
        fl.qpis = wave
        prev, self._inflight_wave = self._inflight_wave, (algo, fl)
        self.recorder.count_wave()
        if prev is not None:
            processed += self._complete_wave(*prev)
        if self.pipeline_depth <= 1:
            # pipelining disabled: complete the wave we just launched before
            # returning — the serial loop, through the identical code path
            processed += self._flush_wave_pipeline()
        return processed

    def _flush_wave_pipeline(self) -> int:
        """Process the in-flight wave (if any); returns pods processed."""
        n = self._drain_wave_completions()
        infl, self._inflight_wave = self._inflight_wave, None
        if infl is None:
            return n
        return n + self._complete_wave(*infl)

    def _complete_wave(self, algo, fl) -> int:
        """Block on a launched wave's results and run the host half of its
        scheduling cycles: assume/reserve/permit per pod, then the wave's
        batched binding (the host half of the pipeline)."""
        from ..ops import FallbackNeeded

        rec = self.recorder
        wave = fl.qpis
        record = fl.record
        # one root span per wave: collect/finish/bind phases nest under it
        # (launch-side phases were children of the launching call's spans)
        with rec.tracer.span(
            f"wave/{record.wave_id if record is not None else 0}",
            pods=len(wave),
        ):
            breaker = getattr(algo, "breaker", None)
            try:
                with rec.phase("kernel"):
                    hosts, planes = algo.backend.collect(fl, rng=algo.rng)
            except FallbackNeeded as e:
                # tie-draw overflow, poisoned carry, or injected device
                # flake: results discarded, pods re-run per-pod against
                # live state; a successor launched on the bad carry is
                # poisoned too. The backend already closed the flight
                # record with the fallback reason.
                if breaker is not None:
                    if getattr(e, "device_flake", False):
                        breaker.record_failure(str(e))
                    else:
                        breaker.record_benign()
                self._poison_successor(algo)
                algo.fallback_count += len(wave)
                with rec.phase("finish"), rec.fallback_attribution(
                        self.framework_for_pod(wave[0].pod)):
                    for qpi in wave:
                        algo.revert_wave_plan(qpi.pod)
                        self.schedule_pod_info(qpi)
                if (breaker is not None and breaker.device_blocked()
                        and getattr(e, "device_flake", False)):
                    # the flake tripped the breaker OPEN: drain the (poisoned)
                    # successor now rather than holding it in flight through
                    # the cooldown — its pods reroute to the host tier in
                    # queue order right behind this wave's
                    infl = self._inflight_wave
                    rec.stall_profiler.mark_gap(
                        infl[1].record if infl is not None else None,
                        "flush")
                    return len(wave) + self._flush_wave_pipeline()
                return len(wave)
            if breaker is not None:
                # the device round-tripped a full wave: that is the
                # breaker's success signal (host-side bind outcomes are a
                # different failure domain)
                breaker.record_success()
            algo.kernel_count += len(wave)
            # crash point: wave collected off the device but none of its
            # per-pod finish cycles have run — a crash here strands the
            # launch-time wave plan with nothing assumed in the cache yet
            faultinject.fire("loop.wave")
            with rec.phase("finish", record):
                exported = self._export_wave_signatures(algo, fl, planes)
                if record is not None:
                    record.cache_exports = exported
                invalidated = False
                batch: list[tuple] = []
                ledger = rec.pod_ledger
                wave_id = record.wave_id if record is not None else None
                for qpi, host in zip(wave, hosts):
                    if host is not None and not invalidated:
                        # kernel picked this pod's node; the wave_id is the
                        # exemplar link to the wave/<id> trace span
                        ledger.stamp(qpi.pod.meta.key, "kernel_verdict",
                                     wave_id=wave_id)
                    if invalidated or host is None:
                        # host=None re-runs reproduce the FitError (no rng
                        # draws, no state change — safe under a live
                        # successor); invalidated pods re-run because the
                        # carry diverged
                        algo.revert_wave_plan(qpi.pod)
                        self.schedule_pod_info(qpi)
                        continue
                    fw = self.framework_for_pod(qpi.pod)
                    state = CycleState()
                    vol_plan = algo.take_wave_plan(qpi.pod.meta.key)
                    if vol_plan is not None:
                        # node-neutral volume decision made at wave
                        # admission: seed the cycle state so Reserve/PreBind
                        # run the normal VolumeBinding flow against the
                        # selected host
                        from .plugins.volumes import (
                            VolumeBinding,
                            _BindingState,
                            _ClaimsToBind,
                        )

                        bs = _BindingState(_ClaimsToBind())
                        bs.per_node[host] = vol_plan
                        state.write(VolumeBinding.STATE_KEY, bs)
                    result = ScheduleResult(
                        suggested_host=host, evaluated_nodes=planes.n,
                        feasible_nodes=1,
                    )
                    result, status = self._finish_scheduling_cycle(
                        state, fw, qpi, result, from_wave=True
                    )
                    if not status.is_success:
                        if vol_plan is not None:
                            algo.safe_revert_volumes(vol_plan)
                        self._handle_scheduling_failure(
                            fw, qpi, status, self.queue.moved_count
                        )
                        # the kernel placed this pod but the host reverted
                        # it: the carry (and any successor computed from it)
                        # is wrong
                        self._poison_successor(algo)
                        invalidated = True
                        continue
                    if (fw.waiting_pod(qpi.pod.meta.key) is not None
                            or not self._default_bind_only(fw)):
                        self._dispatch_binding(state, fw, qpi, result)
                    else:
                        batch.append((state, fw, qpi, result))
            with rec.phase("bind", record):
                self._bind_wave(batch)
        if record is not None:
            rec.end_wave(
                record,
                fallback_reason="host revert: carry poisoned"
                if invalidated else None,
            )
            # feed the adaptive controller's (opt-in) latency guard
            self.wave_controller.observe(record.duration_s)
        return len(wave)

    def _export_wave_signatures(self, algo, fl, planes) -> int:
        """Warm the host BatchCache from the kernel's per-signature score
        rows: each distinct wave signature exports its ordered feasible node
        list, so long-tail pods that later take the host path ride
        GetNodeHint (one re-Filter) instead of a full Filter+Score pass —
        kernel work also feeds OpportunisticBatching's cache. Returns the
        number of signatures exported (the flight record's cache_exports)."""
        batch = getattr(algo, "batch", None)
        sig_scores = fl.info.get("sig_scores")
        if batch is None or sig_scores is None or fl.sig_ids is None:
            return 0
        import numpy as np

        # device->host fetch of the per-signature score rows, through the
        # backend's accounted transfer seam (devicetelemetry "scores" plane)
        rows = algo.backend.telemetry.accounted_fetch("scores", sig_scores)
        seen: set[int] = set()
        exported = 0
        for pod, gid in zip(fl.pods, fl.sig_ids):
            gid = int(gid)
            if gid in seen:
                continue
            seen.add(gid)
            fw = self.framework_for_pod(pod)
            signature = fw.sign_pod(pod)
            if signature is None:
                continue
            row = rows[gid]
            # stable argsort on -score = score-descending, snapshot node
            # order within ties (matching select_host's ordered list);
            # -1 rows (infeasible / plane padding) drop out
            order = np.argsort(-row, kind="stable")
            names = [planes.node_names[i] for i in order if row[i] >= 0]
            if names:
                batch.store_schedule_results(signature, names)
                exported += 1
        return exported

    def _poison_successor(self, algo) -> None:
        """Mark the in-flight wave's results unusable and drop the carry —
        host-side state diverged from what its kernel assumed."""
        algo.backend.invalidate_carry()
        if self._inflight_wave is not None:
            self._inflight_wave[1].mark_poisoned()

    def _default_bind_only(self, fw: Framework) -> bool:
        """True when the profile's bind chain is exactly the DefaultBinder —
        the only binder whose store write the wave transaction replicates."""
        from .plugins.basics import DefaultBinder

        return (len(fw.bind_plugins) == 1
                and isinstance(fw.bind_plugins[0], DefaultBinder))

    def _bind_wave(self, batch: list[tuple]) -> None:
        """The binding cycle for a whole wave: PreBind per pod (host chain —
        no-ops for kernel-eligible pods), then ONE multi-pod bind transaction
        (store.bind_pods; routed through the async dispatcher when
        SchedulerAsyncAPICalls is on so the next wave's scheduling overlaps
        this wave's API writes — the wave-granular form of the reference's
        always-async bindingCycle, schedule_one.go:146, and its dispatcher,
        api_dispatcher.go:32-112), then per-pod completion."""
        if not batch:
            return
        ready: list[tuple] = []
        for state, fw, qpi, result in batch:
            st = fw.wait_on_permit(qpi.pod)  # instant: no waiting pod in batch
            if st.is_success:
                st = fw.run_pre_bind_plugins(state, qpi.pod, result.suggested_host)
            if not st.is_success:
                self._handle_binding_failure(
                    state, fw, qpi, result.suggested_host, st
                )
                continue
            ready.append((state, fw, qpi, result))
        if not ready:
            return
        bindings = [(q.pod.meta.key, r.suggested_host) for _, _, q, r in ready]
        for key, _host in bindings:
            self.recorder.pod_ledger.stamp(key, "bind_dispatch")

        if self.api_cacher is not None:
            # the dispatcher worker ONLY parks the outcome; all queue/cache/
            # pipeline mutation happens on the scheduling thread when it
            # drains _wave_completions (no cross-thread check-then-act on
            # the carry coherence flags)
            self.api_cacher.bind_pods(
                bindings,
                on_done=lambda results, err:
                    self._wave_completions.append((ready, results, err)),
            )
            return
        try:
            results = self.store.bind_pods(bindings)
        except Exception as e:  # noqa: BLE001
            self._apply_wave_bind_results(ready, None, e)
            return
        self._apply_wave_bind_results(ready, results, None)

    def _drain_wave_completions(self) -> int:
        """Apply parked async wave-bind outcomes (scheduling thread only).
        Returns 0 — the pods were counted as processed by their wave."""
        while self._wave_completions:
            ready, results, err = self._wave_completions.popleft()
            self._apply_wave_bind_results(ready, results, err)
        return 0

    def _apply_wave_bind_results(self, ready: list[tuple], results, err) -> None:
        from ..store.store import ConflictError

        # crash point: the store bind already executed (dispatcher worker or
        # sync call), but the cache still carries assumes and queue.done has
        # not run — the prepare/commit gap reconcile's adopt path must cover
        faultinject.fire("loop.bind_commit")

        # one correlation token per wave: a 512-pod wave's Scheduled events
        # collapse to ~spill-threshold individual events + one aggregate,
        # instead of one store object per pod
        self._wave_event_seq += 1
        corr = f"wave/{self._wave_event_seq}"
        for entry, status in zip(ready, results or ["conflict"] * len(ready)):
            state, fw, qpi, result = entry
            if err is not None or status != "bound":
                # "missing" (pod deleted mid-flight) must also take the
                # failure path: the DELETED event for a not-yet-bound pod
                # never touches the cache, so only _handle_binding_failure's
                # forget releases the assumed resources (the requeued entry
                # is dropped at its next pop by _skip_pod_schedule)
                e = err or ConflictError(
                    f"pod {qpi.pod.meta.key} bind rejected ({status})"
                )
                self._handle_binding_failure(
                    state, fw, qpi, result.suggested_host, Status.as_error(e)
                )
                continue
            self._finish_binding(state, fw, qpi, result.suggested_host,
                                 correlation=corr)

    # -- pod-group (gang) cycle ---------------------------------------------------

    def schedule_pod_group(self, qpi: QueuedPodInfo, fw: Framework) -> None:
        """scheduleOnePodGroup (schedule_one_podgroup.go:42): pop every
        unscheduled gang sibling, take ONE snapshot, run the per-pod
        algorithm with in-snapshot assume + revert, then submit — bindings
        for all members on success, per-pod failure handling otherwise."""
        pod = qpi.pod
        gk = self._group_key(pod)
        group = self.store.try_get("PodGroup", gk)
        gstate = self.cache.pod_group_states.get(gk)
        if group is None or gstate is None:
            # PreEnqueue normally parks group-less members; be defensive
            self._handle_scheduling_failure(
                fw, qpi,
                Status.unschedulable(f"PodGroup {gk} not found",
                                     plugin="GangScheduling"),
                self.queue.moved_count,
            )
            return

        # podGroupInfoForPod:119,143 — pop every sibling still queued
        qpis = [qpi]
        for key in sorted(gstate.unscheduled):
            if key == pod.meta.key:
                continue
            sib = self.queue.pop_specific(key)
            if sib is not None:
                qpis.append(sib)
        # priority desc, then queue timestamp asc (:151)
        qpis.sort(key=lambda q: (-q.pod.spec.priority, q.timestamp))

        self.cache.update_snapshot(self.snapshot)
        outcome = self._pod_group_wave_algorithm(fw, gk, qpis)
        if outcome is None:
            outcome = self._pod_group_algorithm(fw, gk, qpis)
        self._submit_pod_group_result(fw, gk, qpis, outcome)

    def _pod_group_wave_algorithm(self, fw: Framework, gk: str, qpis: list):
        """Gang wave (README "Gang waves"): whole-group device placement —
        one batched kernel scans the gang over every topology-domain mask
        and picks the best feasible domain, replacing the per-placement
        dry-run loop of _pod_group_algorithm. Returns an outcome tuple for
        _submit_pod_group_result, or None when the group must ride the
        host path; every None leaves rng/snapshot/cache untouched, so the
        host cycle then runs bit-identically to a no-device build."""
        if not self.gang_waves:
            return None
        algo = self.algorithms.get(fw.profile_name)
        if algo is None or getattr(algo, "backend", None) is None:
            return None
        from .tpu.gangplanner import try_gang_wave

        hosts = try_gang_wave(self, fw, algo, gk, qpis)
        if hosts is None:
            return None
        return self._pod_group_apply_wave(fw, gk, qpis, hosts)

    def _pod_group_apply_wave(self, fw: Framework, gk: str, qpis: list,
                              hosts: list):
        """The apply half of _pod_group_default_algorithm with the device
        wave's precomputed hosts: in-snapshot assume + reserve + permit per
        member, full revert on any failure — outcome statuses are the host
        path's, so _submit_pod_group_result is shared unchanged."""
        placed: list[tuple] = []  # (qpi, state, result, pod_info)
        gsnap = self.snapshot.pod_group_states.get(gk)
        evaluated = self.snapshot.num_nodes()
        for q, host in zip(qpis, hosts):
            state = CycleState()
            state.is_pod_group_scheduling_cycle = True
            result = ScheduleResult(suggested_host=host,
                                    evaluated_nodes=evaluated,
                                    feasible_nodes=1)
            pi = PodInfo(q.pod, self.names)
            self.snapshot.assume_pod(pi, host)  # kubesched-lint: disable=SNAP01
            if gsnap is not None:
                gsnap.unscheduled.discard(q.pod.meta.key)
                gsnap.assumed.add(q.pod.meta.key)
            st = fw.run_reserve_plugins_reserve(state, q.pod, host)
            if st.is_success:
                st = fw.run_permit_plugins(state, q.pod, host)
            if not (st.is_success or st.is_wait):
                placed.append((q, state, result, pi))
                self._revert_pod_group(fw, gk, placed)
                return ("unschedulable" if st.is_rejected else "error", q, st)
            placed.append((q, state, result, pi))
        return ("success", placed, None)

    def _pod_group_algorithm(self, fw: Framework, gk: str, qpis: list):
        """podGroupSchedulingAlgorithm (:573): placement enumeration when
        PlacementGenerate plugins produced >1 candidate (each dry-run in a
        narrowed snapshot, best picked by PlacementScore), else the default
        whole-snapshot algorithm."""
        from .cache.snapshot import Placement

        pods = [q.pod for q in qpis]
        pstate = CycleState()
        placements = None
        narrowed = False
        required = False
        if fw.placement_generate_plugins:
            parent = Placement(
                "all", [ni.name for ni in self.snapshot.list_nodes()]
            )
            placements, _st = fw.run_placement_generate_plugins(
                pstate, pods, parent
            )
            if not _st.is_success and not _st.is_skip:
                # e.g. requiredDomain inconsistency: scheduled members span
                # two domains (topology_placement.go getScheduledPods error)
                return ("error", qpis[0], _st)
            # a SINGLE placement must still constrain (the requiredDomain
            # pin of a partially-scheduled gang is exactly one placement)
            narrowed = placements != [parent]
            for p in fw.placement_generate_plugins:
                mode = getattr(p, "topology_mode", lambda _p: None)(pods)
                required = required or mode == "Required"
        if placements is not None and narrowed:
            # podGroupSchedulingPlacementAlgorithm:520 — dry-run per
            # placement, score the ones that fit, run the real algorithm
            # under the winner
            # SNAP01 suppressions here and in the group-algorithm helpers
            # below: assume/forget on the cycle snapshot is the sanctioned
            # gang-scheduling fork API (schedule_one.go:1113-1118) — the
            # scheduling cycle is single-threaded and every assume is
            # reverted on the finally/revert path.
            best = None
            for pl in placements:
                self.snapshot.assume_placement(pl)  # kubesched-lint: disable=SNAP01
                try:
                    ok = self._pod_group_dry_run(fw, qpis)
                    if ok:
                        score = fw.run_placement_score_plugins(pstate, pods, pl)
                        if best is None or score > best[0]:
                            best = (score, pl)
                finally:
                    self.snapshot.forget_placement()  # kubesched-lint: disable=SNAP01
            if best is not None:
                self.snapshot.assume_placement(best[1])  # kubesched-lint: disable=SNAP01
                try:
                    return self._pod_group_default_algorithm(fw, gk, qpis)
                finally:
                    self.snapshot.forget_placement()  # kubesched-lint: disable=SNAP01
            if required:
                return ("unschedulable", qpis[0], Status.unschedulable(
                    "no topology domain can hold the whole pod group",
                    plugin="TopologyPlacementGenerator",
                ))
            # Preferred topology: fall back to the unconstrained snapshot
        return self._pod_group_default_algorithm(fw, gk, qpis)

    def _pod_group_dry_run(self, fw: Framework, qpis: list) -> bool:
        """Does the whole gang fit the (placement-narrowed) snapshot?
        Schedules each member with in-snapshot assumes, reverts everything,
        restores the tie-break rng (dry runs must not consume the stream)."""
        algo = self.algorithms[fw.profile_name]
        rng_state = algo.rng.getstate()
        placed: list[tuple[str, str]] = []
        ok = True
        for q in qpis:
            state = CycleState()
            state.is_pod_group_scheduling_cycle = True
            try:
                result = algo.schedule_pod(state, q.pod, self.snapshot)
            except (FitError, Exception):  # noqa: BLE001
                ok = False
                break
            pi = PodInfo(q.pod, self.names)
            self.snapshot.assume_pod(pi, result.suggested_host)  # kubesched-lint: disable=SNAP01
            placed.append((q.pod.meta.key, result.suggested_host))
        for key, host in reversed(placed):
            self.snapshot.forget_pod(key, host)  # kubesched-lint: disable=SNAP01
        algo.rng.setstate(rng_state)
        return ok

    def _pod_group_default_algorithm(self, fw: Framework, gk: str, qpis: list):
        """podGroupSchedulingDefaultAlgorithm:275 — sequential per-pod
        algorithm; assumes go into the SNAPSHOT (schedule_one.go:1113-1118),
        reserve + permit run per pod (the gang plugin returns Wait until the
        snapshot group state reaches quorum, then allows every sibling)."""
        algo = self.algorithms[fw.profile_name]
        placed: list[tuple] = []  # (qpi, state, result, pod_info)
        gsnap = self.snapshot.pod_group_states.get(gk)
        for q in qpis:
            state = CycleState()
            state.is_pod_group_scheduling_cycle = True
            try:
                result = algo.schedule_pod(state, q.pod, self.snapshot)
            except FitError as fe:
                self._revert_pod_group(fw, gk, placed)
                return ("unschedulable", q, fe)
            except Exception as e:  # noqa: BLE001
                self._revert_pod_group(fw, gk, placed)
                return ("error", q, Status.as_error(e))
            pi = PodInfo(q.pod, self.names)
            self.snapshot.assume_pod(pi, result.suggested_host)  # kubesched-lint: disable=SNAP01
            if gsnap is not None:
                gsnap.unscheduled.discard(q.pod.meta.key)
                gsnap.assumed.add(q.pod.meta.key)
            st = fw.run_reserve_plugins_reserve(state, q.pod, result.suggested_host)
            if st.is_success:
                st = fw.run_permit_plugins(state, q.pod, result.suggested_host)
            if not (st.is_success or st.is_wait):
                placed.append((q, state, result, pi))
                self._revert_pod_group(fw, gk, placed)
                return ("unschedulable" if st.is_rejected else "error", q, st)
            placed.append((q, state, result, pi))
        return ("success", placed, None)

    def _revert_pod_group(self, fw: Framework, gk: str, placed: list) -> None:
        """The deferred revertFn of the group algorithm (schedule_one.go:
        363-393): unreserve, drop permit waiters, forget in-snapshot assumes,
        restore the snapshot group state."""
        gsnap = self.snapshot.pod_group_states.get(gk)
        for q, state, result, pi in reversed(placed):
            fw.run_reserve_plugins_unreserve(state, q.pod, result.suggested_host)
            fw.remove_waiting_pod(q.pod.meta.key)
            self.snapshot.forget_pod(pi.key, result.suggested_host)  # kubesched-lint: disable=SNAP01
            if gsnap is not None:
                gsnap.assumed.discard(q.pod.meta.key)
                gsnap.unscheduled.add(q.pod.meta.key)

    def _submit_pod_group_result(self, fw: Framework, gk: str, qpis: list,
                                 outcome) -> None:
        """submitPodGroupAlgorithmResult:410 — success starts every member's
        binding cycle; failure routes every member through the failure
        handler (the failing pod with its own diagnosis)."""
        kind = outcome[0]
        if kind == "success":
            # gang placements mutate node state outside the wave pipeline
            self.mark_wave_external()
            dispatchable: list[tuple] = []
            for q, state, result, _pi in outcome[1]:
                try:
                    self.cache.assume_pod(q.pod, result.suggested_host)
                except Exception as e:  # noqa: BLE001
                    self._handle_scheduling_failure(
                        fw, q, Status.as_error(e), self.queue.moved_count
                    )
                    continue
                self.cache.pod_group_states.pod_assumed(gk, q.pod.meta.key)
                dispatchable.append((q, state, result))
            # crash point: every member is assumed (cache + gang quorum
            # state) but no binding has been dispatched — the stale-permit
            # window reconcile's permit_cleared sweep must cover
            faultinject.fire("gang.permit")
            for q, state, result in dispatchable:
                self._dispatch_binding(state, fw, q, result)
            return
        failing, err = outcome[1], outcome[2]
        if isinstance(err, FitError):
            for p in err.diagnosis.unschedulable_plugins:
                failing.unschedulable_plugins.add(p)
            fail_status = Status.unschedulable(str(err), plugin="")
        else:
            fail_status = err
        sibling_status = Status.unschedulable(
            f"pod group {gk}: member {failing.pod.meta.key} did not fit",
            plugin="GangScheduling",
        )
        for q in qpis:
            self._handle_scheduling_failure(
                fw, q, fail_status if q is failing else sibling_status,
                self.queue.moved_count,
            )

    # -- scheduling cycle ---------------------------------------------------------

    def _scheduling_cycle(
        self, state: CycleState, fw: Framework, qpi: QueuedPodInfo
    ) -> tuple[ScheduleResult | None, Status]:
        pod = qpi.pod
        self.cache.update_snapshot(self.snapshot)
        algo = self.algorithms[fw.profile_name]
        try:
            result = algo.schedule_pod(state, pod, self.snapshot)
        except FitError as fit_err:
            # PostFilter (preemption) — schedule_one.go:293
            for p in fit_err.diagnosis.unschedulable_plugins:
                qpi.unschedulable_plugins.add(p)
            for p in fit_err.diagnosis.pending_plugins:
                qpi.pending_plugins.add(p)
            if fw.post_filter_plugins:
                pf_result, pf_status = fw.run_post_filter_plugins(
                    state, pod, fit_err.diagnosis.node_to_status
                )
                if pf_status.is_success and pf_result and pf_result.nominated_node_name:
                    # nominate; pod returns to queue and retries (victims terminating)
                    self.queue.add_nominated_pod(
                        pod, pf_result.nominated_node_name, PodInfo(pod, self.names)
                    )
                    self._patch_nominated_node(pod, pf_result.nominated_node_name)
            return None, Status.unschedulable(str(fit_err), plugin="")
        except Exception as e:  # noqa: BLE001
            return None, Status.as_error(e)

        return self._finish_scheduling_cycle(state, fw, qpi, result)

    def _finish_scheduling_cycle(
        self, state: CycleState, fw: Framework, qpi: QueuedPodInfo,
        result: ScheduleResult, from_wave: bool = False,
    ) -> tuple[ScheduleResult | None, Status]:
        """assume + reserve + permit (the post-algorithm half of the
        scheduling cycle, schedule_one.go:320-393) — shared by the per-pod
        path and the batched wave path."""
        pod = qpi.pod
        # assume (schedule_one.go:320,1106): cache sees the pod on the node now
        assumed = pod
        try:
            self.cache.assume_pod(assumed, result.suggested_host)
        except Exception as e:  # noqa: BLE001
            return None, Status.as_error(e)
        if not from_wave:
            # a host-path placement changes node state the wave pipeline's
            # device carry didn't see
            self.mark_wave_external()
        gk = self._group_key(pod)
        if gk is not None:
            self.cache.pod_group_states.pod_assumed(gk, pod.meta.key)

        # reserve
        st = fw.run_reserve_plugins_reserve(state, assumed, result.suggested_host)
        if not st.is_success:
            fw.run_reserve_plugins_unreserve(state, assumed, result.suggested_host)
            self._forget(assumed)
            return None, st

        # permit
        st = fw.run_permit_plugins(state, assumed, result.suggested_host)
        if not (st.is_success or st.is_wait):
            fw.run_reserve_plugins_unreserve(state, assumed, result.suggested_host)
            self._forget(assumed)
            return None, st
        return result, Status()

    def _group_key(self, pod: Pod) -> str | None:
        sg = pod.spec.scheduling_group
        return f"{pod.meta.namespace}/{sg.pod_group_name}" if sg else None

    def _forget(self, pod: Pod) -> None:
        self.cache.forget_pod(pod)
        # forgetting frees node resources outside the wave writeback
        self.mark_wave_external()
        gk = self._group_key(pod)
        if gk is not None:
            self.cache.pod_group_states.pod_unassumed(gk, pod.meta.key)

    def mark_wave_external(self, poison: bool = True) -> None:
        """Something outside the wave pipeline's own writeback changed
        cluster state: the device carry is stale (next launch resyncs).

        poison=True (host-path assume/forget on the scheduling thread): the
        in-flight wave's results are discarded too — its kernel computed
        placements without this mutation, and sequential order puts the
        mutation FIRST. poison=False (informer events): the in-flight wave's
        pods were popped before the event, so using its results matches the
        reference's snapshot-at-cycle-start semantics (schedule_one.go:182)."""
        marked = False
        for algo in self.algorithms.values():
            backend = getattr(algo, "backend", None)
            if backend is not None and backend._carry is not None:
                backend.mark_external()
                marked = True
        if poison and marked and self._inflight_wave is not None:
            self._inflight_wave[1].mark_poisoned()

    # -- binding cycle --------------------------------------------------------------

    def _binding_cycle(
        self, state: CycleState, fw: Framework, qpi: QueuedPodInfo, result: ScheduleResult
    ) -> None:
        pod = qpi.pod
        host = result.suggested_host

        # gang Permit wait is the dominant binding-cycle stall for gang
        # members — surface it as its own ledger segment (OBS02: segment
        # names come from podlatency.SEGMENTS, no new series needed)
        gang_waiting = fw.waiting_pod(pod.meta.key) is not None
        if gang_waiting:
            self.recorder.pod_ledger.stamp(pod.meta.key, "gang_wait_start")
        st = fw.wait_on_permit(pod)
        if gang_waiting:
            self.recorder.pod_ledger.stamp(pod.meta.key, "gang_wait_end")
        if not st.is_success:
            self._handle_binding_failure(state, fw, qpi, host, st)
            return

        st = fw.run_pre_bind_plugins(state, pod, host)
        if not st.is_success:
            self._handle_binding_failure(state, fw, qpi, host, st)
            return

        self.recorder.pod_ledger.stamp(pod.meta.key, "bind_dispatch")
        st = self._bind(state, fw, pod, host)
        if not st.is_success and not st.is_skip:
            self._handle_binding_failure(state, fw, qpi, host, st)
            return

        self._finish_binding(state, fw, qpi, host)

    def _finish_binding(self, state, fw: Framework, qpi: QueuedPodInfo, host: str,
                        correlation: str | None = None) -> None:
        """Post-bind tail shared by the per-pod cycle and the wave batch."""
        pod = qpi.pod
        # ledger: the bind is durable — close the entry (status_ack, if a
        # kubelet reports the pod Running, lands on the retained entry later)
        self.recorder.pod_ledger.stamp(pod.meta.key, "bind_commit")
        self.recorder.pod_ledger.complete(pod.meta.key)
        fw.run_post_bind_plugins(state, pod, host)
        # pod leaves the cycle for good: stop in-flight event tracking only now
        # (a done() before bind would drop events needed on bind failure)
        self.queue.done(qpi.key, qpi.inflight_token)
        self.queue.delete_nominated_pod_if_exists(pod)
        if self.metrics is not None:
            self.metrics.pod_scheduled(qpi)
        if self.event_recorder is not None:
            self.event_recorder.event(pod, "Normal", "Scheduled",
                                      f"bound to {host}",
                                      correlation=correlation)
        _log.v2("Successfully bound pod to node", pod=qpi.key, node=host,
                evaluatedNodes=getattr(qpi, "evaluated_nodes", None))
        gk = self._group_key(pod)
        if gk is not None:
            self.cache.pod_group_states.pod_scheduled(gk, pod.meta.key)

    def _bind(self, state, fw: Framework, pod: Pod, host: str) -> Status:
        """bind:1136 — an interested binder extender takes precedence over
        the bind plugins (extendersBinding, schedule_one.go:1160); with
        SchedulerAsyncAPICalls the store write goes through the dispatcher
        (DefaultBinder via APICacher.BindPod)."""
        algo = self.algorithms.get(fw.profile_name)
        for ext in getattr(algo, "extenders", []) or []:
            if ext.is_binder() and ext.is_interested(pod):
                # the webhook owns the binding API write (extender.go Bind:362
                # delegates to the extender process). Until the external
                # writer's update lands in the store, the pod stays assumed in
                # cache; if the webhook never writes, the assume expires and
                # the pod is retried — same crash-consistency as the reference
                return ext.bind(pod, host)
        if self.api_cacher is not None:
            from .api_dispatcher import CallSkippedError

            try:
                call = self.api_cacher.bind_pod(pod, host)
            except CallSkippedError as e:
                return Status.as_error(e)
            # binding cycle already runs off the scheduling loop; waiting here
            # preserves failure handling without blocking scheduling. The
            # budget (KUBE_TPU_BIND_WAIT_S) is burned in short slices so a
            # stalled dispatcher is logged while it stalls, not 30s later
            deadline = _time.monotonic() + BIND_WAIT_S
            # the dispatcher in-flight wait is pipeline backpressure: the
            # loop can't prep a successor while it sits here, so the time
            # lands on the stall profiler's cumulative bind_backpressure
            with self.recorder.stall_profiler.stall(None,
                                                    "bind_backpressure"):
                while not call.done.wait(
                    timeout=min(_BIND_WAIT_SLICE_S,
                                max(0.0, deadline - _time.monotonic()))
                ):
                    remaining = deadline - _time.monotonic()
                    if remaining <= 0:
                        return Status.as_error(TimeoutError(
                            f"async bind of {pod.meta.key} timed out after "
                            f"{BIND_WAIT_S}s (KUBE_TPU_BIND_WAIT_S)"
                        ))
                    _log.error("async bind still pending; waiting",
                               pod=pod.meta.key, node=host,
                               remaining_s=round(remaining, 1))
            if call.error is not None:
                return Status.as_error(call.error)
            return Status()
        return fw.run_bind_plugins(state, pod, host)

    def _handle_binding_failure(self, state, fw, qpi, host, status: Status) -> None:
        """handleBindingCycleError (schedule_one.go:504) — unreserve, forget,
        requeue via AssignedPodDelete movement."""
        pod = qpi.pod
        fw.run_reserve_plugins_unreserve(state, pod, host)
        self._forget(pod)
        from .framework import events as ev
        from .framework.events import ClusterEvent

        self.queue.move_all_to_active_or_backoff(
            ClusterEvent(ev.ASSIGNED_POD, ev.DELETE, "BindFailure"), None, None
        )
        self._handle_scheduling_failure(fw, qpi, status, self.queue.moved_count)

    def _handle_scheduling_failure(
        self, fw: Framework, qpi: QueuedPodInfo, status: Status, cycle: int
    ) -> None:
        """handleSchedulingFailure:1188 — requeue + PodScheduled condition.
        Backoff counters are maintained by the queue itself on re-add
        (scheduling_queue.go:924-932)."""
        pod = qpi.pod
        if status.plugin:
            qpi.unschedulable_plugins.add(status.plugin)
        self.queue.add_unschedulable_if_not_present(qpi, cycle)
        self._patch_condition(pod, status)
        if self.event_recorder is not None:
            self.event_recorder.event(
                pod, "Warning", "FailedScheduling", status.message()
            )
        _log.v2("Unable to schedule pod; waiting", pod=qpi.key,
                reason=status.message())
        if self.metrics is not None:
            self.metrics.pod_unschedulable(qpi)

    # -- API writeback ----------------------------------------------------------------

    def _patch_condition(self, pod: Pod, status: Status) -> None:
        from ..api.types import PodCondition

        cur = self.store.try_get("Pod", pod.meta.key)
        if cur is None:
            return
        reason = "Unschedulable" if status.is_rejected else "SchedulerError"
        msg = status.message()
        for c in cur.status.conditions:
            if c.type == "PodScheduled":
                if c.reason == reason and c.message == msg:
                    return
                break
        condition = PodCondition("PodScheduled", "False", reason, msg)
        if self.api_cacher is not None:
            # SchedulerAsyncAPICalls: status writes ride the dispatcher so
            # failure handling never blocks the loop (api_cache.go:29-61);
            # the queued patch dedups/merges per pod key and is dropped if
            # the pod binds first (relevance ordering, api_calls.go:33)
            from .api_dispatcher import CallSkippedError

            try:
                self.api_cacher.patch_pod_status(pod, condition=condition)
            except CallSkippedError:
                pass
            return
        for c in cur.status.conditions:
            if c.type == "PodScheduled":
                c.status, c.reason, c.message = "False", reason, msg
                break
        else:
            cur.status.conditions.append(condition)
        try:
            self.store.update(cur, check_version=False)
        except Exception:  # noqa: BLE001
            pass

    def _patch_nominated_node(self, pod: Pod, node_name: str) -> None:
        if self.api_cacher is not None:
            from .api_dispatcher import CallSkippedError

            try:
                self.api_cacher.patch_pod_status(pod, nominated_node=node_name)
            except CallSkippedError:
                pass
            return
        cur = self.store.try_get("Pod", pod.meta.key)
        if cur is None:
            return
        cur.status.nominated_node_name = node_name
        try:
            self.store.update(cur, check_version=False)
        except Exception:  # noqa: BLE001
            pass

    def wait_for_bindings(self) -> None:
        # a launched-but-uncollected wave holds popped pods — never leave it
        # behind (its pods would be lost to the queue's accounting)
        infl = self._inflight_wave
        if infl is not None:
            self.recorder.stall_profiler.mark_gap(infl[1].record, "flush")
        self._flush_wave_pipeline()
        for t in self._binding_threads:
            t.join(timeout=5)
        self._binding_threads.clear()
        self._drain_wave_completions()
