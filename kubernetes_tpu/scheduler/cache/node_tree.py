"""Zone-interleaved node ordering for spreading fairness.

Reference: pkg/scheduler/backend/cache/node_tree.go:32-143 — nodes are grouped
by zone and the flat list round-robins across zones so adaptive sampling
(percentageOfNodesToScore) still touches every zone.
"""

from __future__ import annotations

from ...api.types import Node

ZONE_LABEL = "topology.kubernetes.io/zone"
REGION_LABEL = "topology.kubernetes.io/region"


def _zone_of(node: Node) -> str:
    region = node.meta.labels.get(REGION_LABEL, "")
    zone = node.meta.labels.get(ZONE_LABEL, "")
    return f"{region}:\x00:{zone}" if (region or zone) else ""


class NodeTree:
    def __init__(self) -> None:
        self._tree: dict[str, list[str]] = {}
        self._zones: list[str] = []
        self.num_nodes = 0

    def add_node(self, node: Node) -> None:
        zone = _zone_of(node)
        names = self._tree.get(zone)
        if names is None:
            names = []
            self._tree[zone] = names
            self._zones.append(zone)
        if node.meta.name not in names:
            names.append(node.meta.name)
            self.num_nodes += 1

    def remove_node(self, node: Node) -> None:
        zone = _zone_of(node)
        names = self._tree.get(zone)
        if names and node.meta.name in names:
            names.remove(node.meta.name)
            self.num_nodes -= 1
            if not names:
                del self._tree[zone]
                self._zones.remove(zone)

    def update_node(self, old: Node, new: Node) -> None:
        if _zone_of(old) != _zone_of(new):
            self.remove_node(old)
        self.add_node(new)

    def list(self) -> list[str]:
        """Round-robin interleave across zones (node_tree.go list())."""
        out: list[str] = []
        idx = [0] * len(self._zones)
        remaining = self.num_nodes
        while remaining > 0:
            progressed = False
            for zi, zone in enumerate(self._zones):
                names = self._tree[zone]
                if idx[zi] < len(names):
                    out.append(names[idx[zi]])
                    idx[zi] += 1
                    remaining -= 1
                    progressed = True
            if not progressed:
                break
        return out
