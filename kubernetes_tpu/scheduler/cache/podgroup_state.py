"""Per-gang pod accounting, snapshotted into scheduling cycles.

Reference: pkg/scheduler/backend/cache/podgroupstate.go:66,217 — each PodGroup
tracks unscheduled/assumed/scheduled member sets with generations; the gang
plugin reads the snapshot copy inside gang cycles and the live copy otherwise.
"""

from __future__ import annotations

import threading

from ...api.types import PodGroup


class PodGroupState:
    __slots__ = ("group", "unscheduled", "assumed", "scheduled")

    def __init__(self, group: PodGroup | None = None):
        self.group = group
        self.unscheduled: set[str] = set()
        self.assumed: set[str] = set()
        self.scheduled: set[str] = set()

    @property
    def all_pods_count(self) -> int:
        return len(self.unscheduled) + len(self.assumed) + len(self.scheduled)

    @property
    def scheduled_pods_count(self) -> int:
        return len(self.scheduled)

    @property
    def assumed_or_scheduled_count(self) -> int:
        return len(self.assumed) + len(self.scheduled)

    def clone(self) -> "PodGroupState":
        s = PodGroupState(self.group)
        s.unscheduled = set(self.unscheduled)
        s.assumed = set(self.assumed)
        s.scheduled = set(self.scheduled)
        return s


class PodGroupStates:
    def __init__(self) -> None:
        self._mu = threading.RLock()
        self._groups: dict[str, PodGroupState] = {}  # "namespace/name" -> state

    def set_group(self, group: PodGroup) -> None:
        with self._mu:
            st = self._groups.setdefault(group.meta.key, PodGroupState())
            st.group = group

    def remove_group(self, key: str) -> None:
        with self._mu:
            self._groups.pop(key, None)

    def get(self, key: str) -> PodGroupState | None:
        with self._mu:
            return self._groups.get(key)

    def pod_added(self, group_key: str, pod_key: str) -> None:
        with self._mu:
            st = self._groups.setdefault(group_key, PodGroupState())
            if pod_key not in st.scheduled and pod_key not in st.assumed:
                st.unscheduled.add(pod_key)

    def pod_assumed(self, group_key: str, pod_key: str) -> None:
        with self._mu:
            st = self._groups.setdefault(group_key, PodGroupState())
            st.unscheduled.discard(pod_key)
            st.assumed.add(pod_key)

    def pod_scheduled(self, group_key: str, pod_key: str) -> None:
        with self._mu:
            st = self._groups.setdefault(group_key, PodGroupState())
            st.unscheduled.discard(pod_key)
            st.assumed.discard(pod_key)
            st.scheduled.add(pod_key)

    def pod_unassumed(self, group_key: str, pod_key: str) -> None:
        with self._mu:
            st = self._groups.get(group_key)
            if st is not None:
                st.assumed.discard(pod_key)
                st.unscheduled.add(pod_key)

    def pod_removed(self, group_key: str, pod_key: str) -> None:
        with self._mu:
            st = self._groups.get(group_key)
            if st is not None:
                st.unscheduled.discard(pod_key)
                st.assumed.discard(pod_key)
                st.scheduled.discard(pod_key)

    def snapshot(self) -> dict[str, PodGroupState]:
        with self._mu:
            return {k: v.clone() for k, v in self._groups.items()}
