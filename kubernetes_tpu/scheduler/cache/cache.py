r"""Live cluster cache with assumed pods and O(changed) snapshot updates.

Reference: pkg/scheduler/backend/cache/cache.go. Pod state machine
(interface.go:34-55):

    Initial --Assume--> Assumed --Add(confirm)--> Added
       |                   |  \--Forget--> (deleted)
       \--Add--> Added --Remove/expire--> (deleted)

Assumed pods occupy node resources between the scheduling decision and the
bind confirmation arriving via the informer. Nodes live in a doubly-linked
list ordered by Generation (most recent at head) so UpdateSnapshot walks only
nodes with Generation > snapshot.generation (cache.go:223-265).
"""

from __future__ import annotations

import threading
from typing import Iterable

from ...api.resource import ResourceNames
from ...api.types import Node, Pod
from ..nodeinfo import NodeInfo, PodInfo, next_generation
from .node_tree import NodeTree
from .snapshot import Snapshot
from .podgroup_state import PodGroupStates


class _NodeItem:
    __slots__ = ("info", "next", "prev")

    def __init__(self, info: NodeInfo):
        self.info = info
        self.next: "_NodeItem | None" = None
        self.prev: "_NodeItem | None" = None


class Cache:
    def __init__(self, names: ResourceNames | None = None):
        self.names = names or ResourceNames()
        self._mu = threading.RLock()
        self._nodes: dict[str, _NodeItem] = {}
        self._head: _NodeItem | None = None
        self._node_tree = NodeTree()
        # pod bookkeeping
        self._assumed_pods: set[str] = set()
        self._pod_states: dict[str, PodInfo] = {}  # pods known to the cache
        self._pod_nodes: dict[str, str] = {}  # pod key -> node name
        self.pod_group_states = PodGroupStates()

    # -- generation list maintenance ---------------------------------------

    def _move_to_head(self, item: _NodeItem) -> None:
        if self._head is item:
            return
        if item.prev is not None:
            item.prev.next = item.next
        if item.next is not None:
            item.next.prev = item.prev
        item.prev = None
        item.next = self._head
        if self._head is not None:
            self._head.prev = item
        self._head = item

    def _unlink(self, item: _NodeItem) -> None:
        if item.prev is not None:
            item.prev.next = item.next
        else:
            self._head = item.next
        if item.next is not None:
            item.next.prev = item.prev
        item.prev = item.next = None

    def _touch(self, name: str) -> _NodeItem:
        item = self._nodes.get(name)
        if item is None:
            item = _NodeItem(NodeInfo(self.names))
            self._nodes[name] = item
        self._move_to_head(item)
        return item

    # -- nodes -------------------------------------------------------------

    def add_node(self, node: Node) -> None:
        with self._mu:
            item = self._touch(node.meta.name)
            if item.info.node is not None:
                self._node_tree.update_node(item.info.node, node)
            else:
                self._node_tree.add_node(node)
            item.info.set_node(node)

    def update_node(self, old: Node, new: Node) -> None:
        self.add_node(new)

    def remove_node(self, node: Node) -> None:
        with self._mu:
            item = self._nodes.get(node.meta.name)
            if item is None:
                return
            self._node_tree.remove_node(node)
            # Keep the item if pods still reference it (reference keeps a
            # node-less NodeInfo until pods drain); bump generation so the
            # snapshot notices removal.
            item.info.node = None
            item.info.generation = next_generation()
            if not item.info.pods:
                self._unlink(item)
                del self._nodes[node.meta.name]

    def node_count(self) -> int:
        with self._mu:
            return sum(1 for it in self._nodes.values() if it.info.node is not None)

    def get_node_info(self, name: str) -> NodeInfo | None:
        with self._mu:
            item = self._nodes.get(name)
            return item.info if item else None

    # -- pods --------------------------------------------------------------

    def assume_pod(self, pod: Pod, node_name: str) -> None:
        """Tentatively place pod on node before the bind API call lands."""
        with self._mu:
            key = pod.meta.key
            if key in self._pod_states:
                raise ValueError(f"pod {key} already in cache")
            pi = PodInfo(pod, self.names)
            item = self._touch(node_name)
            item.info.add_pod(pi)
            item.info.generation = next_generation()
            self._pod_states[key] = pi
            self._pod_nodes[key] = node_name
            self._assumed_pods.add(key)

    def forget_pod(self, pod: Pod) -> None:
        """Revert an assume that failed to bind."""
        with self._mu:
            key = pod.meta.key
            if key not in self._assumed_pods:
                return
            self._remove_pod_locked(key)

    def is_assumed_key(self, key: str) -> bool:
        with self._mu:
            return key in self._assumed_pods

    def is_assumed_pod(self, pod: Pod) -> bool:
        with self._mu:
            return pod.meta.key in self._assumed_pods

    def add_pod(self, pod: Pod) -> None:
        """Informer confirms a scheduled pod (Added state)."""
        with self._mu:
            key = pod.meta.key
            if key in self._assumed_pods:
                # confirmation of our own assume
                if self._pod_nodes.get(key) == pod.spec.node_name:
                    self._assumed_pods.discard(key)
                    # refresh stored pod object (rv, status)
                    self._pod_states[key].pod = pod
                    return
                # scheduled elsewhere than assumed: redo
                self._remove_pod_locked(key)
            elif key in self._pod_states:
                self._remove_pod_locked(key)
            pi = PodInfo(pod, self.names)
            item = self._touch(pod.spec.node_name)
            item.info.add_pod(pi)
            item.info.generation = next_generation()
            self._pod_states[key] = pi
            self._pod_nodes[key] = pod.spec.node_name

    def update_pod(self, old: Pod, new: Pod) -> None:
        with self._mu:
            key = new.meta.key
            if key in self._pod_states and not (key in self._assumed_pods):
                self._remove_pod_locked(key)
            if key not in self._pod_states:
                pi = PodInfo(new, self.names)
                item = self._touch(new.spec.node_name)
                item.info.add_pod(pi)
                item.info.generation = next_generation()
                self._pod_states[key] = pi
                self._pod_nodes[key] = new.spec.node_name

    def remove_pod(self, pod: Pod) -> None:
        with self._mu:
            key = pod.meta.key
            if key in self._pod_states:
                self._remove_pod_locked(key)

    def _remove_pod_locked(self, key: str) -> None:
        node_name = self._pod_nodes.pop(key)
        self._pod_states.pop(key)
        self._assumed_pods.discard(key)
        item = self._nodes.get(node_name)
        if item is not None:
            item.info.remove_pod(key)
            item.info.generation = next_generation()
            self._move_to_head(item)
            if item.info.node is None and not item.info.pods:
                self._unlink(item)
                del self._nodes[node_name]

    def pod_count(self) -> int:
        with self._mu:
            return len(self._pod_states)

    def assumed_pod_count(self) -> int:
        with self._mu:
            return len(self._assumed_pods)

    def assumed_pods(self) -> list[Pod]:
        """The pod objects currently assumed-but-unconfirmed — the set a
        startup reconciliation must resolve against store truth (each one
        is a bind that may have half-applied before a crash)."""
        with self._mu:
            return [self._pod_states[k].pod for k in self._assumed_pods]

    # -- snapshot ----------------------------------------------------------

    def update_snapshot(self, snapshot: Snapshot) -> Snapshot:
        """Incremental refresh: O(nodes changed since snapshot.generation).

        Reference: cache.go UpdateSnapshot:190 — walk the generation list from
        head until Generation <= snapshot.generation; rebuild the ordered list
        only when membership or affinity flags changed.
        """
        with self._mu:
            latest = self._head.info.generation if self._head else snapshot.generation
            changed_membership = False
            derived_dirty = False
            touched: list[str] = []
            item = self._head
            while item is not None and item.info.generation > snapshot.generation:
                info = item.info
                name = info.name or self._name_of(item)
                existing = snapshot.node_info_map.get(name)
                if info.node is None:
                    if existing is not None:
                        del snapshot.node_info_map[name]
                        changed_membership = True
                else:
                    if existing is None:
                        changed_membership = True
                    elif (bool(existing.pods_with_affinity)
                          != bool(info.pods_with_affinity)
                          or bool(existing.pods_with_required_anti_affinity)
                          != bool(info.pods_with_required_anti_affinity)):
                        # affinity flags flipped: derived lists must rebuild
                        # (cache.go:202-276 — ONLY then)
                        derived_dirty = True
                    elif existing.pods_with_affinity or \
                            existing.pods_with_required_anti_affinity:
                        derived_dirty = True  # stale ref sits in the lists
                    snapshot.node_info_map[name] = info.clone()
                    snapshot.note_change(name)
                    touched.append(name)
                item = item.next

            # remove snapshot nodes no longer in cache
            if len(snapshot.node_info_map) > self.node_count():
                live = {
                    it.info.name for it in self._nodes.values() if it.info.node is not None
                }
                for name in list(snapshot.node_info_map):
                    if name not in live:
                        del snapshot.node_info_map[name]
                        changed_membership = True

            if changed_membership:
                order = self._node_tree.list()
                snapshot.node_info_list = [
                    snapshot.node_info_map[n] for n in order if n in snapshot.node_info_map
                ]
                snapshot.note_membership()
                snapshot.refresh_list_index()
                snapshot.rebuild_derived_lists()
            elif touched:
                # patch replaced clones at their known positions instead of
                # rebuilding the full O(N) ordered list per update — the
                # per-pod hybrid path updates 1-2 nodes per cycle
                idx = snapshot.list_index()
                for name in touched:
                    i = idx.get(name)
                    if i is not None:
                        snapshot.node_info_list[i] = snapshot.node_info_map[name]
                if derived_dirty:
                    snapshot.rebuild_derived_lists()
            snapshot.pod_group_states = self.pod_group_states.snapshot()
            snapshot.generation = latest
            return snapshot

    def _name_of(self, item: _NodeItem) -> str:
        for name, it in self._nodes.items():
            if it is item:
                return name
        return ""

    # -- introspection ------------------------------------------------------

    def node_names(self) -> list[str]:
        with self._mu:
            return self._node_tree.list()

    def iter_node_infos(self) -> Iterable[NodeInfo]:
        with self._mu:
            return [it.info for it in self._nodes.values() if it.info.node is not None]
