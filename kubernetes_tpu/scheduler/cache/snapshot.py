"""Immutable-per-cycle cluster snapshot, with in-snapshot gang simulation.

Reference: pkg/scheduler/backend/cache/snapshot.go:43 — nodeInfoMap/List plus
derived lists; fork extensions AssumePod/ForgetPod (:278-361) used by the
pod-group cycle so a gang's earlier pods occupy resources for later siblings
without touching the live cache, and Assume/ForgetPlacement (:363-424) which
narrow the visible node list to a placement's nodes.
"""

from __future__ import annotations

import itertools

from ..nodeinfo import NodeInfo, PodInfo

_snapshot_uids = itertools.count(1)


class Placement:
    """A named subset of nodes a gang may be packed into.

    Reference: snapshot placements + topologyaware/topology_placement.go.
    """

    __slots__ = ("name", "node_names")

    def __init__(self, name: str, node_names: list[str]):
        self.name = name
        self.node_names = node_names


class Snapshot:
    def __init__(self) -> None:
        self.node_info_map: dict[str, NodeInfo] = {}
        self.node_info_list: list[NodeInfo] = []
        self.have_pods_with_affinity_list: list[NodeInfo] = []
        self.have_pods_with_required_anti_affinity_list: list[NodeInfo] = []
        self.used_pvc_set: set[str] = set()
        self.generation = 0
        # gang simulation bookkeeping
        self._assumed: list[tuple[str, str]] = []  # (pod_key, node_name)
        self._placement_stack: list[list[NodeInfo]] = []
        self.pod_group_states: dict[str, "object"] = {}
        # change feed for O(changed) consumers (the planes builder): every
        # node mutation appends its name; membership/order changes bump
        # membership_version (consumers must re-list). changelog_base is
        # the version of changelog[0] — entries older than base were
        # compacted away and force a full scan.
        self.version = 0
        self.membership_version = 0
        self.changelog: list[str] = []
        self.changelog_base = 0
        self.uid = next(_snapshot_uids)  # identity across consumer caches
        self._list_index: dict[str, int] = {}
        self._list_index_version = -1

    def list_index(self) -> dict[str, int]:
        """name -> node_info_list position, rebuilt lazily whenever
        membership (and thus order) changed."""
        if self._list_index_version != self.membership_version:
            self.refresh_list_index()
        return self._list_index

    def refresh_list_index(self) -> None:
        self._list_index = {
            ni.name: i for i, ni in enumerate(self.node_info_list)
        }
        self._list_index_version = self.membership_version

    def note_change(self, node_name: str) -> None:
        self.version += 1
        self.changelog.append(node_name)
        if len(self.changelog) > 8192:
            drop = len(self.changelog) // 2
            del self.changelog[:drop]
            self.changelog_base += drop

    def note_membership(self) -> None:
        self.membership_version += 1

    # -- reads (SharedLister / NodeInfoLister) -----------------------------

    def get(self, node_name: str) -> NodeInfo | None:
        return self.node_info_map.get(node_name)

    def list_nodes(self) -> list[NodeInfo]:
        return self.node_info_list

    def num_nodes(self) -> int:
        return len(self.node_info_list)

    def rebuild_derived_lists(self) -> None:
        self.have_pods_with_affinity_list = [
            n for n in self.node_info_list if n.pods_with_affinity
        ]
        self.have_pods_with_required_anti_affinity_list = [
            n for n in self.node_info_list if n.pods_with_required_anti_affinity
        ]

    # -- in-snapshot assume/forget (gang cycles) ---------------------------

    def assume_pod(self, pi: PodInfo, node_name: str) -> None:
        """Occupy resources on a snapshot node (snapshot.go:278)."""
        ni = self.node_info_map.get(node_name)
        if ni is None:
            raise KeyError(f"node {node_name} not in snapshot")
        ni.add_pod(pi)
        self.note_change(node_name)
        self._assumed.append((pi.key, node_name))
        if pi.has_affinity_constraints and ni not in self.have_pods_with_affinity_list:
            self.have_pods_with_affinity_list.append(ni)
        if pi.has_required_anti_affinity and ni not in self.have_pods_with_required_anti_affinity_list:
            self.have_pods_with_required_anti_affinity_list.append(ni)

    def forget_pod(self, pod_key: str, node_name: str) -> None:
        """Revert an in-snapshot assume (snapshot.go:318)."""
        ni = self.node_info_map.get(node_name)
        if ni is None:
            return
        ni.remove_pod(pod_key)
        self.note_change(node_name)
        try:
            self._assumed.remove((pod_key, node_name))
        except ValueError:
            pass
        if not ni.pods_with_affinity and ni in self.have_pods_with_affinity_list:
            self.have_pods_with_affinity_list.remove(ni)
        if (
            not ni.pods_with_required_anti_affinity
            and ni in self.have_pods_with_required_anti_affinity_list
        ):
            self.have_pods_with_required_anti_affinity_list.remove(ni)

    # -- placements (topology-aware gang packing) --------------------------

    def assume_placement(self, placement: Placement) -> None:
        """Narrow node_info_list to the placement's nodes (snapshot.go:363)."""
        self._placement_stack.append(self.node_info_list)
        wanted = set(placement.node_names)
        self.node_info_list = [n for n in self.node_info_list if n.name in wanted]
        self.rebuild_derived_lists()
        self.note_membership()

    def forget_placement(self) -> None:
        if self._placement_stack:
            self.node_info_list = self._placement_stack.pop()
            self.rebuild_derived_lists()
            self.note_membership()

    def num_nodes_in_placement(self) -> int:
        return len(self.node_info_list)
