"""Cache debugger: on-demand dump + cache/store/carry comparison.

Reference: pkg/scheduler/backend/cache/debugger/ — SIGUSR2 makes the
scheduler dump its cache's NodeInfos and the queue's pending pods
(dumper.go) and compare the cache against the informer truth (comparer.go:
nodes/pods present in one side but not the other). Here the comparer
additionally covers the state this design adds on top of the reference's:
the TPU pipeline's device-resident wave carry — the planes' row content is
re-derived from host truth and diffed against what the next wave launch
would consume, the natural tool for diagnosing cache-vs-informer drift in
the carry (VERDICT r3 weak #7).
"""

from __future__ import annotations

import signal


class CacheDebugger:
    def __init__(self, cache, queue, store, backend=None, log=print):
        self.cache = cache
        self.queue = queue
        self.store = store
        self.backend = backend
        self.log = log

    # -- dumper.go -----------------------------------------------------------

    def dump(self) -> str:
        """Human-readable scheduler state: per-node pod count + requested
        vector, assumed pods, queue tier depths."""
        lines = ["Dump of cached NodeInfo"]
        for name in self.cache.node_names():
            ni = self.cache.get_node_info(name)
            if ni is None:
                continue
            lines.append(
                f"  node {name}: pods={len(ni.pods)} "
                f"requested={list(ni.requested.v)} "
                f"allocatable={list(ni.allocatable.v)}"
            )
        lines.append(f"assumed pods: {self.cache.assumed_pod_count()}")
        active, backoff, unsched = self.queue.pending_pods()
        lines.append(
            f"Dump of scheduling queue: active={active} "
            f"backoff={backoff} unschedulable={unsched}"
        )
        out = "\n".join(lines)
        self.log(out)
        return out

    # -- comparer.go ---------------------------------------------------------

    def compare(self) -> list[str]:
        """Cache vs store truth. Assumed pods legitimately sit in the cache
        before their binding lands, so they are excluded from the missing-
        in-store check (the reference's comparer tolerates them the same
        way)."""
        issues: list[str] = []
        store_nodes = {n.meta.name for n in self.store.iter_kind("Node")}
        cache_nodes = set(self.cache.node_names())
        for name in sorted(store_nodes - cache_nodes):
            issues.append(f"node {name} in store but not in cache")
        for name in sorted(cache_nodes - store_nodes):
            issues.append(f"node {name} in cache but not in store")
        bound: dict[str, str] = {}
        for pod in self.store.iter_kind("Pod"):
            if pod.spec.node_name:
                bound[pod.meta.key] = pod.spec.node_name
        for name in cache_nodes:
            ni = self.cache.get_node_info(name)
            if ni is None:
                continue
            for key in ni.pods:
                want = bound.pop(key, None)
                if want is None:
                    if not self.cache.is_assumed_key(key):
                        issues.append(
                            f"pod {key} cached on {name} but not bound "
                            "in store (and not assumed)"
                        )
                elif want != name:
                    issues.append(
                        f"pod {key} cached on {name} but bound to {want}"
                    )
        for key, node in sorted(bound.items()):
            issues.append(f"pod {key} bound to {node} but missing from cache")
        for issue in issues:
            self.log(f"cache comparer: {issue}")
        return issues

    def compare_carry(self, snapshot) -> list[str]:
        """Device-carry coherence: re-derive planes rows from host truth and
        diff against the rows the next wave launch would consume. Only
        meaningful between waves (an in-flight wave legitimately holds
        placements the host hasn't processed)."""
        issues: list[str] = []
        if self.backend is None:
            return issues
        carry = getattr(self.backend, "_carry", None)
        if carry is None or "used" not in carry:
            return issues
        import numpy as np

        # MUST go through backend.sync, not builder.sync: the backend
        # accumulates builder.dirty_rows into its pending delta-upload set,
        # and a bare builder.sync would consume those rows behind its back,
        # leaving device planes silently stale
        planes = self.backend.sync(snapshot)
        host_used = planes.used[: planes.n]
        dev_used = np.asarray(carry["used"])[: planes.n]
        rows = np.flatnonzero((host_used != dev_used).any(axis=1))
        pending = getattr(self.backend, "_pending_dirty", None) or set()
        for i in rows:
            if int(i) in pending:
                continue  # host assume already queued for delta upload
            issues.append(
                f"carry row {int(i)} ({planes.node_names[int(i)]}) "
                f"diverges from host planes: host="
                f"{host_used[int(i)].tolist()} device="
                f"{dev_used[int(i)].tolist()}"
            )
        for issue in issues:
            self.log(f"carry comparer: {issue}")
        return issues

    # -- signal wiring (debugger.go ListenForSignal) -------------------------

    def install(self, signum: int = signal.SIGUSR2) -> None:
        """SIGUSR2 → dump + compare, exactly the reference's trigger."""

        def handler(_sig, _frame):
            self.dump()
            self.compare()

        signal.signal(signum, handler)
