"""Scheduler cluster cache: live state, snapshots, node ordering, gang state.

Reference: pkg/scheduler/backend/cache/.
"""

from .cache import Cache  # noqa: F401
from .snapshot import Snapshot, Placement  # noqa: F401
from .node_tree import NodeTree  # noqa: F401
from .podgroup_state import PodGroupStates, PodGroupState  # noqa: F401
