"""The scheduler: cache, queue, framework runtime, plugins, cycles.

Reference: pkg/scheduler/.
"""

from .scheduler import Scheduler, Profile, Handle  # noqa: F401
