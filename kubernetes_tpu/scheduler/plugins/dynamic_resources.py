"""DynamicResources plugin: device-claim allocation against ResourceSlices.

Reference: pkg/scheduler/framework/plugins/dynamicresources/dynamicresources.go
(PreEnqueue:252 claims-must-exist, PreFilter:408 allocator setup, Filter:637
per-node allocation attempt, Reserve, PreBind, Unreserve) with the structured
allocator from staging/src/k8s.io/dynamic-resource-allocation/ and in-memory
allocation tracking mirroring dra_manager.go / allocateddevices.go.

Device selectors evaluate a CEL subset (utils/cel.py, wired at
api/dra.py) alongside typed selectors; the per-node allocation attempt is
the same shape: gather the node's device inventory, subtract devices
already allocated (claim statuses + in-flight assumes), then greedily
satisfy each request.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ...api.dra import (
    RESERVED_FOR_MAX,
    AllocationResult,
    DeviceAllocationResult,
    DeviceRequest,
    ResourceClaim,
    pod_resource_claim_keys,
)
from ...api.types import Pod
from ..framework import events as ev
from ..framework.events import ClusterEvent, ClusterEventWithHint, QUEUE
from ..framework.interface import Plugin, Status
from ..nodeinfo import NodeInfo

ERR_CLAIM_NOT_FOUND = "waiting for dynamic resource claim to be created"
ERR_CANNOT_ALLOCATE = "cannot allocate all claims"
ERR_RESERVED_ELSEWHERE = "resourceclaim in use and not available on this node"
ERR_TOO_MANY_CONSUMERS = "resourceclaim has reached its maximum consumer count"


@dataclass
class _ClaimState:
    """Per-cycle DRA state (dynamicresources.go stateData). The taken-device
    set and slice list are computed ONCE at PreFilter (the reference builds
    its allocator there too) — Filter only copies the small taken set."""

    claims: list[ResourceClaim] = field(default_factory=list)
    base_taken: set = field(default_factory=set)  # (driver, pool, device)
    slices: list = field(default_factory=list)
    # slice-order-preserving inventory, split once per cycle: entries are
    # (slice_idx, driver, pool, device) so a per-node merge reproduces the
    # exact candidate order a full slice walk would produce
    inv_global: list = field(default_factory=list)
    inv_by_node: dict = field(default_factory=dict)
    # claim key -> per-request variant lists, each entry
    # (subrequest name, driver, selectors, count) tried in order — see
    # Allocator._request_variants. Resolved once per cycle (DeviceClass
    # lookups are node-independent; re-resolving per node deepcopied the
    # class per (pod, node) at 500-node scale)
    requirements: dict = field(default_factory=dict)
    # partitionable devices (KEP-4815): counter budgets + per-device
    # consumption, and the cluster-wide use already committed by existing
    # allocations — all keyed (driver, scoped pool, counter-set name)
    counter_caps: dict = field(default_factory=dict)
    device_consumes: dict = field(default_factory=dict)
    base_counter_use: dict = field(default_factory=dict)
    needs_allocation: bool = False
    # node name -> {claim key -> AllocationResult} computed by Filter
    allocations_per_node: dict[str, dict[str, AllocationResult]] = field(
        default_factory=dict
    )
    # set by reserve; used by unreserve/pre_bind
    reserved_node: str = ""

    def clone(self) -> "_ClaimState":
        c = _ClaimState(
            claims=list(self.claims),
            base_taken=set(self.base_taken),
            slices=list(self.slices),
            needs_allocation=self.needs_allocation,
        )
        # the prebuilt inventory/requirements are per-cycle read-only:
        # sharing the structures (not the containers) is safe
        c.inv_global = list(self.inv_global)
        c.inv_by_node = {n: list(v) for n, v in self.inv_by_node.items()}
        c.requirements = dict(self.requirements)
        c.counter_caps = dict(self.counter_caps)
        c.device_consumes = dict(self.device_consumes)
        c.base_counter_use = dict(self.base_counter_use)  # inner dicts
        # are read-only too: Filter copies them before mutating
        c.allocations_per_node = {
            n: dict(m) for n, m in self.allocations_per_node.items()
        }
        c.reserved_node = self.reserved_node
        return c


class _ChainSet:
    """A read-only base set + a small mutable overlay: the allocator's
    `taken` contract (membership, update, add, iteration) without copying
    the base per candidate node."""

    __slots__ = ("base", "extra")

    def __init__(self, base):
        self.base = base
        self.extra = set()

    def __contains__(self, key) -> bool:
        return key in self.extra or key in self.base

    def __iter__(self):
        yield from self.base
        yield from self.extra

    def add(self, key) -> None:
        self.extra.add(key)

    def update(self, items) -> None:
        self.extra.update(items)


class DRAManager:
    """In-memory view of allocated devices (dra_manager.go +
    allocateddevices.go): claim statuses from the store plus in-flight
    assumed allocations not yet written back."""

    def __init__(self, store):
        self.store = store
        # claim key -> AllocationResult assumed during Reserve
        self.assumed: dict[str, AllocationResult] = {}

    def allocated_device_ids(self) -> set[tuple[str, str, str]]:
        """(driver, pool, device) triples currently taken cluster-wide."""
        taken: set[tuple[str, str, str]] = set()
        for claim in self.store.list_refs("ResourceClaim"):
            alloc = claim.status.allocation
            if alloc is not None:
                for d in alloc.devices:
                    taken.add((d.driver, d.pool, d.device))
        for alloc in self.assumed.values():
            for d in alloc.devices:
                taken.add((d.driver, d.pool, d.device))
        return taken

    def effective_allocation(self, claim: ResourceClaim) -> AllocationResult | None:
        return claim.status.allocation or self.assumed.get(claim.meta.key)

    def assume(self, claim_key: str, alloc: AllocationResult) -> None:
        self.assumed[claim_key] = alloc

    def forget(self, claim_key: str) -> None:
        self.assumed.pop(claim_key, None)


class Allocator:
    """Structured allocator: satisfy a claim's requests from one node's
    inventory (staging/.../structured/allocator.go, typed-selector form)."""

    def __init__(self, store, manager: DRAManager):
        self.store = store
        self.manager = manager

    def _resolve_class(self, device_class_name: str, selectors):
        driver = ""
        out = list(selectors)
        if device_class_name:
            dc = self.store.try_get("DeviceClass", device_class_name)
            if dc is not None:
                driver = dc.driver
                out.extend(dc.selectors)
        return driver, out

    def _request_variants(self, request: DeviceRequest):
        """[(subrequest name, driver, selectors, count, tolerations)]
        tried in order — a plain request is its own single variant; a
        prioritized-list request (KEP-4816 firstAvailable) yields one
        variant per alternative."""
        if request.first_available:
            return [
                (sub.name, *self._resolve_class(sub.device_class_name,
                                                sub.selectors), sub.count,
                 sub.tolerations)
                for sub in request.first_available
            ]
        driver, selectors = self._resolve_class(
            request.device_class_name, request.selectors
        )
        return [("", driver, selectors, request.count, request.tolerations)]

    @staticmethod
    def _merged_inventory(cycle_state, node_name: str):
        """Per-node inventory in exact slice order, cached per node on the
        cycle state — allocate() runs once per (claim, node), and the merge
        must not be rebuilt per claim."""
        inv_cache = getattr(cycle_state, "_inv_cache", None)
        if inv_cache is None:
            inv_cache = {}
            cycle_state._inv_cache = inv_cache
        inv = inv_cache.get(node_name)
        if inv is not None:
            return inv
        node_entries = cycle_state.inv_by_node.get(node_name, [])
        if cycle_state.inv_global:
            import heapq

            inv = [
                (d, p, dev) for _, d, p, dev in heapq.merge(
                    cycle_state.inv_global, node_entries,
                    key=lambda e: e[0],
                )
            ]
        else:
            inv = [(d, p, dev) for _, d, p, dev in node_entries]
        inv_cache[node_name] = inv
        return inv

    @staticmethod
    def node_inventory(slices: list, node_name: str):
        """(driver, pool, device) inventory visible to one node, from a
        pre-listed slice set.

        Device identity is (driver, pool, device); node-local slices get a
        node-scoped pool so equally-named devices on different nodes stay
        distinct (resource/v1 semantics: a pool belongs to one driver and
        names are unique within it — drivers publish per-node pools)."""
        out = []
        for sl in slices:
            if sl.all_nodes or sl.node_name == node_name:
                pool = sl.pool if sl.all_nodes else f"{sl.node_name}/{sl.pool}"
                for dev in sl.devices:
                    out.append((sl.driver, pool, dev))
        return out

    @staticmethod
    def _counters_ok(caps: dict, uses: list[dict], drv: str, pool: str,
                     cons) -> bool:
        """KEP-4815: every counter the partition consumes must fit what is
        left of its set's budget after all use layers (committed + this
        claim + this variant)."""
        for set_name, cnts in cons.items():
            cap = caps.get((drv, pool, set_name))
            if cap is None:
                return False  # partition without a published budget
            for cname, amt in cnts.items():
                used = sum(
                    u.get((drv, pool, set_name), {}).get(cname, 0)
                    for u in uses
                )
                if used + amt > cap.get(cname, 0):
                    return False
        return True

    @staticmethod
    def _bump_counters(use: dict, drv: str, pool: str, cons) -> None:
        for set_name, cnts in cons.items():
            u = use.setdefault((drv, pool, set_name), {})
            for cname, amt in cnts.items():
                u[cname] = u.get(cname, 0) + amt

    @staticmethod
    def _merge_use(dst: dict, src: dict) -> None:
        for k, cnts in src.items():
            u = dst.setdefault(k, {})
            for cname, amt in cnts.items():
                u[cname] = u.get(cname, 0) + amt

    @staticmethod
    def _counter_tables(slices) -> tuple[dict, dict]:
        """(caps, consumes) keyed (driver, scoped pool, ...) from a raw
        slice list — the legacy allocate() path must enforce KEP-4815
        budgets exactly like the PreFilter-built cycle state does."""
        caps: dict = {}
        consumes: dict = {}
        for sl in slices:
            pool = sl.pool if sl.all_nodes else f"{sl.node_name}/{sl.pool}"
            for set_name, c in (sl.shared_counters or {}).items():
                caps[(sl.driver, pool, set_name)] = c
            for dev in sl.devices:
                if dev.consumes_counters:
                    consumes[(sl.driver, pool, dev.name)] = \
                        dev.consumes_counters
        return caps, consumes

    def allocate(
        self, claim: ResourceClaim, node_name: str,
        taken: "set[tuple[str, str, str]] | _ChainSet",
        slices: list | None = None,
        cycle_state=None,
        counter_use: dict | None = None,
    ) -> AllocationResult | None:
        """Greedy per-request allocation; mutates `taken` on success so one
        Filter pass can allocate several claims without double-booking.
        With `cycle_state` (the PreFilter-built _ClaimState) the inventory
        and class requirements come prebuilt — O(node's devices) per call
        instead of a full slice walk + DeviceClass store gets per node."""
        reqs = None
        if cycle_state is not None:
            inventory = self._merged_inventory(cycle_state, node_name)
            reqs = cycle_state.requirements.get(claim.meta.key)
        else:
            if slices is None:
                slices = self.store.list_refs("ResourceSlice")
            inventory = self.node_inventory(slices, node_name)
        picked: list[DeviceAllocationResult] = []
        newly: list[tuple[str, str, str]] = []
        committed_use = counter_use if counter_use is not None else {}
        claim_use: dict = {}
        if cycle_state is not None:
            consumes = cycle_state.device_consumes
            caps = cycle_state.counter_caps
        else:
            caps, consumes = self._counter_tables(slices)
            if counter_use is None and consumes:
                # no precomputed committed use: derive it from the taken
                # set so already-allocated partitions count against caps
                for key in taken:
                    cons = consumes.get(key)
                    if cons:
                        self._bump_counters(committed_use, key[0], key[1],
                                            cons)
        from ...api.dra import untolerated_taints

        for ri, request in enumerate(claim.spec.requests):
            variants = (reqs[ri] if reqs is not None
                        else self._request_variants(request))
            satisfied = False
            for sub_name, driver, selectors, count, tolerations in variants:
                picked_v: list[DeviceAllocationResult] = []
                newly_v: list[tuple[str, str, str]] = []
                use_v: dict = {}
                need = count
                # the allocation result names the winning alternative as
                # <request>/<subrequest> (the reference's format)
                result_name = (f"{request.name}/{sub_name}" if sub_name
                               else request.name)
                for drv, pool, dev in inventory:
                    if need == 0:
                        break
                    if driver and drv != driver:
                        continue
                    key = (drv, pool, dev.name)
                    if key in taken or key in newly or key in newly_v:
                        continue
                    if not all(sel.matches(dev.attributes,
                                           capacity=dev.capacity,
                                           driver=drv, name=dev.name)
                               for sel in selectors):
                        continue
                    if dev.taints and untolerated_taints(dev.taints,
                                                         tolerations):
                        # KEP-5055: NoSchedule AND NoExecute taints keep
                        # new allocations off the device unless tolerated
                        continue
                    cons = consumes.get(key)
                    if cons is not None and not self._counters_ok(
                        caps, [committed_use, claim_use, use_v],
                        drv, pool, cons,
                    ):
                        continue  # partition budget exhausted
                    picked_v.append(DeviceAllocationResult(
                        result_name, drv, pool, dev.name))
                    newly_v.append(key)
                    if cons is not None:
                        self._bump_counters(use_v, drv, pool, cons)
                    need -= 1
                if need == 0:
                    picked.extend(picked_v)
                    newly.extend(newly_v)
                    self._merge_use(claim_use, use_v)
                    satisfied = True
                    break  # firstAvailable: the first full fit wins
            if not satisfied:
                return None
        taken.update(newly)
        if counter_use is not None:
            self._merge_use(counter_use, claim_use)
        return AllocationResult(devices=tuple(picked), node_name=node_name)


class DynamicResources(Plugin):
    """dynamicresources/dynamicresources.go — DRA extension points."""

    name = "DynamicResources"
    STATE_KEY = "PreFilterDynamicResources"

    def __init__(self, store, manager: DRAManager | None = None):
        self.store = store
        self.manager = manager or DRAManager(store)
        self.allocator = Allocator(store, self.manager)
        # (slice rv signature, inv_global, inv_by_node, counter_caps,
        # device_consumes) — see pre_filter
        self._inventory_cache: tuple | None = None

    def events_to_register(self):
        return [
            ClusterEventWithHint(
                ClusterEvent(ev.RESOURCE_CLAIM, ev.ADD | ev.UPDATE | ev.DELETE),
                lambda *_: QUEUE,
            ),
            ClusterEventWithHint(
                ClusterEvent(ev.RESOURCE_SLICE, ev.ADD | ev.UPDATE), lambda *_: QUEUE
            ),
            ClusterEventWithHint(ClusterEvent(ev.NODE, ev.ADD), lambda *_: QUEUE),
        ]

    # -- queue gating --------------------------------------------------------

    def pre_enqueue(self, pod: Pod) -> Status:
        """PreEnqueue:252 — claims must exist before the pod may queue."""
        for key in pod_resource_claim_keys(pod):
            if self.store.try_get("ResourceClaim", key) is None:
                return Status.unresolvable(ERR_CLAIM_NOT_FOUND, plugin=self.name)
        return Status()

    # -- scheduling cycle ----------------------------------------------------

    def pre_filter(self, state, pod: Pod, nodes):
        keys = pod_resource_claim_keys(pod)
        if not keys:
            return None, Status.skip()
        s = _ClaimState()
        for key in keys:
            claim = self.store.try_get("ResourceClaim", key)
            if claim is None:
                return None, Status.unresolvable(ERR_CLAIM_NOT_FOUND, plugin=self.name)
            s.claims.append(claim)
        # allocator setup happens once per cycle (dynamicresources.go
        # PreFilter:408) — Filter must not re-list the store per node
        s.needs_allocation = any(
            self.manager.effective_allocation(c) is None for c in s.claims
        )
        if s.needs_allocation:
            s.base_taken = self.manager.allocated_device_ids()
            s.slices = self.store.list_refs("ResourceSlice")
            # the slice-derived inventory is identical between cycles while
            # the slices themselves are unchanged — cache it keyed by the
            # slices' resourceVersions (one claim pod per cycle rebuilt a
            # 5000-device inventory per POD before; reference: the
            # resourceslicetracker keeps a live view for the same reason)
            sig = tuple(sl.meta.resource_version for sl in s.slices)
            cached = self._inventory_cache
            if cached is not None and cached[0] == sig:
                (_, s.inv_global, s.inv_by_node, s.counter_caps,
                 s.device_consumes) = cached
            else:
                for idx, sl in enumerate(s.slices):
                    pool = (sl.pool if sl.all_nodes
                            else f"{sl.node_name}/{sl.pool}")
                    for set_name, caps in (sl.shared_counters or {}).items():
                        s.counter_caps[(sl.driver, pool, set_name)] = caps
                    target = (s.inv_global if sl.all_nodes
                              else s.inv_by_node.setdefault(sl.node_name, []))
                    for dev in sl.devices:
                        target.append((idx, sl.driver, pool, dev))
                        if dev.consumes_counters:
                            s.device_consumes[
                                (sl.driver, pool, dev.name)
                            ] = dev.consumes_counters
                self._inventory_cache = (
                    sig, s.inv_global, s.inv_by_node, s.counter_caps,
                    s.device_consumes,
                )
            # counter use already committed by existing allocations
            for key in s.base_taken:
                cons = s.device_consumes.get(key)
                if not cons:
                    continue
                for set_name, cnts in cons.items():
                    u = s.base_counter_use.setdefault(
                        (key[0], key[1], set_name), {}
                    )
                    for cname, amt in cnts.items():
                        u[cname] = u.get(cname, 0) + amt
            s.requirements = {
                c.meta.key: [self.allocator._request_variants(r)
                             for r in c.spec.requests]
                for c in s.claims
            }
        state.write(self.STATE_KEY, s)
        return None, None

    def filter(self, state, pod: Pod, node_info: NodeInfo) -> Status:
        s: _ClaimState | None = state.read(self.STATE_KEY)
        if s is None:
            return Status()
        node_name = node_info.name
        taken = None  # per-node OVERLAY on the PreFilter-computed base set
        counter_use: dict = {}
        node_allocs: dict[str, AllocationResult] = {}
        for claim in s.claims:
            alloc = self.manager.effective_allocation(claim)
            if alloc is not None:
                # already allocated: node must match the allocation
                if alloc.node_name and alloc.node_name != node_name:
                    return Status.unresolvable(
                        ERR_RESERVED_ELSEWHERE, plugin=self.name
                    )
                if (
                    len(claim.status.reserved_for) >= RESERVED_FOR_MAX
                    and pod.meta.key not in claim.status.reserved_for
                ):
                    return Status.unresolvable(
                        ERR_TOO_MANY_CONSUMERS, plugin=self.name
                    )
                continue
            if taken is None:
                # copying the base set per candidate node made DRA Filter
                # quadratic in allocated claims (thousands of triples copied
                # per (pod, node)); the overlay shares the immutable base.
                # base_counter_use is only populated by partitionable
                # devices (KEP-4815) — when those reach the same scale the
                # same layered treatment applies here
                taken = _ChainSet(s.base_taken)
                counter_use = {
                    k: dict(v) for k, v in s.base_counter_use.items()
                }
            alloc = self.allocator.allocate(claim, node_name, taken,
                                            cycle_state=s,
                                            counter_use=counter_use)
            if alloc is None:
                return Status.unschedulable(ERR_CANNOT_ALLOCATE, plugin=self.name)
            node_allocs[claim.meta.key] = alloc
        if node_allocs:
            s.allocations_per_node[node_name] = node_allocs
        return Status()

    def post_filter(self, state, pod: Pod, node_to_status):
        """PostFilter (dynamicresources.go:787): when the pod is
        unschedulable and holds an allocated-but-unreserved claim, the
        allocation may be what pins it to an infeasible node — deallocate
        so the retry can allocate elsewhere. Always returns Unschedulable
        (it improves the NEXT attempt; preemption still runs after)."""
        s: _ClaimState | None = state.read(self.STATE_KEY)
        if s is None:
            return None, Status.unschedulable(
                "no claims to deallocate", plugin=self.name
            )
        from ...store.store import ConflictError

        freed = 0
        for claim in s.claims:
            cur = self.store.try_get("ResourceClaim", claim.meta.key)
            if cur is None or cur.status.allocation is None:
                continue
            if cur.status.reserved_for:
                continue  # another pod holds it; not ours to free
            cur.status.allocation = None
            try:
                # optimistic-concurrency write: if a concurrent PreBind
                # reserved the claim since our snapshot, the deallocation is
                # stale and MUST lose (a forced write would erase a live
                # reservation and double-allocate the device)
                self.store.update(cur)
                freed += 1
            except ConflictError:
                pass
            except Exception:  # noqa: BLE001
                pass
        return None, Status.unschedulable(
            f"deallocation of {freed} ResourceClaims" if freed
            else "still not schedulable",
            plugin=self.name,
        )

    def reserve(self, state, pod: Pod, node_name: str) -> Status:
        s: _ClaimState | None = state.read(self.STATE_KEY)
        if s is None:
            return Status()
        s.reserved_node = node_name
        for key, alloc in s.allocations_per_node.get(node_name, {}).items():
            self.manager.assume(key, alloc)
        return Status()

    def unreserve(self, state, pod: Pod, node_name: str) -> None:
        s: _ClaimState | None = state.read(self.STATE_KEY)
        if s is None:
            return
        for key in s.allocations_per_node.get(node_name, {}):
            self.manager.forget(key)

    def pre_bind(self, state, pod: Pod, node_name: str) -> Status:
        """Write allocation + reservedFor to the store (PreBind)."""
        s: _ClaimState | None = state.read(self.STATE_KEY)
        if s is None:
            return Status()
        my_allocs = s.allocations_per_node.get(node_name, {})
        try:
            for claim in s.claims:
                cur = self.store.get("ResourceClaim", claim.meta.key)
                alloc = my_allocs.get(claim.meta.key)
                if alloc is not None and cur.status.allocation is None:
                    cur.status.allocation = alloc
                if pod.meta.key not in cur.status.reserved_for:
                    cur.status.reserved_for = tuple(cur.status.reserved_for) + (
                        pod.meta.key,
                    )
                self.store.update(cur, check_version=False)
                # forget only assumes THIS pod created — a shared claim's
                # assume may belong to another pod's in-flight binding
                if claim.meta.key in my_allocs:
                    self.manager.forget(claim.meta.key)
        except Exception as e:  # noqa: BLE001 - surfaced as bind failure
            return Status.as_error(e, self.name)
        return Status()

    def pre_bind_pre_flight(self, state, pod: Pod, node_name: str) -> Status:
        s: _ClaimState | None = state.read(self.STATE_KEY)
        if s is None:
            return Status.skip()
        return Status()

    def sign(self, pod: Pod) -> str | None:
        """Claim-referencing pods are unsignable: allocation state is
        per-pod, so batching identical-pod score reuse would be wrong
        (signers.go treats DRA pods the same way)."""
        if pod.spec.resource_claims:
            return None
        return ""
