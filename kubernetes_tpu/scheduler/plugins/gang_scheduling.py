"""GangScheduling plugin: all-or-nothing pod groups via PreEnqueue + Permit.

Reference: pkg/scheduler/framework/plugins/gangscheduling/gangscheduling.go —
PreEnqueue (:121-157) rejects until the PodGroup exists and
AllPodsCount >= policy.Gang.MinCount; Permit (:160-216) returns Wait until
ScheduledPodsCount reaches quorum, activating gang siblings, then Allows every
waiting sibling. Reads snapshot pod-group state inside gang cycles and live
cache state otherwise (:185-190).
"""

from __future__ import annotations

from ...api.types import Pod
from ...utils.envknob import float_env
from ..framework import events as ev
from ..framework.events import ClusterEvent, ClusterEventWithHint, QUEUE
from ..framework.interface import Plugin, Status

# gangscheduling.go:41 — 5 minutes; env-overridable so soak rigs can shrink
# the starvation window (see README "Gang waves" runbook) without a rebuild
GANG_WAIT_TIMEOUT = float_env("KUBE_TPU_GANG_WAIT_S", 300.0)


class GangScheduling(Plugin):
    name = "GangScheduling"

    def __init__(self, handle=None):
        self.handle = handle  # scheduler Handle: .store, .cache, .queue, .framework

    def set_handle(self, handle) -> None:
        self.handle = handle

    def _group_key(self, pod: Pod) -> str | None:
        sg = pod.spec.scheduling_group
        if sg is None:
            return None
        return f"{pod.meta.namespace}/{sg.pod_group_name}"

    def events_to_register(self):
        return [
            ClusterEventWithHint(ClusterEvent(ev.POD, ev.ADD), lambda p, o, n: QUEUE),
            ClusterEventWithHint(ClusterEvent(ev.POD_GROUP, ev.ADD), lambda p, o, n: QUEUE),
        ]

    def pre_enqueue(self, pod: Pod) -> Status:
        gk = self._group_key(pod)
        if gk is None:
            return Status()
        group = self.handle.store.try_get("PodGroup", gk) if self.handle else None
        if group is None:
            return Status.unresolvable(f"PodGroup {gk} not found", plugin=self.name)
        state = self.handle.cache.pod_group_states.get(gk)
        all_count = state.all_pods_count if state else 0
        if all_count < group.spec.policy.min_count:
            return Status.unresolvable(
                f"gang has {all_count}/{group.spec.policy.min_count} pods",
                plugin=self.name,
            )
        return Status()

    def permit(self, state, pod: Pod, node_name: str):
        gk = self._group_key(pod)
        if gk is None:
            return Status(), 0.0
        group = self.handle.store.try_get("PodGroup", gk)
        if group is None:
            return Status.unschedulable(f"PodGroup {gk} disappeared", plugin=self.name), 0.0
        min_count = group.spec.policy.min_count
        # gang cycles read the snapshot state; per-pod cycles the live cache
        # (gangscheduling.go:185-190)
        snap_states = self.handle.snapshot.pod_group_states
        if state.is_pod_group_scheduling_cycle and gk in snap_states:
            gstate = snap_states[gk]
        else:
            gstate = self.handle.cache.pod_group_states.get(gk)
        assumed_or_scheduled = gstate.assumed_or_scheduled_count if gstate else 0
        if assumed_or_scheduled < min_count:
            # activate siblings stuck in unschedulable/backoff so they get a cycle
            if gstate is not None and self.handle.queue is not None:
                siblings = [
                    self.handle.store.try_get("Pod", k) for k in gstate.unscheduled
                ]
                self.handle.queue.activate([s for s in siblings if s is not None])
            return Status.wait(plugin=self.name), GANG_WAIT_TIMEOUT
        # quorum reached: allow every waiting sibling (gangscheduling.go:207-212)
        fw = self.handle.framework
        if fw is not None:
            for wp in fw.iterate_waiting_pods():
                if self._group_key(wp.pod) == gk:
                    wp.allow(self.name)
        return Status(), 0.0
