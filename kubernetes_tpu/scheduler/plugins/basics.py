"""Small in-tree plugins: PrioritySort, NodeName, NodeUnschedulable, NodePorts,
SchedulingGates, TaintToleration, ImageLocality, DefaultBinder.

Reference: pkg/scheduler/framework/plugins/{queuesort,nodename,
nodeunschedulable,nodeports,schedulinggates,tainttoleration,imagelocality,
defaultbinder}.
"""

from __future__ import annotations

from ...api.types import NO_SCHEDULE, PREFER_NO_SCHEDULE, Pod
from ..framework import events as ev
from ..framework.events import ClusterEvent, ClusterEventWithHint, QUEUE, QUEUE_SKIP
from ..framework.interface import MAX_NODE_SCORE, Plugin, Status
from ..nodeinfo import NodeInfo


class PrioritySort(Plugin):
    """queuesort/priority_sort.go — priority desc, then queue-entry time asc."""

    name = "PrioritySort"

    def less(self, a, b) -> bool:
        pa, pb = a.pod.spec.priority, b.pod.spec.priority
        if pa != pb:
            return pa > pb
        return a.timestamp < b.timestamp


class NodeName(Plugin):
    """nodename/node_name.go:79 — spec.nodeName equality."""

    name = "NodeName"

    def events_to_register(self):
        return [ClusterEventWithHint(ClusterEvent(ev.NODE, ev.ADD))]

    def filter(self, state, pod: Pod, node_info: NodeInfo) -> Status:
        if pod.spec.node_name and pod.spec.node_name != node_info.name:
            return Status.unresolvable("node didn't match the requested node name", plugin=self.name)
        return Status()


class NodeUnschedulable(Plugin):
    """nodeunschedulable/node_unschedulable.go:142 — spec.unschedulable with
    toleration escape hatch."""

    name = "NodeUnschedulable"
    TAINT_KEY = "node.kubernetes.io/unschedulable"

    def events_to_register(self):
        def hint(pod, old, new):
            if new is not None and not new.spec.unschedulable:
                return QUEUE
            return QUEUE_SKIP

        return [
            ClusterEventWithHint(ClusterEvent(ev.NODE, ev.ADD | ev.UPDATE_NODE_TAINT), hint)
        ]

    def filter(self, state, pod: Pod, node_info: NodeInfo) -> Status:
        node = node_info.node
        if node is not None and node.spec.unschedulable:
            tolerated = any(
                t.key in (self.TAINT_KEY, "") and t.operator == "Exists"
                for t in pod.spec.tolerations
            )
            if not tolerated:
                return Status.unresolvable("node(s) were unschedulable", plugin=self.name)
        return Status()


class NodePorts(Plugin):
    """nodeports/node_ports.go — host-port conflict check vs NodeInfo.UsedPorts."""

    name = "NodePorts"
    PRE_FILTER_KEY = "PreFilterNodePorts"

    def events_to_register(self):
        return [ClusterEventWithHint(ClusterEvent(ev.POD, ev.DELETE))]

    def sign(self, pod: Pod) -> str | None:
        """signers.go PortsSigner — host-port demands key the signature."""
        ports = sorted(
            (p.host_ip, p.protocol, p.host_port)
            for c in pod.spec.containers for p in c.ports if p.host_port > 0
        )
        return ";".join(f"{ip}:{proto}:{port}" for ip, proto, port in ports)

    def pre_filter(self, state, pod: Pod, nodes):
        ports = []
        for c in pod.spec.containers:
            for p in c.ports:
                if p.host_port > 0:
                    ports.append((p.host_ip or "0.0.0.0", p.protocol, p.host_port))
        if not ports:
            return None, Status.skip()
        state.write(self.PRE_FILTER_KEY, ports)
        return None, Status()

    @staticmethod
    def _conflict(want: tuple[str, str, int], used: dict) -> bool:
        ip, proto, port = want
        for (uip, uproto, uport) in used:
            if uport != port or uproto != proto:
                continue
            if ip == "0.0.0.0" or uip == "0.0.0.0" or uip == ip:
                return True
        return False

    def filter(self, state, pod: Pod, node_info: NodeInfo) -> Status:
        ports = state.read(self.PRE_FILTER_KEY)
        if not ports:
            return Status()
        for want in ports:
            if self._conflict(want, node_info.used_ports):
                return Status.unschedulable(
                    "node(s) didn't have free ports for the requested pod ports",
                    plugin=self.name,
                )
        return Status()


class SchedulingGates(Plugin):
    """schedulinggates — PreEnqueue gate on spec.schedulingGates."""

    name = "SchedulingGates"

    def events_to_register(self):
        def hint(pod, old, new):
            if new is not None and not new.spec.scheduling_gates:
                return QUEUE
            return QUEUE_SKIP

        return [
            ClusterEventWithHint(
                ClusterEvent(ev.POD, ev.UPDATE_POD_SCHEDULING_GATES_ELIMINATED), hint
            )
        ]

    def pre_enqueue(self, pod: Pod) -> Status:
        if pod.spec.scheduling_gates:
            return Status.unresolvable(
                f"waiting for scheduling gates: {list(pod.spec.scheduling_gates)}",
                plugin=self.name,
            )
        return Status()


class TaintToleration(Plugin):
    """tainttoleration/taint_toleration.go — Filter on NoSchedule/NoExecute,
    Score counts intolerable PreferNoSchedule taints (inverted)."""

    name = "TaintToleration"
    PRE_SCORE_KEY = "PreScoreTaintToleration"

    def events_to_register(self):
        return [ClusterEventWithHint(ClusterEvent(ev.NODE, ev.ADD | ev.UPDATE_NODE_TAINT))]

    def sign(self, pod: Pod) -> str | None:
        """signers.go TolerationsSigner — pods differing in tolerations must
        not share a batch signature."""
        return ";".join(
            f"{t.key}:{t.operator}:{t.value}:{t.effect}"
            for t in sorted(pod.spec.tolerations, key=lambda t: (t.key, t.effect))
        )

    def filter(self, state, pod: Pod, node_info: NodeInfo) -> Status:
        node = node_info.node
        if node is None:
            return Status()
        for taint in node.spec.taints:
            if taint.effect not in (NO_SCHEDULE, "NoExecute"):
                continue
            if not any(t.tolerates(taint) for t in pod.spec.tolerations):
                return Status.unresolvable(
                    f"node(s) had untolerated taint {{{taint.key}: {taint.value}}}",
                    plugin=self.name,
                )
        return Status()

    def pre_score(self, state, pod: Pod, nodes) -> Status:
        tolerations = [t for t in pod.spec.tolerations if t.effect in ("", PREFER_NO_SCHEDULE)]
        state.write(self.PRE_SCORE_KEY, tolerations)
        return Status()

    def score(self, state, pod: Pod, node_info: NodeInfo):
        tolerations = state.read(self.PRE_SCORE_KEY) or []
        node = node_info.node
        count = 0
        if node is not None:
            for taint in node.spec.taints:
                if taint.effect == PREFER_NO_SCHEDULE and not any(
                    t.tolerates(taint) for t in tolerations
                ):
                    count += 1
        return count, Status()

    def normalize_score(self, state, pod: Pod, scores) -> Status:
        """Invert: fewer intolerable taints -> higher score (:180-215)."""
        max_count = max((s for _, s in scores), default=0)
        for row in scores:
            if max_count > 0:
                row[1] = MAX_NODE_SCORE - (row[1] * MAX_NODE_SCORE) // max_count
            else:
                row[1] = MAX_NODE_SCORE
        return Status()


class ImageLocality(Plugin):
    """imagelocality/image_locality.go — score by present image bytes, scaled
    into [23MB, 1GB * containers] (:34-35,93-105)."""

    name = "ImageLocality"
    # KiB units (matching the device kernel's int32 math; < 1 score point of
    # rounding vs the reference's byte thresholds image_locality.go:34-35)
    MIN_THRESHOLD = 23 * 1024
    MAX_CONTAINER_THRESHOLD = 1024 * 1024

    def score(self, state, pod: Pod, node_info: NodeInfo):
        total = 0
        for c in pod.spec.containers:
            if c.image and c.image in node_info.image_sizes:
                total += node_info.image_sizes[c.image] >> 10
        max_threshold = self.MAX_CONTAINER_THRESHOLD * max(len(pod.spec.containers), 1)
        if total < self.MIN_THRESHOLD:
            score = 0
        elif total > max_threshold:
            score = MAX_NODE_SCORE
        else:
            score = (
                MAX_NODE_SCORE
                * (total - self.MIN_THRESHOLD)
                // (max_threshold - self.MIN_THRESHOLD)
            )
        return score, Status()


class DefaultBinder(Plugin):
    """defaultbinder — POST pods/binding against the store."""

    name = "DefaultBinder"

    def __init__(self, store):
        self._store = store

    def bind(self, state, pod: Pod, node_name: str) -> Status:
        from ...store.store import ConflictError, NotFoundError

        try:
            bind_sub = getattr(self._store, "bind_pod", None)
            if bind_sub is not None:
                # binding subresource — the reference's actual API shape
                bind_sub(pod.meta.key, node_name)
            else:
                cur = self._store.get("Pod", pod.meta.key)
                cur.spec.node_name = node_name
                self._store.update(cur, check_version=False)
        except (NotFoundError, ConflictError) as e:
            return Status.as_error(e, self.name)
        return Status()
