"""DefaultPreemption: dry-run victim search + eviction.

Reference: pkg/scheduler/framework/plugins/defaultpreemption/ (SelectVictimsOnNode
:207 — remove lower-priority pods, re-run Filter, reprieve victims that fit
back) driving the engine at pkg/scheduler/framework/preemption/preemption.go
(DryRunPreemption:408, candidate ranking in SelectCandidate).
"""

from __future__ import annotations

from ...api.resource import ResourceNames
from ...api.types import Pod
from ..framework import events as ev
from ..framework.events import ClusterEvent, ClusterEventWithHint
from ..framework.interface import (
    UNSCHEDULABLE,
    Plugin,
    PostFilterResult,
    Status,
)
from ..nodeinfo import NodeInfo, PodInfo


class _Candidate:
    __slots__ = ("node_name", "victims")

    def __init__(self, node_name: str, victims: list[PodInfo]):
        self.node_name = node_name
        self.victims = victims


class DefaultPreemption(Plugin):
    name = "DefaultPreemption"

    def __init__(self, names: ResourceNames, handle=None):
        self.names = names
        self.handle = handle

    def set_handle(self, handle) -> None:
        self.handle = handle

    def events_to_register(self):
        return [ClusterEventWithHint(ClusterEvent(ev.POD, ev.DELETE))]

    # -- eligibility (preemption.go PodEligibleToPreemptOthers) --------------

    def _eligible(self, pod: Pod) -> bool:
        if pod.spec.preemption_policy == "Never":
            return False
        nominated = pod.status.nominated_node_name
        if nominated and self.handle is not None:
            # if a previous nomination exists and victims are still terminating,
            # wait (preemption.go:169) — approximate via node existence check
            ni = self.handle.snapshot.get(nominated) if self.handle.snapshot else None
            if ni is not None and any(
                p.pod.is_terminating and p.pod.spec.priority < pod.spec.priority
                for p in ni.iter_pods()
            ):
                return False
        return True

    # -- victim search -------------------------------------------------------

    def _select_victims_on_node(self, state, pod: Pod, node_info: NodeInfo):
        """SelectVictimsOnNode (default_preemption.go:207): remove all lower-
        priority pods, check fit, then reprieve as many as possible
        (highest-priority victims first)."""
        fw = self.handle.framework
        ni = node_info.clone()
        state = state.clone()
        lower = sorted(
            (pi for pi in ni.iter_pods() if pi.pod.spec.priority < pod.spec.priority),
            key=lambda pi: (-pi.pod.spec.priority, pi.pod.meta.creation_timestamp),
        )
        if not lower:
            return None
        removed: list[PodInfo] = []
        for pi in lower:
            ni.remove_pod(pi.key)
            fw.run_pre_filter_extension_remove_pod(state, pod, pi, ni)
            removed.append(pi)
        if not fw.run_filter_plugins(state, pod, ni).is_success:
            return None  # even with all victims gone the pod doesn't fit
        # reprieve: re-add highest-priority victims that still fit
        victims: list[PodInfo] = []
        for pi in removed:  # removed is sorted high->low priority
            ni.add_pod(pi)
            fw.run_pre_filter_extension_add_pod(state, pod, pi, ni)
            if not fw.run_filter_plugins(state, pod, ni).is_success:
                ni.remove_pod(pi.key)
                fw.run_pre_filter_extension_remove_pod(state, pod, pi, ni)
                victims.append(pi)
        return victims if victims else None

    # -- candidate ranking (preemption.go SelectCandidate) --------------------

    @staticmethod
    def _candidate_rank(c: _Candidate):
        priorities = [v.pod.spec.priority for v in c.victims]
        return (
            max(priorities, default=-(1 << 31)),  # lowest max victim priority
            sum(priorities),
            len(c.victims),
        )

    # -- post filter -----------------------------------------------------------

    def post_filter(self, state, pod: Pod, node_to_status):
        if not self._eligible(pod):
            return None, Status.unresolvable(
                "preemption not allowed for this pod", plugin=self.name
            )
        snapshot = self.handle.snapshot
        candidates: list[_Candidate] = []
        for ni in snapshot.list_nodes():
            if node_to_status.get(ni.name).code != UNSCHEDULABLE:
                continue  # UnschedulableAndUnresolvable can't be fixed by eviction
            victims = self._select_victims_on_node(state, pod, ni)
            if victims:
                candidates.append(_Candidate(ni.name, victims))
        if not candidates:
            return None, Status.unschedulable(
                "preemption: 0/%d nodes are available" % snapshot.num_nodes(),
                plugin=self.name,
            )
        best = min(candidates, key=self._candidate_rank)
        # evict victims via API (async dispatcher in reference; direct here)
        store = self.handle.store
        for v in best.victims:
            try:
                store.delete("Pod", v.key)
            except Exception:
                pass
        # clear lower-priority nominations on this node (preemption.go:236)
        return (
            PostFilterResult(nominated_node_name=best.node_name),
            Status(),
        )
