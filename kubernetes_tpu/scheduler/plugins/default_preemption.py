"""DefaultPreemption: dry-run victim search + PDB-aware selection + async
eviction.

Reference: pkg/scheduler/framework/plugins/defaultpreemption/
(SelectVictimsOnNode :207 — remove lower-priority pods, re-run Filter,
reprieve PDB-violating victims first then the rest, highest priority first;
filterPodsWithPDBViolation :380) driving the engine at
pkg/scheduler/framework/preemption/preemption.go (DryRunPreemption :408,
candidate sampling GetOffsetAndNumCandidates :174-191,
pickOneNodeForPreemption :302-360) with the async executor of
preemption/executor.go (prepareCandidateAsync :145 — nomination happens in
the scheduling cycle, evictions never block it).
"""

from __future__ import annotations

import time

from ...api.resource import ResourceNames
from ...api.types import Pod
from ..framework import events as ev
from ..framework.events import ClusterEvent, ClusterEventWithHint
from ..framework.interface import (
    UNSCHEDULABLE,
    Plugin,
    PostFilterResult,
    Status,
)
from ..nodeinfo import NodeInfo, PodInfo

# preemption.go:45-49 — candidate search is capped, not exhaustive
MIN_CANDIDATE_NODES_PERCENTAGE = 10
MIN_CANDIDATE_NODES_ABSOLUTE = 100


class _Candidate:
    __slots__ = ("node_name", "victims", "num_pdb_violations")

    def __init__(self, node_name: str, victims: list[PodInfo],
                 num_pdb_violations: int = 0):
        self.node_name = node_name
        self.victims = victims
        self.num_pdb_violations = num_pdb_violations


class PreemptionExecutor:
    """executor.go — runs a chosen candidate's preparation off the
    scheduling loop: clear lower-priority nominations on the node, record
    the disruption against matching PDBs, evict the victims. With the async
    dispatcher the evictions ride worker threads (SchedulerAsyncAPICalls /
    SchedulerAsyncPreemption); without it they run inline (deterministic
    tests)."""

    def __init__(self, handle):
        self.handle = handle

    def prepare_candidate(self, candidate: _Candidate, preemptor: Pod,
                          pdbs: list) -> None:
        # 1. lower-priority pods nominated onto this node lose their
        # nomination (executor.go prepareCandidate ClearNominatedNodeName):
        # queue-side AND status-side — a stale status.nominatedNodeName
        # would keep forcing the demoted pod onto the host path and keep
        # simulating it onto a node it will not get
        queue = self.handle.queue
        store = self.handle.store
        for key in list(queue.nominated_pods_for_node(candidate.node_name)):
            npi = queue.nominated_pod_info(key)
            if npi is not None and npi.pod.spec.priority < preemptor.spec.priority:
                queue.delete_nominated_pod_if_exists(npi.pod)
                patch = getattr(store, "patch_pod_status", None)
                if patch is not None:
                    patch(key, nominated_node="")
        # 2. record the disruption on matching PDBs BEFORE evicting, so
        # concurrent preemptors see the spent budget (the eviction API's
        # DisruptedPods bookkeeping)
        store = self.handle.store
        now = time.time()
        for v in candidate.victims:
            for pdb in pdbs:
                if pdb.meta.namespace != v.pod.meta.namespace:
                    continue
                sel = pdb.spec.selector
                if sel is None or sel.empty or not sel.matches(v.pod.meta.labels):
                    continue
                cur = store.try_get("PodDisruptionBudget", pdb.meta.key)
                if cur is None:
                    continue
                cur.status.disrupted_pods[v.pod.meta.name] = now
                if cur.status.disruptions_allowed > 0:
                    cur.status.disruptions_allowed -= 1
                try:
                    store.update(cur, check_version=False)
                except Exception:  # noqa: BLE001
                    pass
        # 3. evict — async through the dispatcher when available
        dispatcher = getattr(self.handle, "api_dispatcher", None)
        if dispatcher is not None:
            from ..api_dispatcher import APICall, CallSkippedError, POD_DELETE
            from ...store.store import NotFoundError

            def make_evict(key):
                def evict():
                    try:
                        store.delete("Pod", key)
                    except NotFoundError:
                        pass

                return evict

            for v in candidate.victims:
                try:
                    dispatcher.add(APICall(POD_DELETE, v.key, make_evict(v.key)))
                except CallSkippedError:
                    pass  # an even-more-relevant call owns the object
        else:
            for v in candidate.victims:
                try:
                    store.delete("Pod", v.key)
                except Exception:  # noqa: BLE001
                    pass


class DefaultPreemption(Plugin):
    name = "DefaultPreemption"

    def __init__(self, names: ResourceNames, handle=None):
        self.names = names
        self.handle = handle
        self._offset = 0  # rotating candidate offset (fairness)

    def set_handle(self, handle) -> None:
        self.handle = handle

    def events_to_register(self):
        return [ClusterEventWithHint(ClusterEvent(ev.POD, ev.DELETE))]

    # -- eligibility (preemption.go PodEligibleToPreemptOthers) --------------

    def _eligible(self, pod: Pod) -> bool:
        if pod.spec.preemption_policy == "Never":
            return False
        nominated = pod.status.nominated_node_name
        if nominated and self.handle is not None:
            # if a previous nomination exists and victims are still terminating,
            # wait (preemption.go:169) — approximate via node existence check
            ni = self.handle.snapshot.get(nominated) if self.handle.snapshot else None
            if ni is not None and any(
                p.pod.is_terminating and p.pod.spec.priority < pod.spec.priority
                for p in ni.iter_pods()
            ):
                return False
        return True

    # -- PDB awareness -------------------------------------------------------

    def _list_pdbs(self) -> list:
        if self.handle is None:
            return []
        return list(self.handle.store.iter_kind("PodDisruptionBudget"))

    @staticmethod
    def _split_pdb_violation(pod_infos: list[PodInfo], pdbs: list):
        """filterPodsWithPDBViolation (default_preemption.go:380): walk the
        victims decrementing each matching PDB's remaining budget; a victim
        that drives any budget negative is 'violating'."""
        allowed = [pdb.status.disruptions_allowed for pdb in pdbs]
        violating: list[PodInfo] = []
        non_violating: list[PodInfo] = []
        for pi in pod_infos:
            pod = pi.pod
            violated = False
            if pod.meta.labels:
                for i, pdb in enumerate(pdbs):
                    if pdb.meta.namespace != pod.meta.namespace:
                        continue
                    sel = pdb.spec.selector
                    if sel is None or sel.empty or not sel.matches(pod.meta.labels):
                        continue
                    if pod.meta.name in pdb.status.disrupted_pods:
                        continue  # already processed; don't double-count
                    allowed[i] -= 1
                    if allowed[i] < 0:
                        violated = True
            (violating if violated else non_violating).append(pi)
        return violating, non_violating

    # -- victim search -------------------------------------------------------

    def _select_victims_on_node(self, state, pod: Pod, node_info: NodeInfo,
                                pdbs: list):
        """SelectVictimsOnNode (default_preemption.go:207): remove all lower-
        priority pods, check fit, then reprieve as many as possible — PDB-
        violating victims first, then the rest, highest priority first.
        Returns (victims, num_pdb_violations) or None."""
        fw = self.handle.framework
        ni = node_info.clone()
        state = state.clone()
        lower = [pi for pi in ni.iter_pods()
                 if pi.pod.spec.priority < pod.spec.priority]
        if not lower:
            return None
        for pi in lower:
            ni.remove_pod(pi.key)
            fw.run_pre_filter_extension_remove_pod(state, pod, pi, ni)
        if not fw.run_filter_plugins(state, pod, ni).is_success:
            return None  # even with all victims gone the pod doesn't fit
        # MoreImportantPod order: priority desc, then earlier start
        lower.sort(key=lambda pi: (-pi.pod.spec.priority,
                                   pi.pod.meta.creation_timestamp))
        violating, non_violating = self._split_pdb_violation(lower, pdbs)
        victims: list[PodInfo] = []
        num_violations = 0

        def reprieve(pi: PodInfo) -> bool:
            ni.add_pod(pi)
            fw.run_pre_filter_extension_add_pod(state, pod, pi, ni)
            if fw.run_filter_plugins(state, pod, ni).is_success:
                return True
            ni.remove_pod(pi.key)
            fw.run_pre_filter_extension_remove_pod(state, pod, pi, ni)
            victims.append(pi)
            return False

        for pi in violating:
            if not reprieve(pi):
                num_violations += 1
        for pi in non_violating:
            reprieve(pi)
        if not victims:
            return None
        victims.sort(key=lambda pi: (-pi.pod.spec.priority,
                                     pi.pod.meta.creation_timestamp))
        return victims, num_violations

    # -- candidate sampling + ranking ----------------------------------------

    def _num_candidates(self, num_nodes: int) -> int:
        """GetOffsetAndNumCandidates (preemption.go:174-191)."""
        n = num_nodes * MIN_CANDIDATE_NODES_PERCENTAGE // 100
        n = max(n, MIN_CANDIDATE_NODES_ABSOLUTE)
        return min(n, num_nodes)

    @staticmethod
    def _candidate_rank(c: _Candidate):
        """pickOneNodeForPreemption criteria (preemption.go:302-360), all
        minimized: PDB violations, highest victim priority, priority sum,
        victim count, then earliest victim start time (prefer nodes whose
        highest-priority victim started LATEST => minimize -start)."""
        priorities = [v.pod.spec.priority for v in c.victims]
        top = max(priorities, default=-(1 << 31))
        latest_start = max(
            (v.pod.meta.creation_timestamp for v in c.victims
             if v.pod.spec.priority == top), default=0.0
        )
        return (
            c.num_pdb_violations,
            top,
            sum(priorities),
            len(c.victims),
            -latest_start,
        )

    # -- post filter -----------------------------------------------------------

    def post_filter(self, state, pod: Pod, node_to_status):
        if not self._eligible(pod):
            return None, Status.unresolvable(
                "preemption not allowed for this pod", plugin=self.name
            )
        snapshot = self.handle.snapshot
        pdbs = self._list_pdbs()
        nodes = snapshot.list_nodes()
        num_all = len(nodes)
        want = self._num_candidates(num_all)
        candidates: list[_Candidate] = []
        # rotating offset (the reference randomizes; a rotating cursor gives
        # the same fairness deterministically)
        start = self._offset % num_all if num_all else 0
        scanned = 0
        for i in range(num_all):
            ni = nodes[(start + i) % num_all]
            scanned += 1
            if node_to_status.get(ni.name).code != UNSCHEDULABLE:
                continue  # UnschedulableAndUnresolvable can't be fixed by eviction
            found = self._select_victims_on_node(state, pod, ni, pdbs)
            if found is not None:
                victims, violations = found
                candidates.append(_Candidate(ni.name, victims, violations))
                if len(candidates) >= want:
                    break
        self._offset = (start + scanned) % num_all if num_all else 0
        if not candidates:
            return None, Status.unschedulable(
                "preemption: 0/%d nodes are available" % num_all,
                plugin=self.name,
            )
        best = min(candidates, key=self._candidate_rank)
        # nomination is synchronous (the scheduling cycle needs it); victim
        # eviction + nomination cleanup run via the executor — off the loop
        # when the async dispatcher is available (executor.go:145)
        PreemptionExecutor(self.handle).prepare_candidate(best, pod, pdbs)
        return (
            PostFilterResult(nominated_node_name=best.node_name),
            Status(),
        )
