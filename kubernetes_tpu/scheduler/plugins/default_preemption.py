"""DefaultPreemption: dry-run victim search + PDB-aware selection + async
eviction.

Reference: pkg/scheduler/framework/plugins/defaultpreemption/
(SelectVictimsOnNode :207 — remove lower-priority pods, re-run Filter,
reprieve PDB-violating victims first then the rest, highest priority first;
filterPodsWithPDBViolation :380) driving the engine at
pkg/scheduler/framework/preemption/preemption.go (DryRunPreemption :408,
candidate sampling GetOffsetAndNumCandidates :174-191,
pickOneNodeForPreemption :302-360) with the async executor of
preemption/executor.go (prepareCandidateAsync :145 — nomination happens in
the scheduling cycle, evictions never block it).
"""

from __future__ import annotations

import time

from ...api.resource import ResourceNames
from ...api.types import Pod
from ..framework import events as ev
from ..framework.events import ClusterEvent, ClusterEventWithHint
from ..framework.interface import (
    UNSCHEDULABLE,
    Plugin,
    PostFilterResult,
    Status,
)
from ..nodeinfo import NodeInfo, PodInfo

# preemption.go:45-49 — candidate search is capped, not exhaustive
MIN_CANDIDATE_NODES_PERCENTAGE = 10
MIN_CANDIDATE_NODES_ABSOLUTE = 100


class _Candidate:
    __slots__ = ("node_name", "victims", "num_pdb_violations")

    def __init__(self, node_name: str, victims: list[PodInfo],
                 num_pdb_violations: int = 0):
        self.node_name = node_name
        self.victims = victims
        self.num_pdb_violations = num_pdb_violations


class PreemptionExecutor:
    """executor.go — runs a chosen candidate's preparation off the
    scheduling loop: clear lower-priority nominations on the node, record
    the disruption against matching PDBs, evict the victims. With the async
    dispatcher the evictions ride worker threads (SchedulerAsyncAPICalls /
    SchedulerAsyncPreemption); without it they run inline (deterministic
    tests)."""

    def __init__(self, handle):
        self.handle = handle

    def prepare_candidate(self, candidate: _Candidate, preemptor: Pod,
                          pdbs: list) -> None:
        # 1. lower-priority pods nominated onto this node lose their
        # nomination (executor.go prepareCandidate ClearNominatedNodeName):
        # queue-side AND status-side — a stale status.nominatedNodeName
        # would keep forcing the demoted pod onto the host path and keep
        # simulating it onto a node it will not get
        queue = self.handle.queue
        store = self.handle.store
        for key in list(queue.nominated_pods_for_node(candidate.node_name)):
            npi = queue.nominated_pod_info(key)
            if npi is not None and npi.pod.spec.priority < preemptor.spec.priority:
                queue.delete_nominated_pod_if_exists(npi.pod)
                patch = getattr(store, "patch_pod_status", None)
                if patch is not None:
                    patch(key, nominated_node="")
        # 2. record the disruption on matching PDBs BEFORE evicting, so
        # concurrent preemptors see the spent budget (the eviction API's
        # DisruptedPods bookkeeping)
        store = self.handle.store
        now = time.time()
        for v in candidate.victims:
            for pdb in pdbs:
                if pdb.meta.namespace != v.pod.meta.namespace:
                    continue
                sel = pdb.spec.selector
                if sel is None or sel.empty or not sel.matches(v.pod.meta.labels):
                    continue
                cur = store.try_get("PodDisruptionBudget", pdb.meta.key)
                if cur is None:
                    continue
                cur.status.disrupted_pods[v.pod.meta.name] = now
                if cur.status.disruptions_allowed > 0:
                    cur.status.disruptions_allowed -= 1
                try:
                    store.update(cur, check_version=False)
                except Exception:  # noqa: BLE001
                    pass
        # 3. evict — async through the dispatcher when available
        dispatcher = getattr(self.handle, "api_dispatcher", None)
        if dispatcher is not None:
            from ..api_dispatcher import APICall, CallSkippedError, POD_DELETE
            from ...store.store import NotFoundError

            def make_evict(key):
                def evict():
                    try:
                        store.delete("Pod", key)
                    except NotFoundError:
                        pass

                return evict

            for v in candidate.victims:
                try:
                    dispatcher.add(APICall(POD_DELETE, v.key, make_evict(v.key)))
                except CallSkippedError:
                    pass  # an even-more-relevant call owns the object
        else:
            for v in candidate.victims:
                try:
                    store.delete("Pod", v.key)
                except Exception:  # noqa: BLE001
                    pass


class DefaultPreemption(Plugin):
    name = "DefaultPreemption"

    def __init__(self, names: ResourceNames, handle=None):
        self.names = names
        self.handle = handle
        self._offset = 0  # rotating candidate offset (fairness)
        # (node name, node generation, preemptor priority) -> sorted
        # lower-priority PodInfos (see _batch_select_victims)
        self._victim_cache: dict = {}

    def set_handle(self, handle) -> None:
        self.handle = handle

    def events_to_register(self):
        return [ClusterEventWithHint(ClusterEvent(ev.POD, ev.DELETE))]

    # -- eligibility (preemption.go PodEligibleToPreemptOthers) --------------

    def _eligible(self, pod: Pod) -> bool:
        if pod.spec.preemption_policy == "Never":
            return False
        nominated = pod.status.nominated_node_name
        if nominated and self.handle is not None:
            # if a previous nomination exists and victims are still terminating,
            # wait (preemption.go:169) — approximate via node existence check
            ni = self.handle.snapshot.get(nominated) if self.handle.snapshot else None
            if ni is not None and any(
                p.pod.is_terminating and p.pod.spec.priority < pod.spec.priority
                for p in ni.iter_pods()
            ):
                return False
        return True

    # -- PDB awareness -------------------------------------------------------

    def _list_pdbs(self) -> list:
        if self.handle is None:
            return []
        return list(self.handle.store.iter_kind("PodDisruptionBudget"))

    @staticmethod
    def _split_pdb_violation(pod_infos: list[PodInfo], pdbs: list):
        """filterPodsWithPDBViolation (default_preemption.go:380): walk the
        victims decrementing each matching PDB's remaining budget; a victim
        that drives any budget negative is 'violating'."""
        allowed = [pdb.status.disruptions_allowed for pdb in pdbs]
        violating: list[PodInfo] = []
        non_violating: list[PodInfo] = []
        for pi in pod_infos:
            pod = pi.pod
            violated = False
            if pod.meta.labels:
                for i, pdb in enumerate(pdbs):
                    if pdb.meta.namespace != pod.meta.namespace:
                        continue
                    sel = pdb.spec.selector
                    if sel is None or sel.empty or not sel.matches(pod.meta.labels):
                        continue
                    if pod.meta.name in pdb.status.disrupted_pods:
                        continue  # already processed; don't double-count
                    allowed[i] -= 1
                    if allowed[i] < 0:
                        violated = True
            (violating if violated else non_violating).append(pi)
        return violating, non_violating

    # -- victim search -------------------------------------------------------

    def _fit_plugin(self):
        from .node_resources import NodeResourcesFit

        for p in self.handle.framework.filter_plugins:
            if isinstance(p, NodeResourcesFit):
                return p
        return None

    @staticmethod
    def _fits_resources(fitp, req, node_info: NodeInfo, used: list[int],
                        pod_count: int) -> bool:
        """NodeResourcesFit.filter's exact arithmetic against an overridden
        usage vector (fit.go:673-760) — the reprieve loop's only possible
        failure mode when nothing but resources can be affected."""
        from ...api.resource import PODS

        alloc = node_info.allocatable
        if pod_count + 1 > alloc[PODS]:
            return False
        width = len(used)
        for i in range(width):
            r = req[i]
            if r == 0 or i == PODS:
                continue
            rname = (fitp.names.names[i] if i < fitp.names.width
                     else f"res{i}")
            if rname in fitp.ignored:
                continue
            if r > alloc[i] - used[i]:
                return False
        return True

    @classmethod
    def _resource_only(cls, pod: Pod, node_info: NodeInfo) -> bool:
        """True when re-ADDING a victim can only break NodeResourcesFit:
        the preemptor carries no inter-pod (anti)affinity, host ports,
        hard spread constraints, or claims (_pod_resource_only), and no
        pod on the node carries required anti-affinity (a reprieved
        victim's anti term could otherwise reject the preemptor). Static
        plugins (taints/affinity/name/unschedulable) are victim-independent
        and already vetted by the full-chain maximal-removal check.
        ONE predicate shared with the batched path — a divergence here
        would let the batch skip filters the sequential path runs."""
        return (cls._pod_resource_only(pod)
                and not node_info.pods_with_required_anti_affinity)

    def _select_victims_on_node(self, state, pod: Pod, node_info: NodeInfo,
                                pdbs: list, status_plugin: str = ""):
        """SelectVictimsOnNode (default_preemption.go:207): remove all lower-
        priority pods, check fit, then reprieve as many as possible — PDB-
        violating victims first, then the rest, highest priority first.
        Returns (victims, num_pdb_violations) or None.

        HOT LOOP #3 (preemption.go:408 DryRunPreemption) treatment:
        - a resource necessary-condition check runs BEFORE the node clone +
          full filter chain (maximal removal is the best case — if
          resources still don't fit, nothing can succeed);
        - when re-adding a victim can only move resources
          (_resource_only), the reprieve loop runs NodeResourcesFit's
          arithmetic instead of the full framework chain per victim;
        - and when additionally the node's failure verdict came from
          NodeResourcesFit itself, the maximal-removal full-chain check is
          skipped too — the kernel reports the FIRST failing filter row,
          NodeResourcesFit sits after every row that could apply to this
          pod (_resource_only rules out ports/spread/IPA/features), so
          that verdict proves all static filters pass."""
        fw = self.handle.framework
        lower = [pi for pi in node_info.iter_pods()
                 if pi.pod.spec.priority < pod.spec.priority]
        if not lower:
            return None
        fitp = self._fit_plugin()
        req = used = None
        resource_only = False
        if fitp is not None:
            req = fitp._pod_info(state, pod).request
            width = max(len(req.v), len(node_info.allocatable.v))
            used = [node_info.requested[i] for i in range(width)]
            for pi in lower:
                for i in range(width):
                    used[i] -= pi.request[i]
            if not self._fits_resources(
                fitp, req, node_info, used,
                len(node_info.pods) - len(lower),
            ):
                return None  # necessary condition: skip the clone + chain
            resource_only = self._resource_only(pod, node_info)
        if not (resource_only and status_plugin == fitp.name):
            # static filters not yet proven: run the maximal-removal full
            # chain on a clone (also the reprieve vehicle when plugins
            # beyond NodeResourcesFit can be affected)
            ni = node_info.clone()
            state = state.clone()
            for pi in lower:
                ni.remove_pod(pi.key)
                fw.run_pre_filter_extension_remove_pod(state, pod, pi, ni)
            if not fw.run_filter_plugins(state, pod, ni).is_success:
                return None  # even with all victims gone: no fit
        # MoreImportantPod order: priority desc, then earlier start
        lower.sort(key=lambda pi: (-pi.pod.spec.priority,
                                   pi.pod.meta.creation_timestamp))
        violating, non_violating = self._split_pdb_violation(lower, pdbs)
        victims: list[PodInfo] = []
        num_violations = 0

        if resource_only:
            kept = [len(node_info.pods) - len(lower)]

            def reprieve(pi: PodInfo) -> bool:
                trial = [u + pi.request[i] for i, u in enumerate(used)]
                # +1 for the preemptor itself, on top of kept pods
                if self._fits_resources(fitp, req, node_info, trial,
                                        kept[0] + 1):
                    used[:] = trial
                    kept[0] += 1
                    return True
                victims.append(pi)
                return False
        else:
            def reprieve(pi: PodInfo) -> bool:
                ni.add_pod(pi)
                fw.run_pre_filter_extension_add_pod(state, pod, pi, ni)
                if fw.run_filter_plugins(state, pod, ni).is_success:
                    return True
                ni.remove_pod(pi.key)
                fw.run_pre_filter_extension_remove_pod(state, pod, pi, ni)
                victims.append(pi)
                return False

        for pi in violating:
            if not reprieve(pi):
                num_violations += 1
        for pi in non_violating:
            reprieve(pi)
        if not victims:
            return None
        victims.sort(key=lambda pi: (-pi.pod.spec.priority,
                                     pi.pod.meta.creation_timestamp))
        return victims, num_violations

    # -- batched victim search (HOT LOOP #3 as dense arrays) -----------------

    def _batch_select_victims(self, state, pod: Pod, nodes: list,
                              statuses) -> dict:
        """One numpy pass replacing per-node _select_victims_on_node for
        the nodes where only resources can decide (preemption.go:408
        DryRunPreemption's dominant case, round-3 task: the candidate ×
        victim dry-run as dense victim-removal deltas instead of a python
        loop per candidate).

        Eligible nodes: the pod is _resource_only-safe, the node carries no
        required anti-affinity pods, its failure verdict came from
        NodeResourcesFit, and it HAS lower-priority pods. The greedy
        reprieve (priority desc, earlier start first) runs as a V-step
        vector scan over every eligible node at once — step v asks "does
        re-adding victim v still fit?" for ALL nodes in one [C, R]
        comparison, byte-identical to the sequential loop's arithmetic.

        Returns {node name: (victims, 0) | None}; nodes it does not decide
        are absent (caller falls back per node). PDBs present → batch off
        (the reprieve ORDER depends on per-victim PDB budgets)."""
        import numpy as np

        fitp = self._fit_plugin()
        if fitp is None:
            return {}
        req_vec = fitp._pod_info(state, pod).request
        if not self._pod_resource_only(pod):
            return {}
        eligible: list = []
        victim_lists: list[list[PodInfo]] = []
        vmax = 0
        prio = pod.spec.priority
        cache = self._victim_cache
        bulk_fit = getattr(statuses, "fit_verdict_names", None)
        fit_names = bulk_fit() if bulk_fit is not None else None
        for ni in nodes:
            if ni.pods_with_required_anti_affinity:
                continue
            if fit_names is not None:
                if ni.name not in fit_names:
                    continue
            elif statuses.get(ni.name).plugin != fitp.name:
                continue
            # sorted victim lists are stable per (node generation, preemptor
            # priority): consecutive preemptors of one priority class reuse
            # them instead of re-walking + re-sorting every node's pods
            ck = (ni.name, ni.generation, prio)
            lower = cache.get(ck)
            if lower is None:
                lower = [pi for pi in ni.iter_pods()
                         if pi.pod.spec.priority < prio]
                # MoreImportantPod order: reprieve tries high priority first
                lower.sort(key=lambda pi: (-pi.pod.spec.priority,
                                           pi.pod.meta.creation_timestamp))
                if len(cache) > 20000:
                    cache.clear()
                cache[ck] = lower
            if not lower:
                continue
            eligible.append(ni)
            victim_lists.append(lower)
            vmax = max(vmax, len(lower))
        if not eligible:
            return {}
        C = len(eligible)
        width = max(
            max(len(ni.allocatable.v) for ni in eligible),
            len(req_vec.v),
        )
        from ...api.resource import PODS

        def vec(v):
            return list(v) + [0] * (width - len(v))

        req = np.asarray(vec(req_vec.v), dtype=np.int64)
        # ignored resources and the PODS column are excluded from the
        # per-resource comparison (exactly _fits_resources)
        active = req > 0
        for i in range(width):
            name = (fitp.names.names[i] if i < fitp.names.width
                    else f"res{i}")
            if name in fitp.ignored:
                active[i] = False
        active[PODS] = False
        alloc = np.asarray([vec(ni.allocatable.v) for ni in eligible],
                           dtype=np.int64)
        used = np.asarray([vec(ni.requested.v) for ni in eligible],
                          dtype=np.int64)
        vreq = np.zeros((C, vmax, width), dtype=np.int64)
        vactive = np.zeros((C, vmax), dtype=bool)
        for c, lower in enumerate(victim_lists):
            for v, pi in enumerate(lower):
                vreq[c, v] = vec(pi.request.v)
                vactive[c, v] = True
        # maximal removal: all lower-priority pods gone
        used = used - vreq.sum(axis=1)
        kept = np.asarray([len(ni.pods) - len(lv)
                           for ni, lv in zip(eligible, victim_lists)],
                          dtype=np.int64)
        pods_cap = alloc[:, PODS]

        req_a = req[active][None, :]
        alloc_a = alloc[:, active]

        def fits(u, k):
            res_ok = (req_a <= alloc_a - u[:, active]).all(axis=1)
            return res_ok & (k + 1 <= pods_cap)

        feasible = fits(used, kept)
        # greedy reprieve scan: step v re-adds victim v where it fits
        victim_mask = np.zeros((C, vmax), dtype=bool)
        for v in range(vmax):
            trial = used + vreq[:, v]
            ok = fits(trial, kept + 1) & vactive[:, v] & feasible
            used = np.where(ok[:, None], trial, used)
            kept = kept + ok
            victim_mask[:, v] = vactive[:, v] & ~ok & feasible
        out: dict = {}
        for c, (ni, lower) in enumerate(zip(eligible, victim_lists)):
            if not feasible[c]:
                out[ni.name] = None
                continue
            victims = [pi for v, pi in enumerate(lower)
                       if victim_mask[c, v]]
            if not victims:
                out[ni.name] = None
                continue
            victims.sort(key=lambda pi: (-pi.pod.spec.priority,
                                         pi.pod.meta.creation_timestamp))
            out[ni.name] = (victims, 0)
        return out

    @staticmethod
    def _pod_resource_only(pod: Pod) -> bool:
        """The pod-level half of _resource_only (node-independent).
        NodeDeclaredFeatures sits BEFORE NodeResourcesFit in the host chain
        but has no kernel row — a kernel NodeResourcesFit verdict cannot
        prove it passed, so a features-requiring pod must take the
        full-chain path."""
        from ...api.storage import pod_claim_names

        aff = pod.spec.affinity
        if aff is not None and (aff.pod_affinity is not None
                                or aff.pod_anti_affinity is not None):
            return False
        if any(p.host_port > 0 for c in pod.spec.containers
               for p in c.ports):
            return False
        if any(c.when_unsatisfiable == "DoNotSchedule"
               for c in pod.spec.topology_spread_constraints):
            return False
        if pod_claim_names(pod) or pod.spec.resource_claims:
            return False
        from .node_declared_features import infer_required_features

        if infer_required_features(pod):
            return False
        return True

    # -- candidate sampling + ranking ----------------------------------------

    def _num_candidates(self, num_nodes: int) -> int:
        """GetOffsetAndNumCandidates (preemption.go:174-191)."""
        n = num_nodes * MIN_CANDIDATE_NODES_PERCENTAGE // 100
        n = max(n, MIN_CANDIDATE_NODES_ABSOLUTE)
        return min(n, num_nodes)

    @staticmethod
    def _candidate_rank(c: _Candidate):
        """pickOneNodeForPreemption criteria (preemption.go:302-360), all
        minimized: PDB violations, highest victim priority, priority sum,
        victim count, then earliest victim start time (prefer nodes whose
        highest-priority victim started LATEST => minimize -start)."""
        priorities = [v.pod.spec.priority for v in c.victims]
        top = max(priorities, default=-(1 << 31))
        latest_start = max(
            (v.pod.meta.creation_timestamp for v in c.victims
             if v.pod.spec.priority == top), default=0.0
        )
        return (
            c.num_pdb_violations,
            top,
            sum(priorities),
            len(c.victims),
            -latest_start,
        )

    # -- post filter -----------------------------------------------------------

    def post_filter(self, state, pod: Pod, node_to_status):
        if not self._eligible(pod):
            return None, Status.unresolvable(
                "preemption not allowed for this pod", plugin=self.name
            )
        snapshot = self.handle.snapshot
        pdbs = self._list_pdbs()
        nodes = snapshot.list_nodes()
        num_all = len(nodes)
        want = self._num_candidates(num_all)
        candidates: list[_Candidate] = []
        # rotating offset (the reference randomizes; a rotating cursor gives
        # the same fairness deterministically)
        start = self._offset % num_all if num_all else 0
        rotation = [nodes[(start + i) % num_all] for i in range(num_all)]
        # batched dry-run for the resource-only nodes (one numpy pass over
        # every candidate); outcomes match the per-node path exactly, so
        # scan order / early exit / offset bookkeeping below are unchanged.
        # PDBs present → reprieve order depends on per-victim budgets, so
        # everything takes the per-node path.
        # bulk UNSCHEDULABLE mask when the statuses are kernel-backed (one
        # vectorized pass instead of a Status per scanned node)
        bulk = getattr(node_to_status, "unschedulable_name_set", None)
        unsched_names = bulk() if bulk is not None else None

        def _retriable(name: str) -> bool:
            if unsched_names is not None:
                return name in unsched_names
            return node_to_status.get(name).code == UNSCHEDULABLE

        batched: dict = {}
        if not pdbs:
            # the sequential scan stops at `want` candidates, so batching
            # more than ~want nodes is wasted work (nearly every node is a
            # candidate in preemption-heavy workloads); the tail past the
            # cap falls back per node in the rare under-supply case
            cap = min(num_all, 2 * want)
            batched = self._batch_select_victims(
                state, pod,
                [ni for ni in rotation[:cap] if _retriable(ni.name)],
                node_to_status,
            )
        scanned = 0
        for ni in rotation:
            scanned += 1
            if not _retriable(ni.name):
                continue  # UnschedulableAndUnresolvable can't be fixed by eviction
            if ni.name in batched:
                found = batched[ni.name]
            else:
                found = self._select_victims_on_node(
                    state, pod, ni, pdbs,
                    status_plugin=node_to_status.get(ni.name).plugin,
                )
            if found is not None:
                victims, violations = found
                candidates.append(_Candidate(ni.name, victims, violations))
                if len(candidates) >= want:
                    break
        self._offset = (start + scanned) % num_all if num_all else 0
        if not candidates:
            return None, Status.unschedulable(
                "preemption: 0/%d nodes are available" % num_all,
                plugin=self.name,
            )
        best = min(candidates, key=self._candidate_rank)
        # nomination is synchronous (the scheduling cycle needs it); victim
        # eviction + nomination cleanup run via the executor — off the loop
        # when the async dispatcher is available (executor.go:145)
        PreemptionExecutor(self.handle).prepare_candidate(best, pod, pdbs)
        return (
            PostFilterResult(nominated_node_name=best.node_name),
            Status(),
        )
