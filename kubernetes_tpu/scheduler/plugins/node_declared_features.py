"""NodeDeclaredFeatures: pods land only on nodes declaring the features
their spec depends on.

Reference: pkg/scheduler/framework/plugins/nodedeclaredfeatures/
(PreFilter infers the pod's required feature set from its spec via
component-helpers/nodedeclaredfeatures, Filter checks it is a subset of
NodeInfo.GetNodeDeclaredFeatures(); empty requirement set skips). The
reference's inference framework derives requirements from spec shapes
(e.g. pod-level resources); ours mirrors that with an inference table over
the spec fields this framework models, plus the explicit
`features.k8s.io/required` annotation as the extensible hook.
"""

from __future__ import annotations

from ...api.types import Pod
from ..framework import events as ev
from ..framework.events import ClusterEvent, ClusterEventWithHint, QUEUE, QUEUE_SKIP
from ..framework.interface import Plugin, Status

REQUIRED_FEATURES_ANNOTATION = "features.k8s.io/required"
_ERR_REASON = "node(s) didn't match Pod's required features"

STATE_KEY = "PreFilterNodeDeclaredFeatures"


def infer_required_features(pod: Pod) -> frozenset[str]:
    """InferForPodScheduling: spec shapes → feature names the node must
    declare. The reference infers from spec fields with node-side feature
    dependencies (e.g. pod-level resources); none of the spec fields this
    framework models carries one yet, so the inference table is currently
    the explicit annotation alone — extend it as fields gain dependencies
    (resource claims deliberately do NOT require a declared feature: device
    fit is the DRA plugin's job, as in the reference)."""
    ann = pod.meta.annotations.get(REQUIRED_FEATURES_ANNOTATION, "")
    if not ann:
        return frozenset()
    return frozenset(f.strip() for f in ann.split(",") if f.strip())


class NodeDeclaredFeatures(Plugin):
    name = "NodeDeclaredFeatures"

    def events_to_register(self):
        def node_hint(pod, old, new):
            if new is None:
                return QUEUE_SKIP
            reqs = infer_required_features(pod)
            declared = set(new.status.declared_features)
            return QUEUE if reqs <= declared else QUEUE_SKIP

        return [ClusterEventWithHint(
            ClusterEvent(ev.NODE, ev.ADD | ev.UPDATE), node_hint
        )]

    def pre_filter(self, state, pod: Pod, nodes):
        reqs = infer_required_features(pod)
        if not reqs:
            return None, Status.skip()
        state.write(STATE_KEY, reqs)
        return None, Status()

    def filter(self, state, pod: Pod, node_info) -> Status:
        reqs = state.read(STATE_KEY)
        if not reqs:
            return Status()
        declared = set(node_info.node.status.declared_features)
        if not (reqs <= declared):
            return Status.unresolvable(_ERR_REASON, plugin=self.name)
        return Status()

    def sign(self, pod: Pod) -> str | None:
        return ",".join(sorted(infer_required_features(pod)))
