"""NodeAffinity plugin: required selector filter + preferred-term scoring.

Reference: pkg/scheduler/framework/plugins/nodeaffinity/node_affinity.go
(PreFilter:159 with single-node fast path, Filter:218, Score:272).
"""

from __future__ import annotations

from ...api.types import Pod
from ..framework import events as ev
from ..framework.events import ClusterEvent, ClusterEventWithHint
from ..framework.interface import Plugin, PreFilterResult, Status
from ..nodeinfo import NodeInfo

_FIELD_HOSTNAME = "metadata.name"


def _node_fields(node) -> dict[str, str]:
    return {_FIELD_HOSTNAME: node.meta.name}


def _required_matches(pod: Pod, node) -> bool:
    # spec.nodeSelector: all labels must match
    for k, v in pod.spec.node_selector.items():
        if node.meta.labels.get(k) != v:
            return False
    aff = pod.spec.affinity
    if aff and aff.node_affinity and aff.node_affinity.required is not None:
        return aff.node_affinity.required.matches(node.meta.labels, _node_fields(node))
    return True


class NodeAffinity(Plugin):
    name = "NodeAffinity"
    PRE_SCORE_KEY = "PreScoreNodeAffinity"

    def __init__(self, added_affinity=None):
        # per-profile AddedAffinity (NodeAffinityArgs)
        self.added_affinity = added_affinity

    def events_to_register(self):
        return [ClusterEventWithHint(ClusterEvent(ev.NODE, ev.ADD | ev.UPDATE_NODE_LABEL))]

    def pre_filter(self, state, pod: Pod, nodes):
        """Single-node-name fast path: In(metadata.name, [n]) narrows the node
        set without touching other nodes (node_affinity.go:159)."""
        aff = pod.spec.affinity
        has_required = (
            aff is not None
            and aff.node_affinity is not None
            and aff.node_affinity.required is not None
        )
        if not pod.spec.node_selector and not has_required:
            return None, Status.skip()
        if has_required:
            terms = aff.node_affinity.required.terms
            node_names: set[str] | None = set()
            for term in terms:
                term_names = None
                for req in term.match_fields:
                    if req.key == _FIELD_HOSTNAME and req.operator == "In":
                        term_names = set(req.values)
                if term_names is None:
                    node_names = None  # this OR-branch matches arbitrary nodes
                    break
                node_names |= term_names
            if node_names is not None:
                return PreFilterResult(node_names), Status()
        return None, Status()

    def filter(self, state, pod: Pod, node_info: NodeInfo) -> Status:
        node = node_info.node
        if node is None:
            return Status.unschedulable("node not found", plugin=self.name)
        if self.added_affinity is not None and not self.added_affinity.matches(
            node.meta.labels, _node_fields(node)
        ):
            return Status.unresolvable(
                "node(s) didn't match scheduler-enforced node affinity", plugin=self.name
            )
        if not _required_matches(pod, node):
            return Status.unresolvable(
                "node(s) didn't match Pod's node affinity/selector", plugin=self.name
            )
        return Status()

    def pre_score(self, state, pod: Pod, nodes) -> Status:
        aff = pod.spec.affinity
        preferred = (
            list(aff.node_affinity.preferred)
            if aff and aff.node_affinity
            else []
        )
        if not preferred:
            return Status.skip()
        state.write(self.PRE_SCORE_KEY, preferred)
        return Status()

    def score(self, state, pod: Pod, node_info: NodeInfo):
        preferred = state.read(self.PRE_SCORE_KEY) or []
        node = node_info.node
        if node is None:
            return 0, Status()
        total = 0
        for term in preferred:
            if term.preference.matches(node.meta.labels, _node_fields(node)):
                total += term.weight
        return total, Status()

    def normalize_score(self, state, pod: Pod, scores) -> Status:
        from ..framework.interface import MAX_NODE_SCORE

        max_score = max((s for _, s in scores), default=0)
        if max_score == 0:
            return Status()
        for row in scores:
            row[1] = row[1] * MAX_NODE_SCORE // max_score
        return Status()

    def sign(self, pod: Pod) -> str | None:
        """Canonical fragment for pod signatures (signers.go)."""
        parts = [f"{k}={v}" for k, v in sorted(pod.spec.node_selector.items())]
        aff = pod.spec.affinity
        if aff and aff.node_affinity:
            parts.append(repr(aff.node_affinity))
        return ";".join(parts)
