"""NodeResources plugins: Fit filter + scoring strategies + BalancedAllocation.

Reference: pkg/scheduler/framework/plugins/noderesources/ — fitsRequest
(fit.go:673-760), LeastAllocated (least_allocated.go:30-52), MostAllocated
(most_allocated.go:30-54), RequestedToCapacityRatio
(requested_to_capacity_ratio.go:31-60), BalancedAllocation
(balanced_allocation.go:204-230), shared scorer resource_allocation.go.

All fit/score arithmetic is integer on plane units, except BalancedAllocation
which is defined as float32 with a fixed op order (host numpy float32 ==
device XLA float32) so host and TPU paths agree bit-for-bit. These formulas
are the canonical spec for the dense kernels in ops/kernels.py — any change
here must be mirrored there (golden tests enforce it).
"""

from __future__ import annotations

import numpy as np

from ...api.resource import CPU, MEM, PODS, ResourceNames, ResourceVec
from ...api.types import Pod
from ..framework import events as ev
from ..framework.events import ClusterEvent, ClusterEventWithHint, QUEUE, QUEUE_SKIP
from ..framework.interface import MAX_NODE_SCORE, Plugin, Status
from ..nodeinfo import NodeInfo, PodInfo

LEAST_ALLOCATED = "LeastAllocated"
MOST_ALLOCATED = "MostAllocated"
REQUESTED_TO_CAPACITY_RATIO = "RequestedToCapacityRatio"

DEFAULT_RESOURCE_WEIGHTS = {"cpu": 1, "memory": 1}


class NodeResourcesFit(Plugin):
    name = "NodeResourcesFit"
    PRE_FILTER_KEY = "PreFilterNodeResourcesFit"

    def __init__(
        self,
        names: ResourceNames,
        scoring_strategy: str = LEAST_ALLOCATED,
        resource_weights: dict[str, int] | None = None,
        shape: list[tuple[int, int]] | None = None,
        ignored_resources: set[str] | None = None,
    ):
        self.names = names
        self.strategy = scoring_strategy
        self.resource_weights = dict(resource_weights or DEFAULT_RESOURCE_WEIGHTS)
        # RequestedToCapacityRatio shape: (utilization%, score) breakpoints
        self.shape = sorted(shape or [(0, 0), (100, MAX_NODE_SCORE)])
        self.ignored = ignored_resources or set()
        self.handle = None  # wired by the scheduler (ScorePlacement needs it)

    def set_handle(self, handle) -> None:
        self.handle = handle

    # -- events ------------------------------------------------------------

    def events_to_register(self):
        def pod_deleted_hint(pod, old, new):
            return QUEUE if new is None or new.is_terminating else QUEUE_SKIP

        def scale_down_hint(pod, old, new):
            """Requeue when any pod (including the pending pod itself) lowered
            its requests (fit.go isSchedulableAfterPodChange)."""
            if new is None:
                return QUEUE
            if old is None:
                return QUEUE_SKIP
            old_req = PodInfo(old, self.names).request
            new_req = PodInfo(new, self.names).request
            shrank = any(n < o for o, n in zip(old_req.v, new_req.v))
            return QUEUE if shrank else QUEUE_SKIP

        return [
            ClusterEventWithHint(ClusterEvent(ev.ASSIGNED_POD, ev.DELETE), pod_deleted_hint),
            ClusterEventWithHint(
                ClusterEvent(ev.NODE, ev.ADD | ev.UPDATE_NODE_ALLOCATABLE)
            ),
            # resource POD (not just AssignedPod): a pending pod scaling down
            # its own request must retrigger itself
            ClusterEventWithHint(ClusterEvent(ev.POD, ev.UPDATE_POD_SCALE_DOWN), scale_down_hint),
        ]

    # -- prefilter / filter -------------------------------------------------

    def pre_filter(self, state, pod: Pod, nodes):
        """Precompute the request vector once per cycle (fit.go:317)."""
        pi = PodInfo(pod, self.names)
        state.write(self.PRE_FILTER_KEY, pi)
        return None, Status()

    def _pod_info(self, state, pod: Pod) -> PodInfo:
        pi = state.read(self.PRE_FILTER_KEY)
        if pi is None or pi.pod is not pod:
            pi = PodInfo(pod, self.names)
        return pi

    def filter(self, state, pod: Pod, node_info: NodeInfo) -> Status:
        """fitsRequest (fit.go:673-760): for every resource,
        request <= allocatable - requested; plus pod-count slot."""
        pi = self._pod_info(state, pod)
        req, alloc, used = pi.request, node_info.allocatable, node_info.requested
        reasons = []
        if len(node_info.pods) + 1 > alloc[PODS]:
            reasons.append("Too many pods")
        width = max(len(req.v), len(alloc.v))
        for i in range(width):
            r = req[i]
            if r == 0 or i == PODS:
                continue
            rname = self.names.names[i] if i < self.names.width else f"res{i}"
            if rname in self.ignored:
                continue
            if r > alloc[i] - used[i]:
                reasons.append(f"Insufficient {rname}")
        if reasons:
            return Status.unschedulable(*reasons, plugin=self.name)
        return Status()

    # -- scoring ------------------------------------------------------------

    def _score_resources(self, pi: PodInfo, node_info: NodeInfo) -> int:
        """resource_allocation.go score: weighted mean of per-resource scores.

        requested includes the incoming pod; cpu/mem use NonZero values.
        """
        total_weight = 0
        total_score = 0
        for rname, weight in self.resource_weights.items():
            i = self.names.get(rname)
            if i is None:
                continue
            alloc = node_info.allocatable[i]
            if alloc <= 0:
                continue
            if i in (CPU, MEM):
                requested = node_info.nonzero_requested[i] + pi.nonzero_request[i]
            else:
                requested = node_info.requested[i] + pi.request[i]
            if requested > alloc:
                requested = alloc
            total_weight += weight
            total_score += self._strategy_score(requested, alloc) * weight
        if total_weight == 0:
            return 0
        return total_score // total_weight

    def _strategy_score(self, requested: int, capacity: int) -> int:
        if self.strategy == LEAST_ALLOCATED:
            # least_allocated.go:30-52 — ((capacity-requested)*100)/capacity
            return (capacity - requested) * MAX_NODE_SCORE // capacity
        if self.strategy == MOST_ALLOCATED:
            # most_allocated.go — (requested*100)/capacity
            return requested * MAX_NODE_SCORE // capacity
        # RequestedToCapacityRatio: piecewise-linear over utilization%
        util = requested * 100 // capacity
        shape = self.shape
        if util <= shape[0][0]:
            return shape[0][1]
        for (x0, y0), (x1, y1) in zip(shape, shape[1:]):
            if util <= x1:
                if x1 == x0:
                    return y1
                return y0 + (y1 - y0) * (util - x0) // (x1 - x0)
        return shape[-1][1]

    def score(self, state, pod: Pod, node_info: NodeInfo):
        return self._score_resources(self._pod_info(state, pod), node_info), Status()

    # -- signatures + gang placement scoring --------------------------------

    def sign(self, pod: Pod) -> str | None:
        pi = PodInfo(pod, self.names)
        return ",".join(str(x) for x in pi.request.v)

    def score_placement(self, state, pods, placement):
        """fit.go:789 ScorePlacement — aggregate gang request vs placement
        free capacity using the strategy score."""
        total_req = ResourceVec(self.names.width)
        for pod in pods:
            total_req.add(PodInfo(pod, self.names).request)
        total_alloc = ResourceVec(self.names.width)
        total_used = ResourceVec(self.names.width)
        snapshot = self.handle.snapshot if self.handle is not None else None
        for name in placement.node_names:
            ni = snapshot.get(name) if snapshot is not None else None
            if ni is None:
                continue
            total_alloc.add(ni.allocatable)
            total_used.add(ni.requested)
        score = 0
        weight_sum = 0
        for rname, weight in self.resource_weights.items():
            i = self.names.get(rname)
            if i is None or total_alloc[i] <= 0:
                continue
            requested = min(total_used[i] + total_req[i], total_alloc[i])
            score += self._strategy_score(requested, total_alloc[i]) * weight
            weight_sum += weight
        return (score // weight_sum if weight_sum else 0), Status()


class BalancedAllocation(Plugin):
    """balanced_allocation.go — favor nodes whose per-resource utilization
    fractions are close together: score = (1 - stddev(fractions)) * 100.

    Float32 with fixed op order; mirrored exactly by the device kernel.
    """

    name = "NodeResourcesBalancedAllocation"
    PRE_SCORE_KEY = "PreScoreBalancedAllocation"

    def __init__(self, names: ResourceNames, resources: list[str] | None = None):
        self.names = names
        self.resources = resources or ["cpu", "memory"]

    def pre_score(self, state, pod: Pod, nodes) -> Status:
        state.write(self.PRE_SCORE_KEY, PodInfo(pod, self.names))
        return Status()

    def score(self, state, pod: Pod, node_info: NodeInfo):
        pi = state.read(self.PRE_SCORE_KEY)
        if pi is None or pi.pod is not pod:
            pi = PodInfo(pod, self.names)
        fracs = []
        for rname in self.resources:
            i = self.names.get(rname)
            if i is None:
                continue
            alloc = node_info.allocatable[i]
            if alloc <= 0:
                continue
            if i in (CPU, MEM):
                requested = node_info.nonzero_requested[i] + pi.nonzero_request[i]
            else:
                requested = node_info.requested[i] + pi.request[i]
            frac = np.float32(requested) / np.float32(alloc)
            fracs.append(min(frac, np.float32(1.0)))
        if len(fracs) < 2:
            return 0, Status()
        arr = np.array(fracs, dtype=np.float32)
        mean = arr.sum(dtype=np.float32) / np.float32(len(arr))
        var = ((arr - mean) ** 2).sum(dtype=np.float32) / np.float32(len(arr))
        std = np.sqrt(var, dtype=np.float32)
        score = int((np.float32(1.0) - std) * np.float32(MAX_NODE_SCORE))
        return score, Status()
