"""Volume plugins: VolumeBinding (+ binder), VolumeRestrictions, VolumeZone,
NodeVolumeLimits.

Reference: pkg/scheduler/framework/plugins/volumebinding/ (volume_binding.go
PreFilter:360 Filter:424 Score:471 Reserve:531 PreBind:577 Unreserve:604;
binder.go FindPodVolumes/AssumePodVolumes/BindPodVolumes),
volumerestrictions/volume_restrictions.go:318 (ReadWriteOncePod conflicts),
volumezone/volume_zone.go:198 (PV zone-label vs node-label match), and
nodevolumelimits/csi.go:257 (CSI attach-limit counting).

TPU-first note: these are the "long tail" host-side plugins (SURVEY.md §7 —
sparse store lookups, tiny cardinalities). They compose with the dense device
kernel through the same framework API; only their Skip/Unschedulable verdicts
gate the kernel's candidate mask.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ...api.storage import (
    CLAIM_BOUND,
    NO_PROVISIONER,
    READ_WRITE_ONCE_POD,
    VOLUME_BOUND,
    ZONE_LABELS,
    PersistentVolume,
    PersistentVolumeClaim,
    StorageClass,
    pod_claim_names,
)
from ...api.types import Pod
from ..framework import events as ev
from ..framework.events import ClusterEvent, ClusterEventWithHint, QUEUE
from ..framework.interface import MAX_NODE_SCORE, Plugin, Status
from ..nodeinfo import NodeInfo

ERR_REASON_NOT_FOUND = "persistentvolumeclaim not found"
ERR_REASON_UNBOUND_IMMEDIATE = "pod has unbound immediate PersistentVolumeClaims"
ERR_REASON_NODE_CONFLICT = "node(s) had volume node affinity conflict"
ERR_REASON_BIND_CONFLICT = "node(s) didn't find available persistent volumes to bind"
ERR_REASON_RWOP_CONFLICT = (
    "node has pod using PersistentVolumeClaim with the same name and "
    "ReadWriteOncePod access mode"
)
ERR_REASON_ZONE_CONFLICT = "node(s) had no available volume zone"
ERR_REASON_MAX_VOLUME_COUNT = "node(s) exceed max volume count"


def _pvc_key(namespace: str, name: str) -> str:
    return f"{namespace}/{name}"


def _owned_by_pod(pvc, pod: Pod) -> bool:
    """component-helpers/storage/ephemeral VolumeIsForPod: the claim must be
    controller-owned by this pod."""
    return any(
        ref.kind == "Pod" and ref.name == pod.meta.name and ref.controller
        for ref in pvc.meta.owner_references
    )


# --- binder ----------------------------------------------------------------


@dataclass
class PodVolumes:
    """Per-(pod,node) binding decision (volumebinding PodVolumes)."""

    static_bindings: list[tuple[str, str]] = field(default_factory=list)  # (pv, pvc key)
    dynamic_provisions: list[str] = field(default_factory=list)  # pvc keys


@dataclass
class _ClaimsToBind:
    bound: list[PersistentVolumeClaim] = field(default_factory=list)
    unbound_delayed: list[PersistentVolumeClaim] = field(default_factory=list)


class VolumeBinder:
    """Topology-aware PV/PVC matcher + two-phase binding against the store.

    Reference: volumebinding/binder.go — FindPodVolumes enumerates candidate
    static PVs per node, AssumePodVolumes reserves them in an assume-cache,
    BindPodVolumes performs the API writes. In this single-process control
    plane the "PV controller wait" collapses to a direct store transaction.
    """

    def __init__(self, store):
        self.store = store
        # pv key -> pvc key reserved in-memory ahead of the PreBind API write
        self.assumed: dict[str, str] = {}

    # -- lookups ------------------------------------------------------------

    def get_claims(self, pod: Pod) -> tuple[_ClaimsToBind | None, Status | None]:
        """Split the pod's claims into bound / unbound-delayed; error statuses
        mirror volume_binding.go PreFilter:360."""
        out = _ClaimsToBind()
        ephemeral_claims = {
            v.claim_name(pod.meta.name)
            for v in pod.spec.volumes
            if v.ephemeral and not v.persistent_volume_claim
        }
        for name in pod_claim_names(pod):
            pvc = self.store.try_get(
                "PersistentVolumeClaim", _pvc_key(pod.meta.namespace, name)
            )
            if pvc is None:
                return None, Status.unresolvable(
                    f'{ERR_REASON_NOT_FOUND} "{name}"', plugin=VolumeBinding.name
                )
            if name in ephemeral_claims and not _owned_by_pod(pvc, pod):
                # ephemeral.VolumeIsForPod — a same-named foreign claim must
                # not be adopted by naming coincidence
                return None, Status.unresolvable(
                    f'PVC "{name}" was not created for pod "{pod.meta.name}"',
                    plugin=VolumeBinding.name,
                )
            if pvc.is_bound:
                out.bound.append(pvc)
                continue
            sc = self.store.try_get("StorageClass", pvc.spec.storage_class_name)
            if sc is not None and sc.is_wait_for_first_consumer:
                out.unbound_delayed.append(pvc)
            else:
                return None, Status.unresolvable(
                    ERR_REASON_UNBOUND_IMMEDIATE, plugin=VolumeBinding.name
                )
        return out, None

    def _pv_available(self, pv: PersistentVolume, pvc) -> bool:
        claimed = pv.spec.claim_ref or self.assumed.get(pv.meta.key, "")
        if claimed == "":
            return True
        if claimed != pvc.meta.key:
            return False
        # same key: the claimRef.uid must match the claim INSTANCE — a PV
        # still referencing a deleted-and-recreated same-named claim is
        # awaiting reclaim, not available (pv_controller.go uid check)
        return (not pv.spec.claim_ref_uid
                or pv.spec.claim_ref_uid == pvc.meta.uid)

    def _pv_matches(self, pv: PersistentVolume, pvc: PersistentVolumeClaim,
                    node_info: NodeInfo) -> bool:
        """pv_util CheckVolumeModeMismatches + FindMatchingVolume conditions."""
        if pv.spec.storage_class_name != pvc.spec.storage_class_name:
            return False
        if not set(pvc.spec.access_modes) <= set(pv.spec.access_modes):
            return False
        if pv.storage_capacity < pvc.requested_storage:
            return False
        return self.pv_fits_node(pv, node_info)

    def pv_fits_node(self, pv: PersistentVolume, node_info: NodeInfo) -> bool:
        if pv.spec.node_affinity is None:
            return True
        node = node_info.node
        return pv.spec.node_affinity.matches(node.meta.labels, {"metadata.name": node.meta.name})

    def list_candidate_pvs(self) -> list[PersistentVolume]:
        """One sorted PV listing per scheduling cycle (computed at PreFilter,
        reused by every per-node Filter — the input is node-independent).
        Shared refs, read-only: a deepcopy of every PV per pod cycle was
        the profile's top cost at 5k nodes."""
        pv_list = self.store.list_refs("PersistentVolume")
        # deterministic smallest-fit-first order (pv_util sorts by size)
        return sorted(pv_list, key=lambda p: (p.storage_capacity, p.meta.name))

    def candidates_for_claims(self, claims: _ClaimsToBind,
                              pv_list: list[PersistentVolume]) -> dict:
        """Per-claim availability prefilter (node-independent half of
        FindMatchingVolume): drops PVs bound to other claims once per cycle
        so the per-node scan touches only genuinely available volumes."""
        return {
            pvc.meta.key: [pv for pv in pv_list
                           if self._pv_available(pv, pvc)]
            for pvc in claims.unbound_delayed
        }

    def node_neutral_volumes(self, pod: Pod) -> PodVolumes | None:
        """The pod's volume decision when it provably CANNOT depend on the
        node — the batched wave's eligibility check (the wave kernel can't
        run per-node host plugins, so only pods whose entire volume stage
        is node-invariant may ride it). Returns None whenever any volume
        plugin would need per-node evaluation or the decision would fail
        (the hybrid path then produces the right status):

        - bound claims: PV must exist, carry no node affinity, no zone
          labels (VolumeZone), no CSI driver (NodeVolumeLimits)
        - no ReadWriteOncePod access modes anywhere (VolumeRestrictions)
        - each unbound WFFC claim's FIRST matching available candidate must
          be unpinned/zone-free/non-CSI — then every node chooses that same
          volume, so Filter passes everywhere and Score is a constant shift
          that cannot move the argmax or its tie set — or there must be a
          provisionable class (provisioning pins the new PV only AFTER node
          selection)."""
        claims, err = self.get_claims(pod)
        if err is not None or claims is None:
            return None
        volumes = PodVolumes()
        for pvc in claims.bound:
            if READ_WRITE_ONCE_POD in pvc.spec.access_modes:
                return None
            pv = self.store.try_get("PersistentVolume", pvc.spec.volume_name)
            if (pv is None or pv.spec.node_affinity is not None
                    or pv.spec.csi_driver
                    or any(k in pv.meta.labels for k in ZONE_LABELS)):
                return None
        pv_list = None
        taken: set[str] = set()
        for pvc in claims.unbound_delayed:
            if READ_WRITE_ONCE_POD in pvc.spec.access_modes:
                return None
            if pv_list is None:
                pv_list = self.list_candidate_pvs()
            chosen = None
            for pv in pv_list:
                if pv.meta.key in taken:
                    continue
                if not self._pv_available(pv, pvc):
                    continue
                if pv.spec.storage_class_name != pvc.spec.storage_class_name:
                    continue
                if not set(pvc.spec.access_modes) <= set(pv.spec.access_modes):
                    continue
                if pv.storage_capacity < pvc.requested_storage:
                    continue
                # the first otherwise-matching candidate decides: if it is
                # node-dependent in any way, per-node choices can diverge
                if (pv.spec.node_affinity is not None or pv.spec.csi_driver
                        or any(k in pv.meta.labels for k in ZONE_LABELS)):
                    return None
                chosen = pv
                break
            if chosen is not None:
                taken.add(chosen.meta.key)
                volumes.static_bindings.append(
                    (chosen.meta.key, pvc.meta.key)
                )
                continue
            sc = self.store.try_get(
                "StorageClass", pvc.spec.storage_class_name
            )
            if sc is None or sc.provisioner == NO_PROVISIONER:
                return None  # BIND_CONFLICT everywhere: hybrid reports it
            volumes.dynamic_provisions.append(pvc.meta.key)
        return volumes

    def find_pod_volumes(
        self,
        pod: Pod,
        claims: _ClaimsToBind,
        node_info: NodeInfo,
        pv_list: list[PersistentVolume] | None = None,
        by_claim: dict[str, list] | None = None,
        bound_pvs: list | None = None,
    ) -> tuple[PodVolumes, list[str]]:
        """binder.go FindPodVolumes — returns (decision, conflict reasons)."""
        reasons: list[str] = []
        volumes = PodVolumes()
        if bound_pvs is None:
            bound_pvs = [
                (pvc, self.store.try_get("PersistentVolume",
                                         pvc.spec.volume_name))
                for pvc in claims.bound
            ]
        for pvc, pv in bound_pvs:
            if pv is None or not self.pv_fits_node(pv, node_info):
                reasons.append(ERR_REASON_NODE_CONFLICT)
                return volumes, reasons
        for pvc in claims.unbound_delayed:
            if by_claim is not None:
                # availability-prefiltered at PreFilter (node-independent):
                # the per-node scan must not re-walk every already-bound PV
                # — at scale that was O(boundPVs × nodes) per pod
                cands = by_claim.get(pvc.meta.key, ())
            else:
                if pv_list is None:
                    pv_list = self.list_candidate_pvs()
                cands = pv_list
            chosen = None
            taken = {pv for pv, _ in volumes.static_bindings}
            for pv in cands:
                if pv.meta.key in taken:
                    continue
                if self._pv_available(pv, pvc) and self._pv_matches(
                    pv, pvc, node_info
                ):
                    chosen = pv
                    break
            if chosen is not None:
                volumes.static_bindings.append((chosen.meta.key, pvc.meta.key))
                continue
            sc = self.store.try_get("StorageClass", pvc.spec.storage_class_name)
            if sc is not None and sc.provisioner != NO_PROVISIONER:
                volumes.dynamic_provisions.append(pvc.meta.key)
            else:
                reasons.append(ERR_REASON_BIND_CONFLICT)
                return volumes, reasons
        return volumes, reasons

    # -- assume / bind / revert ---------------------------------------------

    def assume_pod_volumes(self, volumes: PodVolumes) -> None:
        for pv_key, pvc_key in volumes.static_bindings:
            self.assumed[pv_key] = pvc_key

    def revert_assumed_pod_volumes(self, volumes: PodVolumes) -> None:
        for pv_key, _ in volumes.static_bindings:
            self.assumed.pop(pv_key, None)

    def bind_pod_volumes(self, pod: Pod, volumes: PodVolumes,
                         node_name: str = "") -> Status:
        """binder.go BindPodVolumes — PV.claimRef + PVC.volumeName API writes
        (the reference then waits for the PV controller to ack; here the store
        write *is* the ack). node_name is the selected node: dynamically
        provisioned PVs get pinned to it, mirroring the provisioner honoring
        the volume.kubernetes.io/selected-node annotation."""
        try:
            for pv_key, pvc_key in volumes.static_bindings:
                pv = self.store.get("PersistentVolume", pv_key)
                pvc = self.store.get("PersistentVolumeClaim", pvc_key)
                pv.spec.claim_ref = pvc_key
                pv.spec.claim_ref_uid = pvc.meta.uid
                pv.status.phase = VOLUME_BOUND
                pvc.spec.volume_name = pv.meta.name
                pvc.status.phase = CLAIM_BOUND
                self.store.update(pv, check_version=False)
                self.store.update(pvc, check_version=False)
                self.assumed.pop(pv_key, None)
            for pvc_key in volumes.dynamic_provisions:
                pvc = self.store.get("PersistentVolumeClaim", pvc_key)
                pv = PersistentVolume()
                pv.meta.name = f"pvc-{pvc.meta.uid or pvc.meta.name}"
                pv.meta.namespace = ""
                pv.spec.storage_class_name = pvc.spec.storage_class_name
                pv.spec.access_modes = pvc.spec.access_modes
                pv.spec.capacity = dict(pvc.spec.request)
                pv.spec.claim_ref = pvc_key
                pv.spec.claim_ref_uid = pvc.meta.uid
                pv.status.phase = VOLUME_BOUND
                if node_name:
                    from ...api.types import (
                        NodeSelector,
                        NodeSelectorRequirement,
                        NodeSelectorTerm,
                    )

                    pv.spec.node_affinity = NodeSelector(
                        terms=(
                            NodeSelectorTerm(
                                match_expressions=(
                                    NodeSelectorRequirement(
                                        "kubernetes.io/hostname", "In", (node_name,)
                                    ),
                                )
                            ),
                        )
                    )
                sc = self.store.try_get("StorageClass", pvc.spec.storage_class_name)
                if sc is not None:
                    pv.spec.csi_driver = sc.provisioner
                self.store.create(pv)
                pvc.spec.volume_name = pv.meta.name
                pvc.status.phase = CLAIM_BOUND
                self.store.update(pvc, check_version=False)
        except Exception as e:  # noqa: BLE001 - surfaced as bind failure
            return Status.as_error(e, VolumeBinding.name)
        return Status()


# --- VolumeBinding plugin ---------------------------------------------------


class _BindingState:
    __slots__ = ("claims", "per_node", "pv_candidates", "by_claim",
                 "bound_pvs", "pv_by_key", "pvc_by_key")

    def __init__(self, claims: _ClaimsToBind, pv_candidates=None,
                 by_claim=None, bound_pvs=None):
        self.claims = claims
        self.pv_candidates: list | None = pv_candidates
        self.by_claim: dict | None = by_claim
        # bound claims' PVs prefetched once per cycle — the per-node Filter
        # and Score must not pay a store deepcopy per (pod, node)
        self.bound_pvs: list = bound_pvs or []
        self.pv_by_key: dict = {
            pv.meta.key: pv for pv in (pv_candidates or ())
        }
        for _, pv in self.bound_pvs:
            if pv is not None:
                self.pv_by_key.setdefault(pv.meta.key, pv)
        self.pvc_by_key: dict = {
            pvc.meta.key: pvc
            for pvc in claims.bound + claims.unbound_delayed
        }
        self.per_node: dict[str, PodVolumes] = {}


class VolumeBinding(Plugin):
    """volumebinding/volume_binding.go — topology-aware PV/PVC binding."""

    name = "VolumeBinding"
    STATE_KEY = "PreFilterVolumeBinding"

    def __init__(self, store, binder: VolumeBinder | None = None):
        self.binder = binder or VolumeBinder(store)

    def events_to_register(self):
        return [
            ClusterEventWithHint(ClusterEvent(ev.PVC, ev.ADD | ev.UPDATE), lambda *_: QUEUE),
            ClusterEventWithHint(ClusterEvent(ev.PV, ev.ADD | ev.UPDATE), lambda *_: QUEUE),
            ClusterEventWithHint(ClusterEvent(ev.STORAGE_CLASS, ev.ADD), lambda *_: QUEUE),
            ClusterEventWithHint(ClusterEvent(ev.CSI_NODE, ev.ADD | ev.UPDATE), lambda *_: QUEUE),
            ClusterEventWithHint(ClusterEvent(ev.NODE, ev.ADD | ev.UPDATE_NODE_LABEL), lambda *_: QUEUE),
        ]

    def pre_filter(self, state, pod: Pod, nodes):
        claims, err = self.binder.get_claims(pod)
        if err is not None:
            return None, err
        if not claims.bound and not claims.unbound_delayed:
            return None, Status.skip()
        candidates = (
            self.binder.list_candidate_pvs() if claims.unbound_delayed else []
        )
        by_claim = self.binder.candidates_for_claims(claims, candidates)
        bound_pvs = [
            (pvc, self.binder.store.try_get("PersistentVolume",
                                            pvc.spec.volume_name))
            for pvc in claims.bound
        ]
        state.write(self.STATE_KEY,
                    _BindingState(claims, candidates, by_claim, bound_pvs))
        return None, None

    def _state(self, state) -> _BindingState | None:
        return state.read(self.STATE_KEY)

    def filter(self, state, pod: Pod, node_info: NodeInfo) -> Status:
        s = self._state(state)
        if s is None:
            return Status()
        volumes, reasons = self.binder.find_pod_volumes(
            pod, s.claims, node_info, s.pv_candidates, by_claim=s.by_claim,
            bound_pvs=s.bound_pvs,
        )
        if reasons:
            # UnschedulableAndUnresolvable (volume_binding.go Filter): no
            # eviction changes PV node affinity, so preemption must not try
            return Status.unresolvable(*reasons, plugin=self.name)
        s.per_node[node_info.name] = volumes
        return Status()

    def filter_batch(self, state, pod: Pod, node_infos):
        """All-nodes Filter in one call (the host long-tail analogue of the
        dense kernel): the per-claim candidate scan is node-independent
        except for PV node affinity, so one ordered pass over candidates
        assigns every node its first matching volume — bit-identical to
        calling filter() per node, at O(candidates + nodes) instead of
        O(candidates x nodes). Returns None (-> per-node fallback) for
        multi-claim pods, whose within-pod taken-set interplay the
        vectorization doesn't model."""
        s = self._state(state)
        if s is None:
            return [None] * len(node_infos)
        if len(s.claims.unbound_delayed) > 1:
            return None
        n = len(node_infos)
        statuses: list[Status | None] = [None] * n
        conflict = None
        for pvc, pv in s.bound_pvs:
            if pv is None:
                conflict = Status.unresolvable(ERR_REASON_NODE_CONFLICT,
                                               plugin=self.name)
                return [conflict] * n
            if pv.spec.node_affinity is None:
                continue
            for i, ni in enumerate(node_infos):
                if statuses[i] is None and not self.binder.pv_fits_node(
                    pv, ni
                ):
                    if conflict is None:
                        conflict = Status.unresolvable(
                            ERR_REASON_NODE_CONFLICT, plugin=self.name)
                    statuses[i] = conflict
        if not s.claims.unbound_delayed:
            empty = PodVolumes()
            for i, ni in enumerate(node_infos):
                if statuses[i] is None:
                    s.per_node[ni.name] = empty
            return statuses

        pvc = s.claims.unbound_delayed[0]
        # node-independent half of _pv_matches, once per cycle
        cands = [
            pv for pv in (s.by_claim or {}).get(pvc.meta.key, ())
            if self.binder._pv_available(pv, pvc)
            and pv.spec.storage_class_name == pvc.spec.storage_class_name
            and set(pvc.spec.access_modes) <= set(pv.spec.access_modes)
            and pv.storage_capacity >= pvc.requested_storage
        ]
        remaining = [i for i in range(n) if statuses[i] is None]
        assignment: dict[int, PersistentVolume] = {}
        for pv in cands:
            if not remaining:
                break
            if pv.spec.node_affinity is None:
                for i in remaining:
                    assignment[i] = pv
                remaining = []
            else:
                still = []
                for i in remaining:
                    if self.binder.pv_fits_node(pv, node_infos[i]):
                        assignment[i] = pv
                    else:
                        still.append(i)
                remaining = still
        sc = self.binder.store.try_get(
            "StorageClass", pvc.spec.storage_class_name
        )
        prov = (PodVolumes(dynamic_provisions=[pvc.meta.key])
                if sc is not None and sc.provisioner != NO_PROVISIONER
                else None)
        bind_fail = None
        vol_cache: dict[str, PodVolumes] = {}
        for i, ni in enumerate(node_infos):
            if statuses[i] is not None:
                continue
            pv = assignment.get(i)
            if pv is not None:
                vol = vol_cache.get(pv.meta.key)
                if vol is None:
                    vol = PodVolumes(
                        static_bindings=[(pv.meta.key, pvc.meta.key)]
                    )
                    vol_cache[pv.meta.key] = vol
                s.per_node[ni.name] = vol
            elif prov is not None:
                s.per_node[ni.name] = prov
            else:
                if bind_fail is None:
                    bind_fail = Status.unresolvable(
                        ERR_REASON_BIND_CONFLICT, plugin=self.name)
                statuses[i] = bind_fail
        return statuses

    def score_batch(self, state, pod: Pod, node_infos) -> list[int]:
        """Score over all nodes at once; assignments sharing a PodVolumes
        share one capacity computation."""
        s = self._state(state)
        if s is None:
            return [0] * len(node_infos)
        cache: dict[int, int] = {}
        out = []
        for ni in node_infos:
            volumes = s.per_node.get(ni.name)
            if volumes is None or not volumes.static_bindings:
                out.append(0)
                continue
            key = id(volumes)
            val = cache.get(key)
            if val is None:
                total_req = total_cap = 0
                for pv_key, pvc_key in volumes.static_bindings:
                    pv = s.pv_by_key.get(pv_key)
                    pvc = s.pvc_by_key.get(pvc_key)
                    if pv is None or pvc is None:
                        continue
                    total_req += pvc.requested_storage
                    total_cap += pv.storage_capacity
                val = ((MAX_NODE_SCORE * total_req) // total_cap
                       if total_cap else 0)
                cache[key] = val
            out.append(val)
        return out

    def score(self, state, pod: Pod, node_info: NodeInfo):
        """Static-binding utilization shape: tighter fit scores higher
        (volume_binding.go Score:471 with the default shape — 0% util -> 0,
        100% util -> MaxNodeScore)."""
        s = self._state(state)
        if s is None:
            return 0, None
        volumes = s.per_node.get(node_info.name)
        if volumes is None or not volumes.static_bindings:
            return 0, None
        total_req = 0
        total_cap = 0
        for pv_key, pvc_key in volumes.static_bindings:
            # cycle-state lookups, NOT store gets: a deepcopy per
            # (pod, node) Score call dominated the 5k-node profile
            pv = s.pv_by_key.get(pv_key)
            pvc = s.pvc_by_key.get(pvc_key)
            if pv is None or pvc is None:
                continue
            total_req += pvc.requested_storage
            total_cap += pv.storage_capacity
        if total_cap == 0:
            return 0, None
        return (MAX_NODE_SCORE * total_req) // total_cap, None

    def reserve(self, state, pod: Pod, node_name: str) -> Status:
        s = self._state(state)
        if s is None:
            return Status()
        volumes = s.per_node.get(node_name)
        if volumes is None:
            return Status.as_error(
                RuntimeError(f"no volume decision for node {node_name}"), self.name
            )
        self.binder.assume_pod_volumes(volumes)
        return Status()

    def unreserve(self, state, pod: Pod, node_name: str) -> None:
        s = self._state(state)
        if s is None:
            return
        volumes = s.per_node.get(node_name)
        if volumes is not None:
            self.binder.revert_assumed_pod_volumes(volumes)

    def pre_bind_pre_flight(self, state, pod: Pod, node_name: str) -> Status:
        s = self._state(state)
        if s is None:
            return Status.skip()
        v = s.per_node.get(node_name)
        if v is None or (not v.static_bindings and not v.dynamic_provisions):
            return Status.skip()
        return Status()

    def pre_bind(self, state, pod: Pod, node_name: str) -> Status:
        s = self._state(state)
        if s is None:
            return Status()
        volumes = s.per_node.get(node_name)
        if volumes is None:
            return Status()
        return self.binder.bind_pod_volumes(pod, volumes, node_name)

    def sign(self, pod: Pod) -> str | None:
        """signers.go VolumeSigner — claim names identify volume topology."""
        return ",".join(sorted(pod_claim_names(pod)))


# --- VolumeRestrictions -----------------------------------------------------


class _RestrictionsState:
    """COW per-cycle RWOP conflict count (volume_restrictions.go
    preFilterState); clone() gives preemption dry-runs their own counter."""

    __slots__ = ("rwop_keys", "conflicts")

    def __init__(self, rwop_keys: frozenset, conflicts: int):
        self.rwop_keys = rwop_keys
        self.conflicts = conflicts

    def clone(self) -> "_RestrictionsState":
        return _RestrictionsState(self.rwop_keys, self.conflicts)


class VolumeRestrictions(Plugin):
    """volumerestrictions/volume_restrictions.go — ReadWriteOncePod access-mode
    conflicts (:318). Legacy in-tree disk (GCE PD / AWS EBS) double-attach
    checks are intentionally absent: those drivers are CSI-migrated in the
    reference snapshot."""

    name = "VolumeRestrictions"

    def __init__(self, store):
        self.store = store

    def events_to_register(self):
        return [
            ClusterEventWithHint(ClusterEvent(ev.ASSIGNED_POD, ev.DELETE), lambda *_: QUEUE),
            ClusterEventWithHint(ClusterEvent(ev.PVC, ev.ADD | ev.UPDATE), lambda *_: QUEUE),
        ]

    STATE_KEY = "PreFilterVolumeRestrictions"

    def pre_filter(self, state, pod: Pod, nodes):
        claim_names = pod_claim_names(pod)
        if not claim_names:
            return None, Status.skip()
        rwop_keys = set()
        for name in claim_names:
            key = _pvc_key(pod.meta.namespace, name)
            pvc = self.store.try_get("PersistentVolumeClaim", key)
            if pvc is None:
                return None, Status.unresolvable(
                    f'{ERR_REASON_NOT_FOUND} "{name}"', plugin=self.name
                )
            if READ_WRITE_ONCE_POD in pvc.spec.access_modes:
                rwop_keys.add(key)
        if not rwop_keys:
            return None, Status.skip()
        # cluster-wide holder count; AddPod/RemovePod keep it consistent in
        # preemption dry-runs so evicting the holder resolves the conflict
        conflicts = sum(
            ni.pvc_ref_counts.get(key, 0) for ni in nodes for key in rwop_keys
        )
        state.write(self.STATE_KEY, _RestrictionsState(frozenset(rwop_keys), conflicts))
        return None, None

    def _conflict_delta(self, rwop_keys: frozenset, pod_info) -> int:
        return sum(1 for k in pod_info.pvc_keys if k in rwop_keys)

    def add_pod(self, state, pod: Pod, pod_info_to_add, node_info) -> Status:
        s = state.read(self.STATE_KEY)
        if s is not None:
            s.conflicts += self._conflict_delta(s.rwop_keys, pod_info_to_add)
        return Status()

    def remove_pod(self, state, pod: Pod, pod_info_to_remove, node_info) -> Status:
        s = state.read(self.STATE_KEY)
        if s is not None:
            s.conflicts -= self._conflict_delta(s.rwop_keys, pod_info_to_remove)
        return Status()

    def filter(self, state, pod: Pod, node_info: NodeInfo) -> Status:
        s = state.read(self.STATE_KEY)
        if s is not None and s.conflicts > 0:
            return Status.unschedulable(ERR_REASON_RWOP_CONFLICT, plugin=self.name)
        return Status()


# --- VolumeZone -------------------------------------------------------------


class VolumeZone(Plugin):
    """volumezone/volume_zone.go — bound PVs carrying well-known zone/region
    labels constrain the node's matching labels (:198)."""

    name = "VolumeZone"

    def __init__(self, store):
        self.store = store

    def events_to_register(self):
        return [
            ClusterEventWithHint(ClusterEvent(ev.PVC, ev.ADD | ev.UPDATE), lambda *_: QUEUE),
            ClusterEventWithHint(ClusterEvent(ev.PV, ev.ADD | ev.UPDATE), lambda *_: QUEUE),
            ClusterEventWithHint(ClusterEvent(ev.NODE, ev.ADD | ev.UPDATE_NODE_LABEL), lambda *_: QUEUE),
        ]

    def _pod_pv_zone_constraints(self, pod: Pod) -> list[tuple[str, str]] | Status:
        out: list[tuple[str, str]] = []
        for name in pod_claim_names(pod):
            pvc = self.store.try_get(
                "PersistentVolumeClaim", _pvc_key(pod.meta.namespace, name)
            )
            if pvc is None:
                return Status.unresolvable(
                    f'{ERR_REASON_NOT_FOUND} "{name}"', plugin=self.name
                )
            if not pvc.spec.volume_name:
                continue  # unbound: VolumeBinding owns topology for these
            pv = self.store.try_get("PersistentVolume", pvc.spec.volume_name)
            if pv is None:
                continue
            for label in ZONE_LABELS:
                if label in pv.meta.labels:
                    out.append((label, pv.meta.labels[label]))
        return out

    def pre_filter(self, state, pod: Pod, nodes):
        constraints = self._pod_pv_zone_constraints(pod)
        if isinstance(constraints, Status):
            return None, constraints
        if not constraints:
            return None, Status.skip()
        state.write("PreFilterVolumeZone", constraints)
        return None, None

    def filter(self, state, pod: Pod, node_info: NodeInfo) -> Status:
        constraints = state.read("PreFilterVolumeZone")
        if not constraints:
            return Status()
        labels = node_info.node.meta.labels
        for key, value in constraints:
            # missing label counts as a mismatch (volume_zone.go:198 — the
            # node must carry the PV's topology label with the same value);
            # unresolvable: eviction can't relabel nodes
            if labels.get(key) != value:
                return Status.unresolvable(ERR_REASON_ZONE_CONFLICT, plugin=self.name)
        return Status()


# --- NodeVolumeLimits (CSI) -------------------------------------------------


class NodeVolumeLimits(Plugin):
    """nodevolumelimits/csi.go — per-driver CSI attach-limit filter (:257).
    Counts unique volumes already attached (existing pods' bound PVs) plus the
    incoming pod's, per CSI driver, against the node's CSINode allocatable."""

    name = "NodeVolumeLimits"

    def __init__(self, store):
        self.store = store

    def events_to_register(self):
        return [
            ClusterEventWithHint(ClusterEvent(ev.CSI_NODE, ev.ADD | ev.UPDATE), lambda *_: QUEUE),
            ClusterEventWithHint(ClusterEvent(ev.ASSIGNED_POD, ev.DELETE), lambda *_: QUEUE),
            ClusterEventWithHint(ClusterEvent(ev.PVC, ev.ADD | ev.UPDATE), lambda *_: QUEUE),
        ]

    def _driver_of(self, pvc_key: str) -> tuple[str, str] | None:
        """Resolve a claim to (driver, volume identity) or None if
        driverless. Copy-free reads (get_ref): this runs per attached claim
        per node in the Filter hot loop, where try_get's deepcopies were
        the dominant cost of the whole CSI scheduling cycle."""
        read = getattr(self.store, "get_ref", self.store.try_get)
        pvc = read("PersistentVolumeClaim", pvc_key)
        if pvc is None:
            return None
        if pvc.spec.volume_name:
            pv = read("PersistentVolume", pvc.spec.volume_name)
            if pv is not None and pv.spec.csi_driver:
                return pv.spec.csi_driver, pv.meta.name
            return None
        sc = read("StorageClass", pvc.spec.storage_class_name)
        if sc is not None and sc.provisioner != NO_PROVISIONER:
            # to-be-provisioned volume counts toward its driver's limit
            return sc.provisioner, pvc_key
        return None

    STATE_KEY = "PreFilterNodeVolumeLimits"
    MEMO_KEY = "PreFilterNodeVolumeLimitsMemo"

    def pre_filter(self, state, pod: Pod, nodes):
        # resolve the pod's claims to per-driver volume identities once — the
        # result is node-independent (csi.go PreFilter)
        new_by_driver: dict[str, set[str]] = {}
        for name in pod_claim_names(pod):
            res = self._driver_of(_pvc_key(pod.meta.namespace, name))
            if res is None:
                continue
            driver, vol = res
            new_by_driver.setdefault(driver, set()).add(vol)
        if not new_by_driver:
            return None, Status.skip()
        state.write(self.STATE_KEY, new_by_driver)
        # claim->driver resolutions are stable within a cycle: memoize them
        # across the per-node Filter calls (csi.go resolves once per cycle)
        state.write(self.MEMO_KEY, {})
        return None, None

    def filter(self, state, pod: Pod, node_info: NodeInfo) -> Status:
        new_by_driver = state.read(self.STATE_KEY)
        if not new_by_driver:
            return Status()
        read = getattr(self.store, "get_ref", self.store.try_get)
        csi_node = read("CSINode", node_info.name)
        if csi_node is None or not csi_node.drivers:
            return Status()
        memo: dict = state.read(self.MEMO_KEY) or {}
        used_by_driver: dict[str, set[str]] = {}
        for key in node_info.pvc_ref_counts:
            if key in memo:
                res = memo[key]
            else:
                res = memo[key] = self._driver_of(key)
            if res is None:
                continue
            driver, vol = res
            used_by_driver.setdefault(driver, set()).add(vol)
        for driver, new_vols in new_by_driver.items():
            limit = csi_node.limit_for(driver)
            if limit <= 0:
                continue
            used = used_by_driver.get(driver, set())
            if len(used | new_vols) > limit:
                return Status.unschedulable(
                    ERR_REASON_MAX_VOLUME_COUNT, plugin=self.name
                )
        return Status()
