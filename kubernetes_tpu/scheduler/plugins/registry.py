"""In-tree plugin registry and default enablement/weights.

Reference: pkg/scheduler/framework/plugins/registry.go:49-77 and default
plugin set + weights at pkg/scheduler/apis/config/v1/default_plugins.go:29-73
(TaintToleration w3, NodeAffinity w2, PodTopologySpread w2, InterPodAffinity
w2, NodeResourcesFit w1, NodeResourcesBalancedAllocation w1, ImageLocality w1).
"""

from __future__ import annotations

from ...api.resource import ResourceNames
from .basics import (
    DefaultBinder,
    ImageLocality,
    NodeName,
    NodePorts,
    NodeUnschedulable,
    PrioritySort,
    SchedulingGates,
    TaintToleration,
)
from .interpod_affinity import InterPodAffinity
from .node_affinity import NodeAffinity
from .node_resources import BalancedAllocation, NodeResourcesFit
from .pod_topology_spread import PodTopologySpread
from .volumes import (
    NodeVolumeLimits,
    VolumeBinding,
    VolumeRestrictions,
    VolumeZone,
)

DEFAULT_WEIGHTS = {
    "TaintToleration": 3,
    "NodeAffinity": 2,
    "PodTopologySpread": 2,
    "InterPodAffinity": 2,
    "NodeResourcesFit": 1,
    "NodeResourcesBalancedAllocation": 1,
    "ImageLocality": 1,
    "VolumeBinding": 1,
}


def default_plugins(store, names: ResourceNames, feature_gates=None, args: dict | None = None):
    """The default-profile plugin list, in extension-point order."""
    args = args or {}
    fit_args = args.get("NodeResourcesFit", {})
    ipa_args = args.get("InterPodAffinity", {})
    plugins = [
        SchedulingGates(),
        PrioritySort(),
        NodeUnschedulable(),
        NodeName(),
        TaintToleration(),
        NodeAffinity(),
        NodePorts(),
        NodeResourcesFit(
            names,
            scoring_strategy=fit_args.get("strategy", "LeastAllocated"),
            resource_weights=fit_args.get("resources"),
            shape=fit_args.get("shape"),
        ),
        VolumeRestrictions(store),
        NodeVolumeLimits(store),
        VolumeBinding(store),
        VolumeZone(store),
        PodTopologySpread(),
        InterPodAffinity(ignore_preferred_terms_of_existing_pods=ipa_args.get(
            "ignorePreferredTermsOfExistingPods", False)),
        BalancedAllocation(names),
        ImageLocality(),
        DefaultBinder(store),
    ]
    gates = feature_gates or {}
    if gates.get("NodeDeclaredFeatures", True):
        from .node_declared_features import NodeDeclaredFeatures

        # filters before NodeResourcesFit (default_plugins.go gated adds)
        idx = next(i for i, p in enumerate(plugins)
                   if p.name == "NodeResourcesFit")
        plugins.insert(idx, NodeDeclaredFeatures())
    if gates.get("DynamicResourceAllocation", True):
        from .dynamic_resources import DynamicResources

        idx = next(i for i, p in enumerate(plugins) if p.name == "PodTopologySpread")
        plugins.insert(idx, DynamicResources(store))
    if gates.get("GangScheduling", True):
        from .gang_scheduling import GangScheduling

        plugins.insert(1, GangScheduling())
    if gates.get("TopologyAwareWorkloadScheduling", True):
        from .topology_placement import TopologyPlacementGenerator

        plugins.append(TopologyPlacementGenerator())
    if gates.get("DefaultPreemption", True):
        from .default_preemption import DefaultPreemption

        plugins.append(DefaultPreemption(names))
    return plugins
