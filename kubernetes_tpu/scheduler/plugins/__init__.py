"""In-tree plugins — the "ops library" (reference: pkg/scheduler/framework/plugins/)."""

from .basics import (  # noqa: F401
    DefaultBinder,
    ImageLocality,
    NodeName,
    NodePorts,
    NodeUnschedulable,
    PrioritySort,
    SchedulingGates,
    TaintToleration,
)
from .interpod_affinity import InterPodAffinity  # noqa: F401
from .node_affinity import NodeAffinity  # noqa: F401
from .node_resources import BalancedAllocation, NodeResourcesFit  # noqa: F401
from .pod_topology_spread import PodTopologySpread  # noqa: F401
from .gang_scheduling import GangScheduling  # noqa: F401
from .default_preemption import DefaultPreemption  # noqa: F401
from .registry import DEFAULT_WEIGHTS, default_plugins  # noqa: F401
