"""InterPodAffinity: pod↔pod (anti)affinity over topology domains.

Reference: pkg/scheduler/framework/plugins/interpodaffinity/ — PreFilter builds
topologyToMatchedTermCount maps (filtering.go:91-185) by scanning
HavePodsWithAffinityList; Filter is 3 predicate checks (filtering.go:352-412);
Score sums weighted preferred-term matches over existing pods
(scoring.go:81-257).

The domain-count preaggregation (NOT naive pods x pods) is exactly the shape
the TPU kernel uses: match vectors over existing pods segment-summed into
(term, domain) counts.
"""

from __future__ import annotations

from ...api.types import Pod
from ..framework import events as ev
from ..framework.events import ClusterEvent, ClusterEventWithHint
from ..framework.interface import MAX_NODE_SCORE, Plugin, Status
from ..nodeinfo import AffinityTerm, NodeInfo, PodInfo

TopoPair = tuple[str, str]  # (topology key, value)


class _PreFilterState:
    __slots__ = (
        "pod_info",
        "existing_anti_counts",
        "affinity_counts",
        "anti_affinity_counts",
    )

    def __init__(self):
        self.pod_info: PodInfo | None = None
        # (key,value) -> count of existing pods whose required anti-affinity
        # terms match the incoming pod in that domain
        self.existing_anti_counts: dict[TopoPair, int] = {}
        # per incoming required affinity term index: (key,value) -> match count
        self.affinity_counts: list[dict[TopoPair, int]] = []
        self.anti_affinity_counts: list[dict[TopoPair, int]] = []

    def clone(self):
        s = _PreFilterState()
        s.pod_info = self.pod_info
        s.existing_anti_counts = dict(self.existing_anti_counts)
        s.affinity_counts = [dict(d) for d in self.affinity_counts]
        s.anti_affinity_counts = [dict(d) for d in self.anti_affinity_counts]
        return s


def _topo_pairs(node, term: AffinityTerm) -> TopoPair | None:
    val = node.meta.labels.get(term.topology_key)
    return (term.topology_key, val) if val is not None else None


class InterPodAffinity(Plugin):
    name = "InterPodAffinity"
    PRE_FILTER_KEY = "PreFilterInterPodAffinity"
    PRE_SCORE_KEY = "PreScoreInterPodAffinity"

    def __init__(self, ignore_preferred_terms_of_existing_pods: bool = False):
        self.ignore_preferred_existing = ignore_preferred_terms_of_existing_pods

    def events_to_register(self):
        return [
            ClusterEventWithHint(ClusterEvent(ev.POD, ev.ALL)),
            ClusterEventWithHint(ClusterEvent(ev.NODE, ev.ADD | ev.UPDATE_NODE_LABEL)),
        ]

    # -- prefilter -----------------------------------------------------------

    def pre_filter(self, state, pod: Pod, nodes: list[NodeInfo]):
        from ...api.resource import ResourceNames

        pi = PodInfo(pod, ResourceNames())
        aff = pod.spec.affinity
        has_constraints = pi.required_affinity_terms or pi.required_anti_affinity_terms
        s = _PreFilterState()
        s.pod_info = pi

        # existing pods' required anti-affinity vs incoming pod
        # (filtering.go getExistingAntiAffinityCounts — scan only nodes with
        # pods that declare required anti-affinity)
        any_existing_anti = False
        for ni in nodes:
            if ni.pods_with_required_anti_affinity:
                any_existing_anti = True
                break
        if not has_constraints and not any_existing_anti:
            return None, Status.skip()

        for ni in nodes:
            node = ni.node
            if node is None:
                continue
            for epi in ni.pods_with_required_anti_affinity:
                for term in epi.required_anti_affinity_terms:
                    if term.matches(pod):
                        pair = _topo_pairs(node, term)
                        if pair is not None:
                            s.existing_anti_counts[pair] = s.existing_anti_counts.get(pair, 0) + 1

        # incoming pod's required terms vs existing pods
        # (filtering.go getIncomingAffinityAntiAffinityCounts)
        if pi.required_affinity_terms:
            s.affinity_counts = [{} for _ in pi.required_affinity_terms]
        if pi.required_anti_affinity_terms:
            s.anti_affinity_counts = [{} for _ in pi.required_anti_affinity_terms]
        if has_constraints:
            for ni in nodes:
                node = ni.node
                if node is None:
                    continue
                for epi in ni.iter_pods():
                    for ti, term in enumerate(pi.required_affinity_terms):
                        if term.matches(epi.pod):
                            pair = _topo_pairs(node, term)
                            if pair is not None:
                                d = s.affinity_counts[ti]
                                d[pair] = d.get(pair, 0) + 1
                    for ti, term in enumerate(pi.required_anti_affinity_terms):
                        if term.matches(epi.pod):
                            pair = _topo_pairs(node, term)
                            if pair is not None:
                                d = s.anti_affinity_counts[ti]
                                d[pair] = d.get(pair, 0) + 1
        state.write(self.PRE_FILTER_KEY, s)
        return None, Status()

    # -- add/remove pod extensions -------------------------------------------

    def add_pod(self, state, pod, pod_info_to_add: PodInfo, node_info: NodeInfo) -> Status:
        return self._update(state, pod, pod_info_to_add, node_info, +1)

    def remove_pod(self, state, pod, pod_info_to_remove: PodInfo, node_info: NodeInfo) -> Status:
        return self._update(state, pod, pod_info_to_remove, node_info, -1)

    def _update(self, state, pod, epi: PodInfo, node_info: NodeInfo, delta: int) -> Status:
        s: _PreFilterState | None = state.read(self.PRE_FILTER_KEY)
        if s is None or node_info.node is None:
            return Status()
        node = node_info.node
        for term in epi.required_anti_affinity_terms:
            if term.matches(pod):
                pair = _topo_pairs(node, term)
                if pair is not None:
                    s.existing_anti_counts[pair] = s.existing_anti_counts.get(pair, 0) + delta
        pi = s.pod_info
        if pi is not None:
            for ti, term in enumerate(pi.required_affinity_terms):
                if term.matches(epi.pod):
                    pair = _topo_pairs(node, term)
                    if pair is not None and ti < len(s.affinity_counts):
                        d = s.affinity_counts[ti]
                        d[pair] = d.get(pair, 0) + delta
            for ti, term in enumerate(pi.required_anti_affinity_terms):
                if term.matches(epi.pod):
                    pair = _topo_pairs(node, term)
                    if pair is not None and ti < len(s.anti_affinity_counts):
                        d = s.anti_affinity_counts[ti]
                        d[pair] = d.get(pair, 0) + delta
        return Status()

    # -- filter ---------------------------------------------------------------

    def filter(self, state, pod: Pod, node_info: NodeInfo) -> Status:
        s: _PreFilterState | None = state.read(self.PRE_FILTER_KEY)
        if s is None:
            return Status()
        node = node_info.node
        if node is None:
            return Status.unschedulable("node not found", plugin=self.name)
        pi = s.pod_info

        # 1. existing pods' required anti-affinity reject (filtering.go:352)
        for (key, val), count in s.existing_anti_counts.items():
            if count > 0 and node.meta.labels.get(key) == val:
                return Status.unschedulable(
                    "node(s) had pods with anti-affinity rules rejecting the pod",
                    plugin=self.name,
                )

        # 2. incoming required anti-affinity (filtering.go:389)
        for ti, term in enumerate(pi.required_anti_affinity_terms):
            pair = _topo_pairs(node, term)
            if pair is None:
                continue
            if s.anti_affinity_counts[ti].get(pair, 0) > 0:
                return Status.unschedulable(
                    "node(s) didn't satisfy pod anti-affinity rules", plugin=self.name
                )

        # 3. incoming required affinity (filtering.go:404) — every term must
        # match in this node's domain, unless no pod matches it anywhere and
        # the pod matches its own term (bootstrap case).
        for ti, term in enumerate(pi.required_affinity_terms):
            pair = _topo_pairs(node, term)
            if pair is not None and s.affinity_counts[ti].get(pair, 0) > 0:
                continue
            term_matched_anywhere = any(v > 0 for v in s.affinity_counts[ti].values())
            if not term_matched_anywhere and term.matches(pod):
                continue  # self-match bootstrap
            return Status.unschedulable(
                "node(s) didn't satisfy pod affinity rules", plugin=self.name
            )
        return Status()

    # -- score -----------------------------------------------------------------

    def pre_score(self, state, pod: Pod, nodes: list[NodeInfo]) -> Status:
        from ...api.resource import ResourceNames

        pi = PodInfo(pod, ResourceNames())
        has_preferred = pi.preferred_affinity_terms or pi.preferred_anti_affinity_terms
        if not has_preferred and self.ignore_preferred_existing:
            return Status.skip()
        # (key,value) -> accumulated weight for the incoming pod
        scores: dict[TopoPair, int] = {}

        def accumulate(node, terms, target: Pod, sign: int):
            for weight, term in terms:
                if term.matches(target):
                    val = node.meta.labels.get(term.topology_key)
                    if val is not None:
                        pair = (term.topology_key, val)
                        scores[pair] = scores.get(pair, 0) + sign * weight

        any_existing_affinity = any(ni.pods_with_affinity for ni in nodes)
        if not has_preferred and not any_existing_affinity:
            return Status.skip()

        for ni in nodes:
            node = ni.node
            if node is None:
                continue
            pods = ni.pods_with_affinity if not has_preferred else ni.iter_pods()
            for epi in pods:
                # incoming pod's preferred terms vs existing pod
                accumulate(node, pi.preferred_affinity_terms, epi.pod, +1)
                accumulate(node, pi.preferred_anti_affinity_terms, epi.pod, -1)
                if not self.ignore_preferred_existing:
                    # existing pod's preferred terms vs incoming pod
                    accumulate(node, epi.preferred_affinity_terms, pod, +1)
                    accumulate(node, epi.preferred_anti_affinity_terms, pod, -1)
        if not scores:
            return Status.skip()
        state.write(self.PRE_SCORE_KEY, scores)
        return Status()

    def score(self, state, pod: Pod, node_info: NodeInfo):
        scores = state.read(self.PRE_SCORE_KEY)
        if not scores:
            return 0, Status()
        node = node_info.node
        if node is None:
            return 0, Status()
        total = 0
        for (key, val), weight in scores.items():
            if node.meta.labels.get(key) == val:
                total += weight
        return total, Status()

    def normalize_score(self, state, pod: Pod, scores) -> Status:
        """scoring.go:229 — scale [min,max] -> [0,100] handling negatives."""
        vals = [s for _, s in scores]
        if not vals:
            return Status()
        max_v, min_v = max(vals), min(vals)
        spread = max_v - min_v
        for row in scores:
            if spread == 0:
                row[1] = MAX_NODE_SCORE if max_v > 0 else 0
            else:
                row[1] = MAX_NODE_SCORE * (row[1] - min_v) // spread
        return Status()

    def sign(self, pod: Pod) -> str | None:
        aff = pod.spec.affinity
        if aff is None or (aff.pod_affinity is None and aff.pod_anti_affinity is None):
            return ""
        return repr((aff.pod_affinity, aff.pod_anti_affinity))
