"""TopologyAware placement generation + scoring for pod-group cycles.

Reference: pkg/scheduler/framework/plugins/topologyaware/topology_placement.go
:61-105 (KEP-5732) — partitions the parent placement's nodes by the pod
group's SchedulingConstraints.Topology[0].Key into one Placement per domain,
so the group cycle can try to pack the whole gang into a single topology
domain. The upstream leaves PlacementScore as a TODO
(schedule_one_podgroup.go:569); ours scores a placement by the mean
NodeResourcesFit strategy score of its nodes, so LeastAllocated prefers the
emptiest domain and MostAllocated bin-packs the fullest one that still fits.
"""

from __future__ import annotations

from ...api.types import Pod
from ..cache.snapshot import Placement
from ..framework.interface import Plugin, Status


class TopologyPlacementGenerator(Plugin):
    name = "TopologyPlacementGenerator"

    def __init__(self, handle=None):
        self.handle = handle

    def set_handle(self, handle) -> None:
        self.handle = handle

    def _group_of(self, pod: Pod):
        sg = pod.spec.scheduling_group
        if sg is None or self.handle is None:
            return None
        gk = f"{pod.meta.namespace}/{sg.pod_group_name}"
        return self.handle.store.try_get("PodGroup", gk)

    def topology_mode(self, pods: list[Pod]) -> str | None:
        """"Required" | "Preferred" | None when the group has no topology
        constraint (drives whether a no-fitting-domain gang fails or falls
        back to all nodes)."""
        group = self._group_of(pods[0]) if pods else None
        if group is None or not group.spec.constraints.topology:
            return None
        return group.spec.constraints.topology[0].mode

    def _scheduled_pods_domain(self, pods: list[Pod], key: str):
        """requiredDomain (topology_placement.go:74-93
        getScheduledPodsTopologyDomain): a partially-scheduled gang is
        pinned to the single domain its already-scheduled members occupy.
        Returns (domain | None, error Status | None)."""
        sg = pods[0].spec.scheduling_group if pods else None
        if sg is None:
            return None, None
        gk = f"{pods[0].meta.namespace}/{sg.pod_group_name}"
        gstate = self.handle.cache.pod_group_states.get(gk)
        if gstate is None or not gstate.scheduled:
            return None, None
        snapshot = self.handle.snapshot
        domain = None
        for pod_key in sorted(gstate.scheduled):
            pod = self.handle.store.try_get("Pod", pod_key)
            if pod is None or not pod.spec.node_name:
                continue
            ni = snapshot.get(pod.spec.node_name)
            node = ni.node if ni is not None else None
            if node is None:
                continue
            val = node.meta.labels.get(key)
            if val is None:
                return None, Status.as_error(RuntimeError(
                    f"no topology domain found for scheduled pod {pod_key}"
                ), self.name)
            if domain is not None and domain != val:
                return None, Status.as_error(RuntimeError(
                    f"more than 1 domain for pod group {gk}: {domain}, {val}"
                ), self.name)
            domain = val
        return domain, None

    def generate_placements(self, state, pods: list[Pod], placements):
        """topology_placement.go:61-105 — one child placement per domain
        value of the group's first topology key, in sorted value order; a
        partially-scheduled gang only gets its scheduled members' domain
        (requiredDomain, :74-93), so an incremental gang cannot split."""
        group = self._group_of(pods[0]) if pods else None
        if group is None or not group.spec.constraints.topology:
            return placements, Status.skip()
        key = group.spec.constraints.topology[0].key
        required_domain, err = self._scheduled_pods_domain(pods, key)
        if err is not None:
            return placements, err
        snapshot = self.handle.snapshot
        out: list[Placement] = []
        for parent in placements:
            domains: dict[str, list[str]] = {}
            for name in parent.node_names:
                ni = snapshot.get(name)
                node = ni.node if ni is not None else None
                if node is None:
                    continue
                val = node.meta.labels.get(key)
                if val is not None and (required_domain is None
                                        or val == required_domain):
                    domains.setdefault(val, []).append(name)
            for val in sorted(domains):
                out.append(Placement(f"{parent.name}/{key}={val}", domains[val]))
        if not out and required_domain is not None:
            # the pinned domain has no candidate nodes left: with Required
            # topology the gang must not land elsewhere — an empty
            # placement makes the dry-run fail cleanly
            return [Placement(f"{key}={required_domain}", [])], Status()
        if not out:
            return placements, Status.skip()
        return out, Status()

    def score_placement(self, state, pods: list[Pod], placement) -> tuple[int, Status]:
        """Mean free-capacity score (0-100) of the placement's nodes under
        the LeastAllocated shape: emptier domains score higher, giving the
        gang headroom; deterministic tie-break is placement order."""
        snapshot = self.handle.snapshot
        total = 0
        n = 0
        for name in placement.node_names:
            ni = snapshot.get(name)
            if ni is None or ni.node is None:
                continue
            score = 0
            parts = 0
            for col in (0, 1):  # cpu, memory plane columns
                cap = ni.allocatable[col]
                if cap <= 0:
                    continue
                used = min(ni.requested[col], cap)
                score += (cap - used) * 100 // cap
                parts += 1
            if parts:
                total += score // parts
                n += 1
        return (total // n if n else 0), Status()
