"""PodTopologySpread: maxSkew filter + normalized spreading score.

Reference: pkg/scheduler/framework/plugins/podtopologyspread/ — PreFilter
builds per-(topologyKey,value) match counts with two-minimum criticalPaths
(filtering.go:97,237); Filter enforces `count + selfMatch - min <= maxSkew`
(filtering.go:314); Score computes per-domain counts weighted by
topologyNormalizingWeight = log(domains+2) (scoring.go:118-305). Cluster
defaults (SystemDefaulting, plugin.go:46-60): zone + hostname ScheduleAnyway.

TPU-equiv (ops/kernels.py): domain ids per node + segment-sums.
"""

from __future__ import annotations

import numpy as np

from ...api.labels import LabelSelector
from ...api.types import (
    DO_NOT_SCHEDULE,
    SCHEDULE_ANYWAY,
    Pod,
    TopologySpreadConstraint,
)
from ..framework import events as ev
from ..framework.events import ClusterEvent, ClusterEventWithHint
from ..framework.interface import MAX_NODE_SCORE, Plugin, Status
from ..nodeinfo import NodeInfo, PodInfo

ZONE_LABEL = "topology.kubernetes.io/zone"
HOSTNAME_LABEL = "kubernetes.io/hostname"

_SYSTEM_DEFAULT_CONSTRAINTS = (
    TopologySpreadConstraint(3, HOSTNAME_LABEL, SCHEDULE_ANYWAY, None),
    TopologySpreadConstraint(5, ZONE_LABEL, SCHEDULE_ANYWAY, None),
)


class _MatchNothing:
    """nil labelSelector on an explicit constraint selects no pods (k8s
    LabelSelectorAsSelector semantics)."""

    def matches(self, labels) -> bool:
        return False

    def canonical(self) -> str:
        return "<nothing>"


_MATCH_NOTHING = _MatchNothing()


def _self_selector(pod: Pod, c: TopologySpreadConstraint):
    return c.label_selector if c.label_selector is not None else _MATCH_NOTHING


class _PreFilterState:
    __slots__ = ("constraints", "domain_counts", "min_counts", "self_matches")

    def __init__(self):
        self.constraints: list[TopologySpreadConstraint] = []
        # per-constraint: {domain value: count of matching pods}
        self.domain_counts: list[dict[str, int]] = []
        self.min_counts: list[int] = []
        self.self_matches: list[int] = []

    def clone(self):
        s = _PreFilterState()
        s.constraints = self.constraints
        s.domain_counts = [dict(d) for d in self.domain_counts]
        s.min_counts = list(self.min_counts)
        s.self_matches = list(self.self_matches)
        return s

    def recompute_min(self, i: int) -> None:
        d = self.domain_counts[i]
        self.min_counts[i] = min(d.values()) if d else 0


class PodTopologySpread(Plugin):
    name = "PodTopologySpread"
    PRE_FILTER_KEY = "PreFilterPodTopologySpread"
    PRE_SCORE_KEY = "PreScorePodTopologySpread"

    def __init__(self, default_constraints=None, system_defaulting: bool = True):
        self.default_constraints = tuple(default_constraints or ())
        self.system_defaulting = system_defaulting

    def events_to_register(self):
        return [
            ClusterEventWithHint(ClusterEvent(ev.POD, ev.ADD | ev.DELETE | ev.UPDATE_POD_LABEL)),
            ClusterEventWithHint(ClusterEvent(ev.NODE, ev.ADD | ev.UPDATE_NODE_LABEL | ev.DELETE)),
        ]

    # -- constraint selection ----------------------------------------------

    def _constraints_for(self, pod: Pod, action: str) -> list[TopologySpreadConstraint]:
        explicit = [
            c for c in pod.spec.topology_spread_constraints if c.when_unsatisfiable == action
        ]
        if pod.spec.topology_spread_constraints:
            return explicit
        defaults = self.default_constraints or (
            _SYSTEM_DEFAULT_CONSTRAINTS if self.system_defaulting else ()
        )
        out = []
        for c in defaults:
            if c.when_unsatisfiable != action:
                continue
            sel = c.label_selector or LabelSelector.of(dict(pod.meta.labels))
            out.append(
                TopologySpreadConstraint(c.max_skew, c.topology_key, c.when_unsatisfiable, sel)
            )
        return out

    # -- prefilter: build domain counts -------------------------------------

    def pre_filter(self, state, pod: Pod, nodes: list[NodeInfo]):
        constraints = self._constraints_for(pod, DO_NOT_SCHEDULE)
        if not constraints:
            return None, Status.skip()
        s = _PreFilterState()
        s.constraints = constraints
        for c in constraints:
            sel = _self_selector(pod, c)
            counts: dict[str, int] = {}
            for ni in nodes:
                node = ni.node
                if node is None:
                    continue
                val = node.meta.labels.get(c.topology_key)
                if val is None:
                    continue  # nodes without the key are not domains
                # node-affinity honored domains (filtering.go: nodeaffinity check)
                counts.setdefault(val, 0)
                for pi in ni.iter_pods():
                    if pi.pod.meta.namespace != pod.meta.namespace:
                        continue
                    if pi.pod.is_terminating:
                        continue
                    if sel.matches(pi.pod.meta.labels):
                        counts[val] += 1
            s.domain_counts.append(counts)
            s.min_counts.append(min(counts.values()) if counts else 0)
            s.self_matches.append(1 if sel.matches(pod.meta.labels) else 0)
        state.write(self.PRE_FILTER_KEY, s)
        return None, Status()

    def filter(self, state, pod: Pod, node_info: NodeInfo) -> Status:
        s: _PreFilterState | None = state.read(self.PRE_FILTER_KEY)
        if s is None:
            return Status()
        node = node_info.node
        if node is None:
            return Status.unschedulable("node not found", plugin=self.name)
        for i, c in enumerate(s.constraints):
            val = node.meta.labels.get(c.topology_key)
            if val is None:
                return Status.unresolvable(
                    f"node(s) didn't have required label {c.topology_key}", plugin=self.name
                )
            count = s.domain_counts[i].get(val, 0)
            skew = count + s.self_matches[i] - s.min_counts[i]
            if skew > c.max_skew:
                return Status.unschedulable(
                    "node(s) didn't match pod topology spread constraints",
                    plugin=self.name,
                )
        return Status()

    # -- AddPod/RemovePod extensions (nominated pods, preemption dry-runs) ---

    def add_pod(self, state, pod: Pod, pod_info_to_add: PodInfo, node_info: NodeInfo) -> Status:
        return self._update(state, pod, pod_info_to_add, node_info, +1)

    def remove_pod(self, state, pod: Pod, pod_info_to_remove: PodInfo, node_info: NodeInfo) -> Status:
        return self._update(state, pod, pod_info_to_remove, node_info, -1)

    def _update(self, state, pod, pi: PodInfo, node_info: NodeInfo, delta: int) -> Status:
        s: _PreFilterState | None = state.read(self.PRE_FILTER_KEY)
        if s is None or node_info.node is None:
            return Status()
        for i, c in enumerate(s.constraints):
            val = node_info.node.meta.labels.get(c.topology_key)
            if val is None or val not in s.domain_counts[i]:
                continue
            if pi.pod.meta.namespace != pod.meta.namespace:
                continue
            if _self_selector(pod, c).matches(pi.pod.meta.labels):
                s.domain_counts[i][val] += delta
                s.recompute_min(i)
        return Status()

    # -- score ---------------------------------------------------------------

    def pre_score(self, state, pod: Pod, nodes: list[NodeInfo]) -> Status:
        constraints = self._constraints_for(pod, SCHEDULE_ANYWAY)
        if not constraints:
            return Status.skip()
        per_constraint: list[tuple[TopologySpreadConstraint, dict[str, int], int]] = []
        for c in constraints:
            sel = _self_selector(pod, c)
            counts: dict[str, int] = {}
            for ni in nodes:
                node = ni.node
                if node is None:
                    continue
                val = node.meta.labels.get(c.topology_key)
                if val is None:
                    continue
                counts.setdefault(val, 0)
                for pi in ni.iter_pods():
                    if (
                        pi.pod.meta.namespace == pod.meta.namespace
                        and not pi.pod.is_terminating
                        and sel.matches(pi.pod.meta.labels)
                    ):
                        counts[val] += 1
            per_constraint.append((c, counts, 1 if sel.matches(pod.meta.labels) else 0))
        state.write(self.PRE_SCORE_KEY, per_constraint)
        return Status()

    def score(self, state, pod: Pod, node_info: NodeInfo):
        """scoring.go:221 — lower matching count on the node's domains = better;
        raw score here is the *cost*, inverted in normalize."""
        per_constraint = state.read(self.PRE_SCORE_KEY)
        if not per_constraint:
            return 0, Status()
        node = node_info.node
        if node is None:
            return 0, Status()
        # float32 fixed op order — the canonical spec mirrored by the device
        # kernel (ops/kernels.py _pts_score); math.log would be float64 and
        # could truncate differently at int() boundaries.
        cost = np.float32(0.0)
        for c, counts, _self_match in per_constraint:
            val = node.meta.labels.get(c.topology_key)
            if val is None:
                continue
            count = counts.get(val, 0)
            ndomains = len(counts)
            # topologyNormalizingWeight (scoring.go:305)
            weight = np.log(np.float32(ndomains + 2))
            cost = cost + np.float32(count) * weight
        return int(cost), Status()

    def normalize_score(self, state, pod: Pod, scores) -> Status:
        """scoring.go:262 — invert: maxCost -> 0, minCost -> 100."""
        vals = [s for _, s in scores]
        if not vals:
            return Status()
        max_cost, min_cost = max(vals), min(vals)
        spread = max_cost - min_cost
        for row in scores:
            if spread == 0:
                row[1] = MAX_NODE_SCORE
            else:
                row[1] = MAX_NODE_SCORE * (max_cost - row[1]) // spread
        return Status()

    def sign(self, pod: Pod) -> str | None:
        cs = pod.spec.topology_spread_constraints
        return ";".join(
            f"{c.topology_key}:{c.max_skew}:{c.when_unsatisfiable}:"
            f"{c.label_selector.canonical() if c.label_selector else ''}"
            for c in cs
        )
