"""The 3-tier priority scheduling queue with QueueingHint-driven requeue.

Reference: pkg/scheduler/backend/queue/scheduling_queue.go (PriorityQueue),
active_queue.go (in-flight pods + in-flight cluster events), backoff_queue.go
(separate error vs unschedulable exponential backoff), unschedulable_pods.go.

Tiers:
- activeQ:           heap ordered by the QueueSort plugin; Pop() blocks here.
- backoffQ:          heap ordered by backoff expiry; flushed to activeQ.
- unschedulablePods: parked pods waiting for a cluster event that a rejecting
                     plugin's QueueingHintFn says could make them schedulable.

In-flight event tracking: events arriving while a pod is mid-cycle are
recorded and replayed when the pod comes back unschedulable, so concurrent
cluster changes are never lost (active_queue.go:378-450).
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, Callable, Iterable

from ...api.types import Pod
from ...utils.clock import Clock
from ..framework import events as fwk_events
from ..framework.events import ClusterEvent, ClusterEventWithHint, QUEUE
from ..framework.interface import Status
from ..nodeinfo import PodInfo
from .heap import KeyedHeap

DEFAULT_POD_INITIAL_BACKOFF = 1.0  # scheduling_queue.go:79
DEFAULT_POD_MAX_BACKOFF = 10.0  # scheduling_queue.go:83
DEFAULT_MAX_IN_UNSCHEDULABLE_PODS = 300.0  # scheduling_queue.go:66


class QueuedPodInfo:
    """Reference: staging/.../framework/types.go QueuedPodInfo :316-331."""

    __slots__ = (
        "pod_info",
        "timestamp",
        "initial_attempt_timestamp",
        "attempts",
        "unschedulable_count",
        "consecutive_errors_count",
        "gated",
        "gating_plugin",
        "unschedulable_plugins",
        "pending_plugins",
        "backoff_expiry",
        "inflight_token",
    )

    def __init__(self, pod_info: PodInfo, now: float):
        self.pod_info = pod_info
        self.timestamp = now
        self.initial_attempt_timestamp: float | None = None
        self.attempts = 0
        self.unschedulable_count = 0
        self.consecutive_errors_count = 0
        self.gated = False
        self.gating_plugin = ""
        self.unschedulable_plugins: set[str] = set()
        self.pending_plugins: set[str] = set()
        self.backoff_expiry = 0.0
        self.inflight_token = None  # _InFlightPod of the CURRENT attempt

    @property
    def pod(self) -> Pod:
        return self.pod_info.pod

    @property
    def key(self) -> str:
        return self.pod_info.key


class _InFlightPod:
    __slots__ = ("key", "event_seq")

    def __init__(self, key: str, event_seq: int):
        self.key = key
        self.event_seq = event_seq


class SchedulingQueue:
    # fleet ownership predicate at queue admission (installed by
    # scheduler/fleet.py, the sole writer — kubesched-lint FLEET01):
    # None = admit everything. A non-owned pod never enters any tier.
    shard_filter = None

    def __init__(
        self,
        less_fn: Callable[[QueuedPodInfo, QueuedPodInfo], bool],
        clock: Clock | None = None,
        pre_enqueue_plugins: list | None = None,
        queueing_hint_map: dict[str, list[ClusterEventWithHint]] | None = None,
        pod_initial_backoff: float = DEFAULT_POD_INITIAL_BACKOFF,
        pod_max_backoff: float = DEFAULT_POD_MAX_BACKOFF,
        pod_max_in_unschedulable_pods: float = DEFAULT_MAX_IN_UNSCHEDULABLE_PODS,
        pop_from_backoff: bool = True,
    ):
        self._clock = clock or Clock()
        self._mu = threading.Condition()
        self._active = KeyedHeap[QueuedPodInfo](lambda q: q.key, less_fn)
        self._backoff = KeyedHeap[QueuedPodInfo](
            lambda q: q.key, lambda a, b: a.backoff_expiry < b.backoff_expiry
        )
        # error backoffs live in their OWN heap (backoff_queue.go
        # podErrorBackoffQ): pop-from-backoff must never short-circuit an
        # error backoff — it exists to protect the apiserver
        self._error_backoff = KeyedHeap[QueuedPodInfo](
            lambda q: q.key, lambda a, b: a.backoff_expiry < b.backoff_expiry
        )
        self._unschedulable: dict[str, QueuedPodInfo] = {}
        self._pre_enqueue = pre_enqueue_plugins or []
        # plugin name -> its registered events+hints
        self._hint_map = queueing_hint_map or {}
        self._initial_backoff = pod_initial_backoff
        self._max_backoff = pod_max_backoff
        # SchedulerPopFromBackoffQ (kube_features.go:913, default on since
        # 1.33): an idle scheduler pops the earliest-expiry backoff pod
        # instead of sleeping out the window — retries (nominated
        # preemptors especially) stop paying whole backoff windows
        self._pop_from_backoff = pop_from_backoff
        self._max_unschedulable_duration = pod_max_in_unschedulable_pods
        # in-flight tracking
        self._event_seq = itertools.count(1)
        self._event_log: list[tuple[int, ClusterEvent, Any, Any]] = []
        self._in_flight: dict[str, _InFlightPod] = {}
        self._min_inflight_seq: int | None = None  # gc cache (monotonic)
        self._closed = False
        self.moved_count = 0  # schedulingCycle counter for AddUnschedulableIfNotPresent
        # nominator (backend/queue/nominator.go)
        self._nominated: dict[str, tuple[str, PodInfo]] = {}  # key -> (node, info)

    # -- helpers -----------------------------------------------------------

    def _run_pre_enqueue(self, qpi: QueuedPodInfo) -> bool:
        """Returns True if admitted to activeQ; sets gated on rejection."""
        for pl in self._pre_enqueue:
            st: Status | None = pl.pre_enqueue(qpi.pod)
            if st is not None and not st.is_success:
                qpi.gated = True
                qpi.gating_plugin = pl.name
                qpi.unschedulable_plugins.add(pl.name)
                return False
        qpi.gated = False
        qpi.gating_plugin = ""
        return True

    # backoffQ ordering window (backoff_queue.go:38): expiries snap to
    # window boundaries so same-window pods flush together and ordering is
    # stable under arrival jitter. The reference uses 1s because its flush
    # ticker fires once per second; our flusher is pop-driven, so a 100ms
    # window gives the same ordering stability without stretching every
    # retry by up to a second.
    BACKOFF_ORDERING_WINDOW = 0.1

    def _align_to_window(self, t: float) -> float:
        """alignToWindow (backoff_queue.go:140): expiries snap to window
        boundaries so whole windows flush together. We snap UP — a backoff
        may stretch to the next boundary but can never run SHORTER than
        computed (flooring against a raw now would cut it by up to a
        window)."""
        w = self.BACKOFF_ORDERING_WINDOW
        return -(-t // w) * w

    def _backoff_duration(self, qpi: QueuedPodInfo) -> float:
        """backoff_queue.go getBackoffTime:217-246 — the error count drives
        the exponent while the LAST cycle errored (it resets on a plain
        unschedulable rejection); otherwise the unschedulable count does."""
        count = qpi.unschedulable_count
        if qpi.consecutive_errors_count > 0:
            count = qpi.consecutive_errors_count
        if count == 0:
            return 0.0
        # cap the exponent before floating: a long failure streak must
        # saturate at max backoff, not overflow
        duration = self._initial_backoff * (2 ** min(count - 1, 40))
        return min(duration, self._max_backoff)

    def _move_to_active_or_backoff_locked(self, qpi: QueuedPodInfo, event_label: str) -> None:
        now = self._clock.now()
        if qpi.pending_plugins:
            # Pending (vs Unschedulable) skips backoff (scheduling_queue.go —
            # hinted by a plugin that declared the pod schedulable now)
            self._active.add(qpi)
            self._mu.notify()
            return
        duration = self._backoff_duration(qpi)
        expiry = self._align_to_window(qpi.timestamp + duration)
        if duration > 0 and expiry > now:
            qpi.backoff_expiry = expiry
            if qpi.consecutive_errors_count > 0:
                self._error_backoff.add(qpi)
            else:
                self._backoff.add(qpi)
        else:
            self._active.add(qpi)
            self._mu.notify()

    # -- public API --------------------------------------------------------

    def add(self, pod: Pod, pod_info: PodInfo | None = None) -> None:
        from ...api.resource import ResourceNames

        sf = self.shard_filter
        if sf is not None and not sf(pod):
            return  # a peer's shard: its owner queues it
        with self._mu:
            pi = pod_info or PodInfo(pod, ResourceNames())
            qpi = QueuedPodInfo(pi, self._clock.now())
            if self._run_pre_enqueue(qpi):
                self._active.add(qpi)
                self._mu.notify()
            else:
                self._unschedulable[qpi.key] = qpi

    def update(self, old_pod: Pod | None, new_pod: Pod) -> None:
        """Refresh the stored pod object wherever it is queued; a gated pod is
        re-evaluated through PreEnqueue (scheduling_queue.go Update)."""
        with self._mu:
            key = new_pod.meta.key
            for heap in (self._active, self._backoff, self._error_backoff):
                qpi = heap.get(key)
                if qpi is not None:
                    qpi.pod_info.pod = new_pod
                    return
            qpi = self._unschedulable.get(key)
            if qpi is not None:
                qpi.pod_info.pod = new_pod
                if qpi.gated and self._run_pre_enqueue(qpi):
                    del self._unschedulable[key]
                    qpi.timestamp = self._clock.now()
                    self._active.add(qpi)
                    self._mu.notify()
                return
            if key not in self._in_flight:
                self.add(new_pod)

    def delete(self, pod: Pod) -> None:
        with self._mu:
            key = pod.meta.key
            self._active.delete(key)
            self._backoff.delete(key)
            self._error_backoff.delete(key)
            self._unschedulable.pop(key, None)
            self._nominated.pop(key, None)

    def pop(self, timeout: float | None = None) -> QueuedPodInfo | None:
        with self._mu:
            self._flush_backoff_locked()
            while (len(self._active) == 0 and not self._closed
                   and not (self._pop_from_backoff and len(self._backoff))):
                if not self._mu.wait(timeout=timeout if timeout is not None else 0.1):
                    if timeout is not None:
                        return None
                self._flush_backoff_locked()
                if (timeout is not None and len(self._active) == 0
                        and not (self._pop_from_backoff
                                 and len(self._backoff))):
                    return None
            if self._closed:
                return None
            if len(self._active):
                qpi = self._active.pop()
            else:
                # activeQ drained: pop the earliest-expiry backoff pod
                # early (backoff_queue.go popBackoffQ semantics)
                qpi = self._backoff.pop()
            qpi.attempts += 1
            # each attempt reports its OWN rejectors (the reference replaces
            # UnschedulablePlugins per failure, never accumulates): a stale
            # set would misclassify a later error as a plugin rejection and
            # park a retriable pod
            qpi.unschedulable_plugins = set()
            qpi.pending_plugins = set()
            if qpi.initial_attempt_timestamp is None:
                qpi.initial_attempt_timestamp = self._clock.now()
            qpi.inflight_token = self._insert_in_flight_locked(qpi.key)
            return qpi

    def pop_specific(self, key: str) -> QueuedPodInfo | None:
        """Remove a specific pod from whichever tier holds it (gang popping,
        scheduling_queue.go PopSpecificPod:1017)."""
        with self._mu:
            qpi = (self._active.delete(key) or self._backoff.delete(key)
                   or self._error_backoff.delete(key))
            if qpi is None:
                qpi = self._unschedulable.pop(key, None)
            if qpi is None:
                return None
            qpi.attempts += 1
            qpi.unschedulable_plugins = set()
            qpi.pending_plugins = set()
            if qpi.initial_attempt_timestamp is None:
                qpi.initial_attempt_timestamp = self._clock.now()
            qpi.inflight_token = self._insert_in_flight_locked(qpi.key)
            return qpi

    def _insert_in_flight_locked(self, key: str) -> "_InFlightPod":
        """Record a popped pod as in-flight. Delete-before-insert keeps the
        dict ordered by seq even when a key is RE-popped while an earlier
        incarnation is still in flight (delete+recreate racing an async
        binding) — a plain assignment would keep the key's OLD position
        with the NEW (largest) seq, and the O(1) first-entry min in
        _gc_event_log_locked would then overstate the minimum and drop
        event-log entries other in-flight pods still need. The displaced
        incarnation's seq is GC'd immediately: a stale cached minimum
        pointing at a seq nobody holds would disable log GC until the
        in-flight set empties."""
        old = self._in_flight.pop(key, None)
        if old is not None:
            self._gc_event_log_locked(old.event_seq)
        rec = _InFlightPod(key, next(self._event_seq))
        self._in_flight[key] = rec
        return rec

    def done(self, key: str, token=None) -> None:
        """Finish a pod's cycle. `token` (QueuedPodInfo.inflight_token) pins
        the call to ONE incarnation: when a pod was deleted + recreated under
        the same key while the first incarnation was mid-binding, the first
        incarnation's done() must not pop the second's in-flight record (its
        mid-flight events would then never replay)."""
        with self._mu:
            p = self._in_flight.get(key)
            if p is None:
                self._gc_event_log_locked(None)
                return
            if token is not None and p is not token:
                # a newer incarnation owns the record; ours was displaced
                # (and GC'd) at its re-pop — nothing to do
                return
            del self._in_flight[key]
            self._gc_event_log_locked(p.event_seq)

    def _gc_event_log_locked(self, removed_seq: int | None = None) -> None:
        """Amortized: event seqs are monotonic, so the in-flight minimum
        only moves when the CURRENT minimum leaves — recomputing it on
        every done() made wave draining O(wave²) in in-flight scans."""
        if not self._event_log:
            if not self._in_flight:
                self._min_inflight_seq = None
            elif (removed_seq is not None
                  and removed_seq == self._min_inflight_seq):
                # the cached minimum just left while the log was empty: a
                # stale cache would satisfy `removed_seq > min` for every
                # later pod (seqs are monotonic) and disable GC forever
                self._min_inflight_seq = None
            return
        if not self._in_flight:
            self._event_log.clear()
            self._min_inflight_seq = None
            return
        if (self._min_inflight_seq is not None and removed_seq is not None
                and removed_seq > self._min_inflight_seq):
            return  # the min didn't change; the log can't shrink
        # seqs are assigned monotonically at insert and dicts preserve
        # insertion order, so the oldest in-flight pod is the FIRST entry —
        # an O(1) read where min() over values made head-of-line done()
        # calls (a draining wave) O(wave²)
        self._min_inflight_seq = next(
            iter(self._in_flight.values())
        ).event_seq
        self._event_log = [
            e for e in self._event_log if e[0] > self._min_inflight_seq
        ]

    def add_unschedulable_if_not_present(self, qpi: QueuedPodInfo, pod_scheduling_cycle: int) -> None:
        """Return a pod after a failed attempt (scheduling_queue.go:905).

        Replays cluster events that fired while the pod was in flight; if any
        matches a rejecting plugin's hint, the pod re-enters backoff/active
        instead of parking in unschedulablePods.
        """
        with self._mu:
            key = qpi.key
            inflight = self._in_flight.get(key)
            if (inflight is not None and qpi.inflight_token is not None
                    and inflight is not qpi.inflight_token):
                # the record belongs to a NEWER incarnation of this key
                # (delete+recreate raced our binding); leave it for them
                inflight = None
            elif inflight is not None:
                del self._in_flight[key]
            qpi.timestamp = self._clock.now()
            # scheduling_queue.go:924-932 — rejected by no plugin means an
            # unexpected error (backoff counts errors); a plugin rejection
            # resets the error streak
            if not qpi.unschedulable_plugins and not qpi.pending_plugins:
                qpi.consecutive_errors_count += 1
            else:
                qpi.unschedulable_count += 1
                qpi.consecutive_errors_count = 0
            removed_seq = inflight.event_seq if inflight is not None else None
            if qpi.gated:
                self._unschedulable[key] = qpi
                self._gc_event_log_locked(removed_seq)
                return
            requeue = False
            if inflight is not None:
                for seq, ev, old, new in self._event_log:
                    if seq <= inflight.event_seq:
                        continue
                    if self._is_worth_requeuing(qpi, ev, old, new):
                        requeue = True
                        break
            self._gc_event_log_locked(removed_seq)
            if not requeue and not qpi.unschedulable_plugins and not qpi.pending_plugins:
                # rejected by no plugin (scheduler/bind error): retriable — go
                # through backoff, never park (reference: backoffQ for errors)
                requeue = True
            if requeue:
                self._move_to_active_or_backoff_locked(qpi, "inflight-event")
            else:
                self._unschedulable[key] = qpi

    def _is_worth_requeuing(self, qpi: QueuedPodInfo, ev: ClusterEvent, old: Any, new: Any) -> bool:
        """scheduling_queue.go isPodWorthRequeuing:488 — consult only the hint
        functions of plugins that rejected this pod."""
        rejectors = qpi.unschedulable_plugins | qpi.pending_plugins
        if not rejectors:
            return True  # rejected by no plugin (e.g. error) — any event helps
        for plugin_name in rejectors:
            for ewh in self._hint_map.get(plugin_name, []):
                if not ewh.event.match(ev):
                    continue
                if ewh.queueing_hint_fn is None:
                    return True
                try:
                    if ewh.queueing_hint_fn(qpi.pod, old, new) == QUEUE:
                        return True
                except Exception:
                    return True  # hint error -> requeue (fail open)
        return False

    def move_all_to_active_or_backoff(self, ev: ClusterEvent, old: Any = None, new: Any = None,
                                      precheck: Callable[[QueuedPodInfo], bool] | None = None) -> None:
        """Cluster event arrived: requeue matching unschedulable pods
        (scheduling_queue.go MoveAllToActiveOrBackoffQueue:1273)."""
        with self._mu:
            self._event_log.append((next(self._event_seq), ev, old, new))
            self.moved_count += 1
            moved = []
            for key, qpi in self._unschedulable.items():
                if qpi.gated:
                    # A gated pod re-runs PreEnqueue when an event matches its
                    # gating plugin's registered events (reference: gated pods
                    # are re-admitted event-driven, not only on pod update).
                    rejectors = qpi.unschedulable_plugins | {qpi.gating_plugin}
                    saved = qpi.unschedulable_plugins
                    qpi.unschedulable_plugins = rejectors
                    worth = ev.resource == fwk_events.WILDCARD or self._is_worth_requeuing(
                        qpi, ev, old, new
                    )
                    qpi.unschedulable_plugins = saved
                    if worth and self._run_pre_enqueue(qpi):
                        moved.append(key)
                    continue
                if precheck is not None and not precheck(qpi):
                    continue
                if ev.resource == fwk_events.WILDCARD or self._is_worth_requeuing(qpi, ev, old, new):
                    moved.append(key)
            for key in moved:
                # backoff expiry counts from the rejection timestamp, so a pod
                # parked longer than its backoff goes straight to activeQ
                qpi = self._unschedulable.pop(key)
                self._move_to_active_or_backoff_locked(qpi, str(ev))

    def activate(self, pods: Iterable[Pod]) -> None:
        """Force pods into activeQ (gang siblings, Permit allow)."""
        with self._mu:
            for pod in pods:
                key = pod.meta.key
                qpi = (self._unschedulable.pop(key, None)
                       or self._backoff.delete(key)
                       or self._error_backoff.delete(key))
                if qpi is None:
                    continue
                qpi.timestamp = self._clock.now()
                self._active.add(qpi)
            self._mu.notify_all()

    def prune(self, keep: Callable[[Pod], bool]) -> int:
        """Drop every QUEUED pod failing `keep` from all three tiers (a
        fleet member losing a shard lease calls this before its next pop —
        the new owner requeues the pods from store truth). In-flight pods
        are left alone: their cycle resolves through the pop-side shard
        gate and the store's CAS, never by yanking state mid-cycle."""
        removed = 0
        with self._mu:
            for heap in (self._active, self._backoff, self._error_backoff):
                for key in list(heap.keys()):
                    qpi = heap.get(key)
                    if qpi is not None and not keep(qpi.pod):
                        heap.delete(key)
                        self._nominated.pop(key, None)
                        removed += 1
            for key in [k for k, q in self._unschedulable.items()
                        if not keep(q.pod)]:
                del self._unschedulable[key]
                self._nominated.pop(key, None)
                removed += 1
        return removed

    def _flush_backoff_locked(self) -> None:
        now = self._clock.now()
        for heap in (self._backoff, self._error_backoff):
            while True:
                head = heap.peek()
                if head is None or head.backoff_expiry > now:
                    break
                self._active.add(heap.pop())
                self._mu.notify()

    def flush_unschedulable_leftover(self) -> None:
        """Pods parked longer than podMaxInUnschedulablePodsDuration re-enter
        (scheduling_queue.go flushUnschedulablePodsLeftover:985)."""
        with self._mu:
            now = self._clock.now()
            expired = [
                k
                for k, q in self._unschedulable.items()
                if not q.gated and now - q.timestamp > self._max_unschedulable_duration
            ]
            for k in expired:
                self._move_to_active_or_backoff_locked(self._unschedulable.pop(k), "leftover")

    # -- nominator ----------------------------------------------------------

    def add_nominated_pod(self, pod: Pod, node_name: str, pod_info: PodInfo | None = None) -> None:
        from ...api.resource import ResourceNames

        with self._mu:
            self._nominated[pod.meta.key] = (
                node_name,
                pod_info or PodInfo(pod, ResourceNames()),
            )

    def delete_nominated_pod_if_exists(self, pod: Pod) -> None:
        with self._mu:
            self._nominated.pop(pod.meta.key, None)

    def nominated_pods_for_node(self, node_name: str) -> list[str]:
        with self._mu:
            return [k for k, (n, _) in self._nominated.items() if n == node_name]

    def nominated_pod_info(self, key: str) -> PodInfo | None:
        with self._mu:
            entry = self._nominated.get(key)
            return entry[1] if entry else None

    def nominated_node_for(self, pod: Pod) -> str:
        with self._mu:
            entry = self._nominated.get(pod.meta.key)
            return entry[0] if entry else ""

    def max_nominated_priority(self, exclude_key: str | None = None) -> int | None:
        """Highest priority among nominated pods (optionally excluding one
        pod) — None when nothing is nominated. Drives the TPU backend's
        narrowed fallback: only pods that could be affected by nominated-pod
        protection (schedule_one.go:1190 filters nominated pods of >= the
        incoming pod's priority) leave the kernel path."""
        with self._mu:
            best: int | None = None
            for key, (_n, info) in self._nominated.items():
                if key == exclude_key:
                    continue
                p = info.pod.spec.priority
                if best is None or p > best:
                    best = p
            return best

    def has_nominated_pods(self) -> bool:
        with self._mu:
            return bool(self._nominated)

    # -- introspection -------------------------------------------------------

    def pending_pods(self) -> tuple[int, int, int]:
        with self._mu:
            return (len(self._active),
                    len(self._backoff) + len(self._error_backoff),
                    len(self._unschedulable))

    def has_pod(self, key: str) -> bool:
        with self._mu:
            return (key in self._active or key in self._backoff
                    or key in self._error_backoff
                    or key in self._unschedulable)

    def close(self) -> None:
        with self._mu:
            self._closed = True
            self._mu.notify_all()
