"""Scheduling queue: activeQ / backoffQ / unschedulablePods + QueueingHints.

Reference: pkg/scheduler/backend/queue/.
"""

from .heap import KeyedHeap  # noqa: F401
from .scheduling_queue import (  # noqa: F401
    QueuedPodInfo,
    SchedulingQueue,
    DEFAULT_POD_INITIAL_BACKOFF,
    DEFAULT_POD_MAX_BACKOFF,
)
