"""Keyed binary heap: update/delete by key, peek/pop min.

Reference: pkg/scheduler/backend/heap/heap.go:133 — a heap whose items are
addressable by key so queue updates are O(log n) instead of rebuild.
"""

from __future__ import annotations

from typing import Callable, Generic, TypeVar

T = TypeVar("T")


class KeyedHeap(Generic[T]):
    def __init__(self, key_fn: Callable[[T], str], less_fn: Callable[[T, T], bool]):
        self._key = key_fn
        self._less = less_fn
        self._items: list[T] = []
        self._index: dict[str, int] = {}

    def __len__(self) -> int:
        return len(self._items)

    def __contains__(self, key: str) -> bool:
        return key in self._index

    def get(self, key: str) -> T | None:
        i = self._index.get(key)
        return self._items[i] if i is not None else None

    def add(self, item: T) -> None:
        """Insert or replace by key."""
        key = self._key(item)
        i = self._index.get(key)
        if i is not None:
            self._items[i] = item
            self._sift_up(i)
            self._sift_down(i)
        else:
            self._items.append(item)
            self._index[key] = len(self._items) - 1
            self._sift_up(len(self._items) - 1)

    def delete(self, key: str) -> T | None:
        i = self._index.get(key)
        if i is None:
            return None
        return self._remove_at(i)

    def peek(self) -> T | None:
        return self._items[0] if self._items else None

    def pop(self) -> T | None:
        if not self._items:
            return None
        return self._remove_at(0)

    def list(self) -> list[T]:
        return list(self._items)

    def keys(self) -> list[str]:
        return list(self._index.keys())

    # -- internals ----------------------------------------------------------

    def _remove_at(self, i: int) -> T:
        item = self._items[i]
        key = self._key(item)
        last = len(self._items) - 1
        if i != last:
            self._items[i] = self._items[last]
            self._index[self._key(self._items[i])] = i
        self._items.pop()
        del self._index[key]
        if i < len(self._items):
            self._sift_up(i)
            self._sift_down(i)
        return item

    def _swap(self, i: int, j: int) -> None:
        self._items[i], self._items[j] = self._items[j], self._items[i]
        self._index[self._key(self._items[i])] = i
        self._index[self._key(self._items[j])] = j

    def _sift_up(self, i: int) -> None:
        while i > 0:
            parent = (i - 1) // 2
            if self._less(self._items[i], self._items[parent]):
                self._swap(i, parent)
                i = parent
            else:
                break

    def _sift_down(self, i: int) -> None:
        n = len(self._items)
        while True:
            smallest = i
            for c in (2 * i + 1, 2 * i + 2):
                if c < n and self._less(self._items[c], self._items[smallest]):
                    smallest = c
            if smallest == i:
                return
            self._swap(i, smallest)
            i = smallest
