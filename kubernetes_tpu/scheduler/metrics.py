"""Scheduler metrics: the §2.6 metric set over the shared registry.

Reference: pkg/scheduler/metrics/metrics.go (scheduleAttempts:225,
SchedulingAlgorithmLatency:251, FrameworkExtensionPointDuration:340,
PluginExecutionDuration:351, pendingPods:276, PodSchedulingSLIDuration:312,
PodSchedulingAttempts:323, CacheSize:394, unschedulableReasons:402, batching
BatchAttemptStats:297/GetNodeHintDuration:496, gang
podGroupScheduleAttempts:519) and metric_recorder.go MetricsAsyncRecorder.
"""

from __future__ import annotations

import threading
import time

from ..utils.metrics import Registry

SCHEDULED = "scheduled"
UNSCHEDULABLE = "unschedulable"
ERROR = "error"


class SchedulerMetrics:
    """The facade the scheduler/framework call sites use; every observation
    lands in a Prometheus-style registry exposable at /metrics."""

    def __init__(self, registry: Registry | None = None, profile: str = "default-scheduler"):
        self.registry = registry or Registry()
        self.profile = profile
        r = self.registry
        self.schedule_attempts = r.counter(
            "scheduler_schedule_attempts_total",
            "Number of attempts to schedule pods, by result",
            labels=("result", "profile"), stability="STABLE",
        )
        self.scheduling_attempt_duration = r.histogram(
            "scheduler_scheduling_attempt_duration_seconds",
            "Scheduling attempt latency (algorithm + binding)",
            labels=("result", "profile"), stability="STABLE",
        )
        self.scheduling_algorithm_duration = r.histogram(
            "scheduler_scheduling_algorithm_duration_seconds",
            "Scheduling algorithm latency", stability="ALPHA",
        )
        self.extension_point_duration = r.histogram(
            "scheduler_framework_extension_point_duration_seconds",
            "Latency per extension point",
            labels=("extension_point", "status", "profile"), stability="STABLE",
        )
        self.plugin_execution_duration = r.histogram(
            "scheduler_plugin_execution_duration_seconds",
            "Plugin execution latency per extension point",
            labels=("plugin", "extension_point"), stability="ALPHA",
        )
        self.pending_pods = r.gauge(
            "scheduler_pending_pods",
            "Pending pods by queue (active|backoff|unschedulable|gated)",
            labels=("queue",), stability="STABLE",
        )
        self.pod_scheduling_sli_duration = r.histogram(
            "scheduler_pod_scheduling_sli_duration_seconds",
            "E2e pod scheduling latency from first attempt, by attempt count",
            labels=("attempts",), stability="BETA",
        )
        self.pod_scheduling_attempts = r.histogram(
            "scheduler_pod_scheduling_attempts",
            "Attempts to successfully schedule a pod",
            buckets=(1, 2, 4, 8, 16), stability="STABLE",
        )
        self.cache_size = r.gauge(
            "scheduler_scheduler_cache_size",
            "Nodes/pods/assumed-pods in the cache", labels=("type",),
        )
        self.unschedulable_reasons = r.gauge(
            "scheduler_unschedulable_pods",
            "Unschedulable pods by plugin", labels=("plugin", "profile"),
        )
        self.queue_incoming_pods = r.counter(
            "scheduler_queue_incoming_pods_total",
            "Pods added to queues by event", labels=("queue", "event"),
        )
        self.preemption_attempts = r.counter(
            "scheduler_preemption_attempts_total", "Preemption attempts",
        )
        self.preemption_victims = r.histogram(
            "scheduler_preemption_victims", "Victims per preemption",
            buckets=(1, 2, 4, 8, 16, 32, 64),
        )
        self.goroutines = r.gauge(
            "scheduler_goroutines", "Worker threads by operation", labels=("operation",),
        )
        # batching (fork: metrics.go:297-310,496-517)
        self.batch_attempts = r.counter(
            "scheduler_batch_attempts_total",
            "OpportunisticBatching outcomes", labels=("result",),
        )
        self.get_node_hint_duration = r.histogram(
            "scheduler_get_node_hint_duration_seconds", "GetNodeHint latency",
        )
        self.store_schedule_results_duration = r.histogram(
            "scheduler_store_schedule_results_duration_seconds",
            "StoreScheduleResults latency",
        )
        # gang (fork: metrics.go:519-534)
        self.pod_group_schedule_attempts = r.counter(
            "scheduler_pod_group_schedule_attempts_total",
            "Pod-group cycle outcomes", labels=("result",),
        )
        self.pod_group_algorithm_duration = r.histogram(
            "scheduler_pod_group_scheduling_algorithm_duration_seconds",
            "Pod-group algorithm latency",
        )
        # async API dispatcher (metrics.go:438-457)
        self.async_api_calls = r.counter(
            "scheduler_async_api_call_execution_total",
            "Executed async API calls", labels=("call_type", "result"),
        )
        self.async_api_pending = r.gauge(
            "scheduler_pending_async_api_calls", "Queued async API calls",
        )
        self.async_api_retries = r.histogram(
            "scheduler_async_api_call_attempts",
            "Attempts per async API call that needed retrying",
            labels=("call_type",), buckets=(1, 2, 3, 4, 6, 8),
        )
        self.async_api_backoff_seconds = r.histogram(
            "scheduler_async_api_call_backoff_seconds",
            "Total backoff slept per retried async API call",
            labels=("call_type",),
            buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0),
        )
        # TPU device-path circuit breaker (degradation ladder)
        self.circuit_breaker_state = r.gauge(
            "scheduler_tpu_circuit_breaker_state",
            "TPU device-path breaker state (0=closed 1=half_open 2=open)",
        )
        self.circuit_breaker_transitions = r.counter(
            "scheduler_tpu_circuit_breaker_transitions_total",
            "TPU device-path breaker state transitions",
            labels=("from_state", "to_state"),
        )
        self.wave_injected_faults = r.counter(
            "scheduler_tpu_wave_injected_faults_total",
            "Chaos faults fired during completed waves' flight windows",
        )
        # watch-stream partition self-heal (degradation ladder)
        self.watch_partitions_detected = r.counter(
            "scheduler_watch_partitions_detected_total",
            "Watch-stream partitions the informers detected from revision "
            "continuity and repaired by resync, by kind",
            labels=("kind",),
        )
        self.watch_partition_repair_latency = r.histogram(
            "scheduler_watch_partition_repair_latency_seconds",
            "Time from the first lost event's emit to the repairing resync",
            labels=("kind",),
            buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0),
        )
        # crash-restart recovery (README "Restart & recovery"): what a fresh
        # scheduler's reconcile() resolved from the previous incarnation's
        # mid-flight state, by recovery kind
        self.restart_recoveries = r.counter(
            "scheduler_restart_recoveries_total",
            "Mid-flight crash state a startup reconcile resolved against "
            "store truth, by recovery kind (adopted/forgotten/requeued/"
            "gang_adopt/gang_release/permit_cleared)",
            labels=("kind",),
        )
        # active-active scheduler fleet (scheduler/fleet.py): shard
        # ownership and lease failover
        self.fleet_shards_owned = r.gauge(
            "scheduler_fleet_shards_owned",
            "Shards this fleet member currently holds the lease for",
        )
        self.fleet_size = r.gauge(
            "scheduler_fleet_size",
            "Configured fleet size (total shard count)",
        )
        self.fleet_shard_failovers = r.counter(
            "scheduler_fleet_shard_failovers_total",
            "Orphaned shard leases this member took over from a dead peer",
            labels=("shard",),
        )
        self.fleet_failover_latency = r.histogram(
            "scheduler_fleet_failover_latency_seconds",
            "Lease expiry to shard adoption by a survivor",
            buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0),
        )
        # TPU backend (new: kernel-vs-host path split)
        self.kernel_dispatches = r.counter(
            "scheduler_tpu_kernel_dispatches_total",
            "Pods scheduled by the device kernel vs host fallback",
            labels=("path",),
        )
        # gang waves (README "Gang waves"): fast-path coverage of PodGroup
        # members — "device" rode a gang wave, "host" the per-pod gang cycle
        self.gang_pods_total = r.counter(
            "scheduler_tpu_gang_pods_total",
            "Gang members placed by the device gang wave vs host gang cycle",
            labels=("path",),
        )
        # wave flight recorder (new: per-wave telemetry, README "Observability")
        self.wave_phase_duration = r.histogram(
            "scheduler_tpu_wave_phase_duration_seconds",
            "Batched-wave latency by pipeline phase",
            labels=("phase",),
        )
        self.wave_duration = r.histogram(
            "scheduler_tpu_wave_duration_seconds",
            "End-to-end batched-wave latency (launch to bind)",
        )
        self.wave_dedup_ratio = r.gauge(
            "scheduler_tpu_wave_dedup_ratio",
            "distinct_signature_ratio of the most recent deduped wave",
        )
        self.signature_cache_hits = r.counter(
            "scheduler_tpu_signature_cache_hits_total",
            "Pods that rode a duplicate signature instead of a full score pass",
        )
        self.cross_wave_signatures = r.counter(
            "scheduler_tpu_cross_wave_signatures_total",
            "Signatures reusing device-resident score rows across wave "
            "boundaries, by outcome (hit|miss|eviction)",
            labels=("outcome",),
        )
        self.wave_fallbacks = r.counter(
            "scheduler_tpu_wave_fallbacks_total",
            "Waves that fell back to per-pod host scheduling, by reason",
            labels=("reason",),
        )
        self.slow_wave_captures_total = r.counter(
            "scheduler_tpu_slow_wave_captures_total",
            "Watchdog profile captures of waves exceeding their deadline",
        )
        self.sli_quantiles = r.gauge(
            "scheduler_pod_scheduling_sli_quantile_seconds",
            "Recorded p50/p99 of pod scheduling SLI duration",
            labels=("quantile",), stability="BETA",
        )
        # pod latency ledger (per-pod e2e decomposition; emitted by
        # scheduler/tpu/podlatency.py — OBS02 keeps LEDGER_SERIES in sync)
        self.pod_e2e_latency = r.histogram(
            "scheduler_pod_e2e_latency_seconds",
            "Per-pod end-to-end scheduling latency by ledger segment",
            labels=("segment",),
        )
        self.pod_e2e_latency_quantiles = r.gauge(
            "scheduler_pod_e2e_latency_quantile_seconds",
            "Recorded p50/p99 of per-pod latency by ledger segment",
            labels=("segment", "quantile"), stability="BETA",
        )
        # device telemetry (transfer ledger / compile tracker / memory
        # watermark; emitted by scheduler/tpu/devicetelemetry.py — OBS02
        # keeps its LEDGER_SERIES in sync)
        self.tpu_transfer_bytes = r.counter(
            "scheduler_tpu_transfer_bytes_total",
            "Bytes crossing the host<->device boundary, by direction "
            "(upload|fetch) and transfer plane",
            labels=("direction", "plane"),
        )
        self.tpu_wave_transfer_bytes = r.histogram(
            "scheduler_tpu_wave_transfer_bytes",
            "Per-wave host<->device transfer bytes, by direction",
            labels=("direction",),
            buckets=tuple(float(4 ** i * 1024) for i in range(10)),
        )
        self.tpu_compiles = r.counter(
            "scheduler_tpu_compiles_total",
            "XLA compilations (jit cache misses), by kernel entry point "
            "and shape-signature label",
            labels=("kernel", "shape"),
        )
        self.tpu_compiled_shapes = r.gauge(
            "scheduler_tpu_compiled_shapes",
            "Distinct compiled shape signatures per kernel entry point",
            labels=("kernel",),
        )
        self.tpu_device_memory = r.gauge(
            "scheduler_tpu_device_memory_bytes",
            "Device-resident plane-buffer bytes (source=ledger from seam "
            "accounting, source=jax from memory_stats when available)",
            labels=("source",),
        )
        # pipeline stall profiler (per-wave wall-clock decomposition into
        # overlap + named stall reasons; emitted by
        # scheduler/tpu/stallprofiler.py — OBS04 keeps STALL_SERIES and
        # the STALL_REASONS literal set in sync)
        self.pipeline_stall_seconds = r.histogram(
            "scheduler_tpu_pipeline_stall_seconds",
            "Per-wave streaming-pipeline stall seconds, by reason "
            "(queue_empty|capacity_gate|prep_serialized|device_busy|"
            "flush|bind_backpressure)",
            labels=("reason",),
        )
        self.pipeline_stall_total = r.gauge(
            "scheduler_tpu_pipeline_stall_total_seconds",
            "Cumulative streaming-pipeline stall seconds, by reason",
            labels=("reason",),
        )
        # event recorder (satellite: spill/aggregation visibility)
        self.events_total = r.counter(
            "scheduler_events_total",
            "Events emitted, by disposition (recorded|aggregated)",
            labels=("disposition",),
        )
        self.events_gc_pruned = r.counter(
            "scheduler_events_gc_pruned_total",
            "Event correlation series pruned by TTL garbage collection",
        )
        self._first_attempt: dict[str, float] = {}
        # exact SLI samples for the recorded-quantile gauges (bounded window;
        # the histogram's bucket interpolation is too coarse for a p99 SLO)
        self._sli_samples: list[float] = []
        self._attempt_counts: dict[str, int] = {}
        # plugin -> currently-unschedulable pod keys (true gauge semantics)
        self._unsched_by_plugin: dict[str, set[str]] = {}

    # -- call sites used by the framework/loop -------------------------------

    def observe_plugin(self, extension_point: str, plugin: str, seconds: float) -> None:
        self.plugin_execution_duration.observe(seconds, plugin, extension_point)

    def observe_extension_point(self, point: str, success: bool, seconds: float) -> None:
        self.extension_point_duration.observe(
            seconds, point, "Success" if success else "Error", self.profile
        )

    def attempt_started(self, qpi) -> None:
        key = qpi.pod.meta.key
        self._first_attempt.setdefault(key, time.time())
        self._attempt_counts[key] = self._attempt_counts.get(key, 0) + 1

    def pod_scheduled(self, qpi) -> None:
        key = qpi.pod.meta.key
        self.attempt_started(qpi)
        attempts = self._attempt_counts.pop(key, 1)
        start = self._first_attempt.pop(key, None)
        self.schedule_attempts.inc(SCHEDULED, self.profile)
        self.pod_scheduling_attempts.observe(attempts)
        if start is not None:
            sli = time.time() - start
            self.pod_scheduling_sli_duration.observe(sli, str(min(attempts, 16)))
            self._sli_samples.append(sli)
            if len(self._sli_samples) > 4096:
                del self._sli_samples[:2048]
        self._clear_unschedulable(key)

    def pod_unschedulable(self, qpi) -> None:
        self.attempt_started(qpi)
        self.schedule_attempts.inc(UNSCHEDULABLE, self.profile)
        key = qpi.pod.meta.key
        for plugin in qpi.unschedulable_plugins:
            pods = self._unsched_by_plugin.setdefault(plugin, set())
            if key not in pods:
                pods.add(key)
                self.unschedulable_reasons.set(len(pods), plugin, self.profile)

    def pod_error(self, qpi) -> None:
        self.attempt_started(qpi)
        self.schedule_attempts.inc(ERROR, self.profile)

    def _clear_unschedulable(self, key: str) -> None:
        for plugin, pods in self._unsched_by_plugin.items():
            if key in pods:
                pods.discard(key)
                self.unschedulable_reasons.set(len(pods), plugin, self.profile)

    def forget_pod(self, key: str) -> None:
        """Pod left the system (deleted) — drop all per-pod tracking so
        churn of permanently-unschedulable pods doesn't leak state."""
        self._first_attempt.pop(key, None)
        self._attempt_counts.pop(key, None)
        self._clear_unschedulable(key)

    def update_queue_gauges(self, active: int, backoff: int, unschedulable: int,
                            gated: int = 0) -> None:
        self.pending_pods.set(active, "active")
        self.pending_pods.set(backoff, "backoff")
        self.pending_pods.set(unschedulable, "unschedulable")
        self.pending_pods.set(gated, "gated")

    def update_cache_gauges(self, nodes: int, pods: int, assumed: int) -> None:
        self.cache_size.set(nodes, "nodes")
        self.cache_size.set(pods, "pods")
        self.cache_size.set(assumed, "assumed_pods")

    # -- wave flight recorder call sites -------------------------------------

    def observe_wave_phase(self, phase: str, seconds: float) -> None:
        self.wave_phase_duration.observe(seconds, phase)

    def wave_completed(self, record) -> None:
        """Land a finished WaveRecord's series (flightrecorder.end_wave)."""
        self.wave_duration.observe(record.duration_s)
        for phase, seconds in record.phases.items():
            self.wave_phase_duration.observe(seconds, phase)
        if record.distinct_signature_ratio is not None:
            self.wave_dedup_ratio.set(record.distinct_signature_ratio)
        if record.clones:
            self.signature_cache_hits.inc(by=record.clones)
        if record.xwave_hits:
            self.cross_wave_signatures.inc("hit", by=record.xwave_hits)
        if record.xwave_misses:
            self.cross_wave_signatures.inc("miss", by=record.xwave_misses)
        if record.xwave_evictions:
            self.cross_wave_signatures.inc("eviction", by=record.xwave_evictions)
        if record.fallback_reason:
            # reason cardinality is bounded: strip per-wave detail after ':'
            self.wave_fallbacks.inc(record.fallback_reason.split(":")[0])
        if record.injected_faults:
            self.wave_injected_faults.inc(by=record.injected_faults)
        # device transfer ledger: per-wave byte histograms (getattr-guarded
        # for records predating the telemetry fields)
        upload = getattr(record, "upload_bytes", 0)
        fetch = getattr(record, "fetch_bytes", 0)
        if upload or fetch:
            self.tpu_wave_transfer_bytes.observe(float(upload), "upload")
            self.tpu_wave_transfer_bytes.observe(float(fetch), "fetch")

    def gang_pods(self, path: str, n: int) -> None:
        """Gang members routed down `path` (flightrecorder.count_gang_pods
        is the one caller — wave_completed never lands this counter, so a
        gang wave's record can't double-count its members)."""
        self.gang_pods_total.inc(path, by=float(n))

    def breaker_transition(self, old_state: str, new_state: str) -> None:
        """TPU circuit-breaker state change (flightrecorder fan-out). The
        value map mirrors circuitbreaker.STATE_VALUES — inlined so importing
        metrics never drags the tpu package."""
        self.circuit_breaker_state.set(
            {"closed": 0, "half_open": 1, "open": 2}.get(new_state, -1)
        )
        self.circuit_breaker_transitions.inc(old_state, new_state)

    def slow_wave_captured(self) -> None:
        self.slow_wave_captures_total.inc()

    def partition_detected(self, kind: str, latency_s: float) -> None:
        """A watch-stream partition was detected and repaired
        (flightrecorder fan-out from the informer's partition observer)."""
        self.watch_partitions_detected.inc(kind)
        self.watch_partition_repair_latency.observe(latency_s, kind)

    def restart_recovery(self, kind: str, n: int = 1) -> None:
        """Startup reconcile resolved n pieces of mid-flight crash state of
        the given kind (flightrecorder fan-out from Scheduler.reconcile)."""
        if n:
            self.restart_recoveries.inc(kind, by=float(n))

    def fleet_ownership(self, owned: int, fleet_size: int) -> None:
        """This member's current shard count (flightrecorder fan-out from
        the fleet's acquire/release callbacks)."""
        self.fleet_shards_owned.set(float(owned))
        self.fleet_size.set(float(fleet_size))

    def fleet_failover(self, shard: int, latency_s: float) -> None:
        """An orphaned shard adopted from a dead peer, with lease-expiry
        to adoption latency."""
        self.fleet_shard_failovers.inc(str(shard))
        self.fleet_failover_latency.observe(latency_s)

    def update_sli_quantiles(self) -> None:
        """Record exact p50/p99 over the recent-sample window (the SLO the
        bench gates on; cheap — called once per wave, not per pod)."""
        samples = sorted(self._sli_samples)
        if not samples:
            return
        n = len(samples)
        self.sli_quantiles.set(samples[min(n - 1, int(0.50 * n))], "p50")
        self.sli_quantiles.set(samples[min(n - 1, int(0.99 * n))], "p99")

    # -- event recorder call sites -------------------------------------------

    def event_recorded(self, aggregated: bool) -> None:
        self.events_total.inc("aggregated" if aggregated else "recorded")

    def events_pruned(self, n: int) -> None:
        if n:
            self.events_gc_pruned.inc(by=n)

    def expose(self) -> str:
        return self.registry.expose()


class MetricsAsyncRecorder:
    """metric_recorder.go MetricsAsyncRecorder — observations buffered on the
    hot path, flushed by a background thread once per interval."""

    def __init__(self, metrics: SchedulerMetrics, interval: float = 1.0):
        self.metrics = metrics
        self.interval = interval
        self._buf: list[tuple] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def observe_plugin_duration_async(
        self, extension_point: str, plugin: str, seconds: float
    ) -> None:
        with self._lock:
            self._buf.append((extension_point, plugin, seconds))

    def flush(self) -> None:
        with self._lock:
            buf, self._buf = self._buf, []
        for point, plugin, seconds in buf:
            self.metrics.observe_plugin(point, plugin, seconds)

    def run(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self.flush()
        self.flush()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
