"""Scheduler metrics: the §2.6 metric set over the shared registry.

Reference: pkg/scheduler/metrics/metrics.go (scheduleAttempts:225,
SchedulingAlgorithmLatency:251, FrameworkExtensionPointDuration:340,
PluginExecutionDuration:351, pendingPods:276, PodSchedulingSLIDuration:312,
PodSchedulingAttempts:323, CacheSize:394, unschedulableReasons:402, batching
BatchAttemptStats:297/GetNodeHintDuration:496, gang
podGroupScheduleAttempts:519) and metric_recorder.go MetricsAsyncRecorder.
"""

from __future__ import annotations

import threading
import time

from ..utils.metrics import Registry

SCHEDULED = "scheduled"
UNSCHEDULABLE = "unschedulable"
ERROR = "error"


class SchedulerMetrics:
    """The facade the scheduler/framework call sites use; every observation
    lands in a Prometheus-style registry exposable at /metrics."""

    def __init__(self, registry: Registry | None = None, profile: str = "default-scheduler"):
        self.registry = registry or Registry()
        self.profile = profile
        r = self.registry
        self.schedule_attempts = r.counter(
            "scheduler_schedule_attempts_total",
            "Number of attempts to schedule pods, by result",
            labels=("result", "profile"), stability="STABLE",
        )
        self.scheduling_attempt_duration = r.histogram(
            "scheduler_scheduling_attempt_duration_seconds",
            "Scheduling attempt latency (algorithm + binding)",
            labels=("result", "profile"), stability="STABLE",
        )
        self.scheduling_algorithm_duration = r.histogram(
            "scheduler_scheduling_algorithm_duration_seconds",
            "Scheduling algorithm latency", stability="ALPHA",
        )
        self.extension_point_duration = r.histogram(
            "scheduler_framework_extension_point_duration_seconds",
            "Latency per extension point",
            labels=("extension_point", "status", "profile"), stability="STABLE",
        )
        self.plugin_execution_duration = r.histogram(
            "scheduler_plugin_execution_duration_seconds",
            "Plugin execution latency per extension point",
            labels=("plugin", "extension_point"), stability="ALPHA",
        )
        self.pending_pods = r.gauge(
            "scheduler_pending_pods",
            "Pending pods by queue (active|backoff|unschedulable|gated)",
            labels=("queue",), stability="STABLE",
        )
        self.pod_scheduling_sli_duration = r.histogram(
            "scheduler_pod_scheduling_sli_duration_seconds",
            "E2e pod scheduling latency from first attempt, by attempt count",
            labels=("attempts",), stability="BETA",
        )
        self.pod_scheduling_attempts = r.histogram(
            "scheduler_pod_scheduling_attempts",
            "Attempts to successfully schedule a pod",
            buckets=(1, 2, 4, 8, 16), stability="STABLE",
        )
        self.cache_size = r.gauge(
            "scheduler_scheduler_cache_size",
            "Nodes/pods/assumed-pods in the cache", labels=("type",),
        )
        self.unschedulable_reasons = r.gauge(
            "scheduler_unschedulable_pods",
            "Unschedulable pods by plugin", labels=("plugin", "profile"),
        )
        self.queue_incoming_pods = r.counter(
            "scheduler_queue_incoming_pods_total",
            "Pods added to queues by event", labels=("queue", "event"),
        )
        self.preemption_attempts = r.counter(
            "scheduler_preemption_attempts_total", "Preemption attempts",
        )
        self.preemption_victims = r.histogram(
            "scheduler_preemption_victims", "Victims per preemption",
            buckets=(1, 2, 4, 8, 16, 32, 64),
        )
        self.goroutines = r.gauge(
            "scheduler_goroutines", "Worker threads by operation", labels=("operation",),
        )
        # batching (fork: metrics.go:297-310,496-517)
        self.batch_attempts = r.counter(
            "scheduler_batch_attempts_total",
            "OpportunisticBatching outcomes", labels=("result",),
        )
        self.get_node_hint_duration = r.histogram(
            "scheduler_get_node_hint_duration_seconds", "GetNodeHint latency",
        )
        self.store_schedule_results_duration = r.histogram(
            "scheduler_store_schedule_results_duration_seconds",
            "StoreScheduleResults latency",
        )
        # gang (fork: metrics.go:519-534)
        self.pod_group_schedule_attempts = r.counter(
            "scheduler_pod_group_schedule_attempts_total",
            "Pod-group cycle outcomes", labels=("result",),
        )
        self.pod_group_algorithm_duration = r.histogram(
            "scheduler_pod_group_scheduling_algorithm_duration_seconds",
            "Pod-group algorithm latency",
        )
        # async API dispatcher (metrics.go:438-457)
        self.async_api_calls = r.counter(
            "scheduler_async_api_call_execution_total",
            "Executed async API calls", labels=("call_type", "result"),
        )
        self.async_api_pending = r.gauge(
            "scheduler_pending_async_api_calls", "Queued async API calls",
        )
        # TPU backend (new: kernel-vs-host path split)
        self.kernel_dispatches = r.counter(
            "scheduler_tpu_kernel_dispatches_total",
            "Pods scheduled by the device kernel vs host fallback",
            labels=("path",),
        )
        self._first_attempt: dict[str, float] = {}
        self._attempt_counts: dict[str, int] = {}
        # plugin -> currently-unschedulable pod keys (true gauge semantics)
        self._unsched_by_plugin: dict[str, set[str]] = {}

    # -- call sites used by the framework/loop -------------------------------

    def observe_plugin(self, extension_point: str, plugin: str, seconds: float) -> None:
        self.plugin_execution_duration.observe(seconds, plugin, extension_point)

    def observe_extension_point(self, point: str, success: bool, seconds: float) -> None:
        self.extension_point_duration.observe(
            seconds, point, "Success" if success else "Error", self.profile
        )

    def attempt_started(self, qpi) -> None:
        key = qpi.pod.meta.key
        self._first_attempt.setdefault(key, time.time())
        self._attempt_counts[key] = self._attempt_counts.get(key, 0) + 1

    def pod_scheduled(self, qpi) -> None:
        key = qpi.pod.meta.key
        self.attempt_started(qpi)
        attempts = self._attempt_counts.pop(key, 1)
        start = self._first_attempt.pop(key, None)
        self.schedule_attempts.inc(SCHEDULED, self.profile)
        self.pod_scheduling_attempts.observe(attempts)
        if start is not None:
            self.pod_scheduling_sli_duration.observe(
                time.time() - start, str(min(attempts, 16))
            )
        self._clear_unschedulable(key)

    def pod_unschedulable(self, qpi) -> None:
        self.attempt_started(qpi)
        self.schedule_attempts.inc(UNSCHEDULABLE, self.profile)
        key = qpi.pod.meta.key
        for plugin in qpi.unschedulable_plugins:
            pods = self._unsched_by_plugin.setdefault(plugin, set())
            if key not in pods:
                pods.add(key)
                self.unschedulable_reasons.set(len(pods), plugin, self.profile)

    def pod_error(self, qpi) -> None:
        self.attempt_started(qpi)
        self.schedule_attempts.inc(ERROR, self.profile)

    def _clear_unschedulable(self, key: str) -> None:
        for plugin, pods in self._unsched_by_plugin.items():
            if key in pods:
                pods.discard(key)
                self.unschedulable_reasons.set(len(pods), plugin, self.profile)

    def forget_pod(self, key: str) -> None:
        """Pod left the system (deleted) — drop all per-pod tracking so
        churn of permanently-unschedulable pods doesn't leak state."""
        self._first_attempt.pop(key, None)
        self._attempt_counts.pop(key, None)
        self._clear_unschedulable(key)

    def update_queue_gauges(self, active: int, backoff: int, unschedulable: int,
                            gated: int = 0) -> None:
        self.pending_pods.set(active, "active")
        self.pending_pods.set(backoff, "backoff")
        self.pending_pods.set(unschedulable, "unschedulable")
        self.pending_pods.set(gated, "gated")

    def update_cache_gauges(self, nodes: int, pods: int, assumed: int) -> None:
        self.cache_size.set(nodes, "nodes")
        self.cache_size.set(pods, "pods")
        self.cache_size.set(assumed, "assumed_pods")

    def expose(self) -> str:
        return self.registry.expose()


class MetricsAsyncRecorder:
    """metric_recorder.go MetricsAsyncRecorder — observations buffered on the
    hot path, flushed by a background thread once per interval."""

    def __init__(self, metrics: SchedulerMetrics, interval: float = 1.0):
        self.metrics = metrics
        self.interval = interval
        self._buf: list[tuple] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def observe_plugin_duration_async(
        self, extension_point: str, plugin: str, seconds: float
    ) -> None:
        with self._lock:
            self._buf.append((extension_point, plugin, seconds))

    def flush(self) -> None:
        with self._lock:
            buf, self._buf = self._buf, []
        for point, plugin, seconds in buf:
            self.metrics.observe_plugin(point, plugin, seconds)

    def run(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self.flush()
        self.flush()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
