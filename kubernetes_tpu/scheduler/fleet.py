"""Active-active scheduler fleet: lease-sharded pod ownership.

N `Scheduler` instances run concurrently over ONE store. Pod ownership is
sharded by a stable content hash — `shard_of(namespace, uid) mod N` — and
the shard map is managed through per-shard coordination Leases via the
client-go-shaped elector (`client/leaderelection.py`), one elector per
shard. A member only admits, pops, and binds pods whose shard it holds:
non-owned pods are ignored at `Scheduler._on_pod_event`, at queue
admission, and at the loop's pop-side `_skip_pod_schedule` gate. Every
member's cache still mirrors ALL bound pods (peer binds are foreign
writes that change node occupancy), so scoring planes stay truthful.

Gang members are sharded by their GROUP key, not their own uid: a
PodGroup is always wholly owned by one member, so all-or-nothing
admission is never split across the fleet, and when a peer dies mid-gang
the member that adopts the shard adopts the whole gang (README runbook
"peer died mid-gang — who cleans up?").

Failover is PR 15's restart machinery re-aimed: when a peer stops
renewing, its shard lease expires and a survivor's elector takes it over
(CAS-arbitrated — two survivors racing resolve through the store's
resourceVersion check). The adopter then runs `Scheduler.adopt_shard`:
the existing `reconcile()` sweeps (adopt/forget/requeue, half-bound gang
adopt-or-release, stale permit promote/revert) scoped to the adopted
shard, plus a requeue pass for the orphaned shard's pending pods the
admission gate had been filtering out. Outcomes land on
`restart_recoveries{kind="shard_adopt_*"}`; adoption latency (lease
deadline -> takeover) lands on the failover histogram. Any residual
cross-member bind race resolves through the store's ConflictError on
`bind_pod` — the same arbiter the restart soak leans on — so a pod is
never bound twice.

Ownership state is frozen behind kubesched-lint rule FLEET01: the
FLEET_SHARD_STATE literal below names the attributes only THIS module
may write (the checker cross-parses it project-wide, CRASH01-style).
"""

from __future__ import annotations

import hashlib
from functools import partial
from typing import Callable, Iterable

from ..api.coordination import shard_lease_name
from ..api.types import Pod
from ..client.leaderelection import LeaderElector

# Fleet shard-ownership state (kubesched-lint rule FLEET01): the shard set
# a member holds and the shard filter installed into the scheduler, loop,
# and queue. Exactly ONE writer — this module — or the admission gates,
# the pop gates, and the lease record can disagree about who owns a pod,
# and a disagreement is a double-bind waiting for a watch gap. FLEET01
# cross-parses this literal and flags writes anywhere else.
FLEET_SHARD_STATE = (
    ("_owned_shards", "scheduler/fleet.py"),
    ("shard_filter", "scheduler/fleet.py"),
)


def shard_of(namespace: str, uid: str, fleet_size: int) -> int:
    """Stable shard assignment: blake2b over "namespace/uid", mod N.

    hashlib (not builtin hash()) so the map is identical across processes,
    restarts, and PYTHONHASHSEED — a pod must land on the same shard in
    every member and every incarnation, or ownership is ambiguous."""
    if fleet_size <= 1:
        return 0
    digest = hashlib.blake2b(
        f"{namespace}/{uid}".encode(), digest_size=8
    ).digest()
    return int.from_bytes(digest, "big") % fleet_size


def pod_shard(pod: Pod, fleet_size: int) -> int:
    """A pod's shard. Gang members hash their GROUP key ("namespace/group")
    instead of their own uid so a PodGroup is wholly owned by one member —
    all-or-nothing admission and mid-gang failover never split across the
    fleet."""
    sg = pod.spec.scheduling_group
    if sg is not None:
        return shard_of(pod.meta.namespace, f"group:{sg.pod_group_name}",
                        fleet_size)
    return shard_of(pod.meta.namespace, pod.meta.uid or pod.meta.name,
                    fleet_size)


def install_shard_filter(scheduler, pred: Callable[[Pod], bool]) -> None:
    """Install one ownership predicate into all three gates: informer
    admission (`Scheduler._on_pod_event`), queue admission
    (`SchedulingQueue.add`/`activate`), and the pop-side
    `ScheduleOneLoop._skip_pod_schedule`. The predicate reads the member's
    live shard set, so acquire/release take effect at the next gate check
    without re-installation."""
    scheduler.shard_filter = pred
    scheduler.loop.shard_filter = pred
    scheduler.queue.shard_filter = pred


class FleetMember:
    """One fleet member: a Scheduler plus per-shard electors.

    Lease-managed mode (default): one `LeaderElector` per shard, lease
    names `<base>-shard-<i>`. A member always contends for its PREFERRED
    shard; unclaimed non-preferred shards are scavenged only after a grace
    period (so a booting fleet settles on its preferred map instead of the
    first member hoarding every shard), and expired leases — a dead peer's
    orphans — are taken over immediately. Ownership is sticky: a fresh
    lease is never contested, only renewed by its holder.

    Static mode (`static_shards`): ownership pinned, no leases — the
    `--shard-id`-without-leader-election deployment and the bench's
    election-free capacity measurement.

    Single-threaded by design: `elect_once()` is called from the member's
    scheduling thread (or a soak's drive loop) between scheduling rounds,
    so acquire/release callbacks never race the loop's pops."""

    def __init__(
        self,
        scheduler,
        fleet_size: int,
        identity: str,
        preferred_shard: int | None = None,
        static_shards: Iterable[int] | None = None,
        lease_name: str = "kube-scheduler",
        namespace: str = "kube-system",
        lease_duration: float = 15.0,
        renew_deadline: float = 10.0,
        retry_period: float = 2.0,
        scavenge_after: float | None = None,
        clock=None,
    ):
        self.scheduler = scheduler
        self.fleet_size = max(1, int(fleet_size))
        self.identity = identity
        self.clock = clock if clock is not None else scheduler.clock
        self._static = static_shards is not None
        if preferred_shard is None and not self._static:
            # stable identity-derived preference: the same member prefers
            # the same shard across restarts
            preferred_shard = shard_of(namespace, identity, self.fleet_size)
        self.preferred_shard = (
            preferred_shard % self.fleet_size
            if preferred_shard is not None else None
        )
        # grace before scavenging an unclaimed non-preferred shard: long
        # enough for that shard's preferred member to boot and claim it
        self.scavenge_after = (
            2.0 * lease_duration if scavenge_after is None else scavenge_after
        )
        self._started_at: float | None = None
        self._owned_shards: set[int] = set()
        # shard -> the orphaned lease's deadline, stashed just before a
        # takeover CAS so the acquire callback can stamp failover latency
        self._takeover_expiry: dict[int, float] = {}
        self.electors: dict[int, LeaderElector] = {}
        if self._static:
            self._static_shards = {
                int(s) % self.fleet_size for s in static_shards
            }
        else:
            self._static_shards = set()
            for s in range(self.fleet_size):
                self.electors[s] = LeaderElector(
                    store=scheduler.store,
                    identity=identity,
                    name=shard_lease_name(lease_name, s),
                    namespace=namespace,
                    lease_duration=lease_duration,
                    renew_deadline=renew_deadline,
                    retry_period=retry_period,
                    clock=self.clock,
                    on_started_leading=partial(self._shard_acquired, s),
                    on_stopped_leading=partial(self._shard_released, s),
                )
        install_shard_filter(scheduler, self.owns_pod)

    # -- ownership reads (free everywhere) --------------------------------

    def owns_pod(self, pod: Pod) -> bool:
        """The installed shard filter: does this member own `pod` NOW?"""
        return pod_shard(pod, self.fleet_size) in self._owned_shards

    def owned_shards(self) -> set[int]:
        return set(self._owned_shards)

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        """Sync informers + reconcile scoped to owned shards (none yet in
        lease mode — each acquisition runs its own scoped adoption), then
        run the first election round."""
        self._started_at = self.clock.now()
        self.scheduler.start()
        if self._static:
            for s in sorted(self._static_shards):
                self._shard_acquired(s)
        else:
            self.elect_once()

    def stop(self) -> None:
        """Clean shutdown: release every held lease so peers can adopt the
        shards immediately instead of waiting out the lease duration."""
        for elector in self.electors.values():
            elector.release()

    def crash(self) -> None:
        """Process death, in-process (the fleet soak's peer kill): no lease
        release, no drain — the orphaned shards stay on record until their
        leases expire and a survivor adopts them."""
        dispatcher = getattr(self.scheduler, "api_dispatcher", None)
        if dispatcher is not None:
            try:
                dispatcher.close()
            except Exception:  # noqa: BLE001 — the corpse may be inconsistent
                pass
        try:
            self.scheduler.informers.stop_all()
        except Exception:  # noqa: BLE001
            pass

    # -- election ---------------------------------------------------------

    def elect_once(self) -> set[int]:
        """One election round over every shard: renew held leases, contend
        for the preferred shard, scavenge unclaimed shards past the grace,
        take over expired (orphaned) ones. Returns the owned set."""
        if self._static:
            return set(self._owned_shards)
        now = self.clock.now()
        for shard, elector in self.electors.items():
            if elector.is_leader():
                # renew; a failed round steps down via run_once, firing
                # _shard_released before this member's next pop
                elector.run_once()
                continue
            lease = elector._get_lease()
            if lease is None or not lease.spec.holder_identity:
                # unclaimed (never created, or cleanly released): preferred
                # member takes it now, others only past the scavenge grace
                if shard == self.preferred_shard or self._past_grace(now):
                    elector.run_once()
                continue
            if lease.spec.holder_identity == self.identity:
                # ours on record (a stepped-down term): reclaim
                elector.run_once()
                continue
            if not lease.spec.expired(now):
                continue  # a live peer's shard: ownership is sticky
            # orphaned shard — the holder stopped renewing. Stash the dead
            # term's deadline so the acquire callback stamps failover
            # latency, then contend (CAS arbitrates racing survivors).
            self._takeover_expiry[shard] = lease.spec.deadline()
            try:
                elector.run_once()
            finally:
                self._takeover_expiry.pop(shard, None)
        return set(self._owned_shards)

    def _past_grace(self, now: float) -> bool:
        return (self._started_at is not None
                and now - self._started_at >= self.scavenge_after)

    # -- acquire/release callbacks (fired inside the electors) ------------

    def _shard_pred(self, shard: int) -> Callable[[Pod], bool]:
        return lambda pod: pod_shard(pod, self.fleet_size) == shard

    def _shard_acquired(self, shard: int) -> None:
        self._owned_shards.add(shard)
        recorder = self.scheduler.flight_recorder
        recorder.shard_ownership(len(self._owned_shards), self.fleet_size)
        expiry = self._takeover_expiry.pop(shard, None)
        # adopt the shard: scoped reconcile sweeps + requeue of pending
        # pods the admission gate had been filtering out. Orphan takeovers
        # count on restart_recoveries{kind="shard_adopt_*"}; first
        # acquisitions on the quieter "shard_acquire_*" kinds.
        prefix = "shard_adopt_" if expiry is not None else "shard_acquire_"
        self.scheduler.adopt_shard(self._shard_pred(shard),
                                   kind_prefix=prefix)
        if expiry is not None:
            latency = max(0.0, self.clock.now() - expiry)
            recorder.shard_failover(shard, latency)

    def _shard_released(self, shard: int) -> None:
        self._owned_shards.discard(shard)
        recorder = self.scheduler.flight_recorder
        recorder.shard_ownership(len(self._owned_shards), self.fleet_size)
        # the lost term must not bind: poison any in-flight wave (its pods
        # may belong to the lost shard) and drop the shard's queued pods
        # BEFORE the loop's next pop — the new owner requeues them from
        # store truth through its own adoption sweep
        self.scheduler.loop.mark_wave_external(poison=True)
        self.scheduler.queue.prune(self.owns_pod)
