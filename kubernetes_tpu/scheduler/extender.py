"""HTTP extender: legacy out-of-process filter/prioritize/bind webhook.

Reference: pkg/scheduler/extender.go (NewHTTPExtender:88, Filter:249,
Prioritize:320, Bind:362) with wire types from
staging/src/k8s.io/kube-scheduler/extender/v1/types.go (ExtenderArgs:73,
ExtenderFilterResult:88, HostPriorityList:132, MaxExtenderPriority=10:29).

The extender is the architectural precedent for out-of-process scheduling
backends: the TPU sidecar design (SURVEY.md §5.8) mirrors this hook with
device-resident tensors instead of HTTP round-trips. Kept here for API parity
and for composing third-party scorers with the kernel path.
"""

from __future__ import annotations

import http.client
import json
import urllib.error
import urllib.request
from dataclasses import dataclass

from ..api.types import Pod
from .framework.interface import MAX_NODE_SCORE, Status
from .nodeinfo import NodeInfo

MAX_EXTENDER_PRIORITY = 10  # extender/v1/types.go:29

# every way a webhook round-trip can fail: transport, protocol, malformed
# JSON (ValueError covers JSONDecodeError), or missing response keys
EXTENDER_ERRORS = (
    urllib.error.URLError,
    http.client.HTTPException,
    OSError,
    RuntimeError,
    ValueError,
    KeyError,
    TypeError,
)


@dataclass
class ExtenderConfig:
    """apis/config KubeSchedulerConfiguration.extenders entry."""

    url_prefix: str
    filter_verb: str = ""
    prioritize_verb: str = ""
    bind_verb: str = ""
    preempt_verb: str = ""
    weight: int = 1
    ignorable: bool = False  # errors don't fail scheduling
    node_cache_capable: bool = False  # send node names, not full nodes
    managed_resources: tuple[str, ...] = ()  # empty -> interested in all pods
    http_timeout: float = 5.0


def _pod_to_wire(pod: Pod) -> dict:
    return {
        "metadata": {
            "name": pod.meta.name,
            "namespace": pod.meta.namespace,
            "uid": pod.meta.uid,
            "labels": dict(pod.meta.labels),
        },
        "spec": {
            "containers": [
                {"name": c.name, "requests": {k: str(v) for k, v in c.requests.items()}}
                for c in pod.spec.containers
            ],
            "priority": pod.spec.priority,
        },
    }


def _node_to_wire(ni: NodeInfo) -> dict:
    node = ni.node
    return {
        "metadata": {"name": node.meta.name, "labels": dict(node.meta.labels)},
        "status": {
            "allocatable": {k: str(v) for k, v in node.status.allocatable.items()}
        },
    }


class HTTPExtender:
    """One configured webhook endpoint (extender.go HTTPExtender)."""

    def __init__(self, config: ExtenderConfig):
        self.config = config

    @property
    def name(self) -> str:
        return self.config.url_prefix

    # -- capability probes (fwk.Extender interface) --------------------------

    def is_ignorable(self) -> bool:
        return self.config.ignorable

    def is_binder(self) -> bool:
        return bool(self.config.bind_verb)

    def is_filter(self) -> bool:
        return bool(self.config.filter_verb)

    def is_prioritizer(self) -> bool:
        return bool(self.config.prioritize_verb)

    def is_interested(self, pod: Pod) -> bool:
        """extender.go IsInterested — managed-resources intersection; empty
        list means every pod."""
        if not self.config.managed_resources:
            return True
        managed = set(self.config.managed_resources)
        for c in pod.spec.containers + pod.spec.init_containers:
            if managed & (set(c.requests) | set(c.limits)):
                return True
        return False

    # -- HTTP plumbing -------------------------------------------------------

    def _post(self, verb: str, payload: dict) -> dict:
        url = f"{self.config.url_prefix.rstrip('/')}/{verb}"
        data = json.dumps(payload).encode()
        req = urllib.request.Request(
            url, data=data, headers={"Content-Type": "application/json"}
        )
        with urllib.request.urlopen(req, timeout=self.config.http_timeout) as resp:
            return json.loads(resp.read().decode() or "{}")

    # -- verbs ---------------------------------------------------------------

    def filter(
        self, pod: Pod, nodes: list[NodeInfo]
    ) -> tuple[list[NodeInfo], dict[str, str], dict[str, str]]:
        """extender.go Filter:249 — returns (feasible, failed,
        failed_and_unresolvable); raises on transport errors."""
        by_name = {ni.name: ni for ni in nodes}
        args: dict = {"pod": _pod_to_wire(pod)}
        if self.config.node_cache_capable:
            args["nodenames"] = list(by_name)
        else:
            args["nodes"] = {"items": [_node_to_wire(ni) for ni in nodes]}
        result = self._post(self.config.filter_verb, args)
        if result.get("error"):
            raise RuntimeError(f"extender {self.name}: {result['error']}")
        if self.config.node_cache_capable and "nodenames" in result:
            keep = [n for n in result["nodenames"] if n in by_name]
        elif "nodes" in result:
            keep = [
                item["metadata"]["name"]
                for item in result["nodes"].get("items", [])
                if item["metadata"]["name"] in by_name
            ]
        else:
            keep = list(by_name)
        return (
            [by_name[n] for n in keep],
            dict(result.get("failedNodes") or {}),
            dict(result.get("failedAndUnresolvableNodes") or {}),
        )

    def prioritize(
        self, pod: Pod, nodes: list[NodeInfo]
    ) -> tuple[dict[str, int], int]:
        """extender.go Prioritize:320 — (host -> raw score 0..10, weight)."""
        args: dict = {"pod": _pod_to_wire(pod)}
        if self.config.node_cache_capable:
            args["nodenames"] = [ni.name for ni in nodes]
        else:
            args["nodes"] = {"items": [_node_to_wire(ni) for ni in nodes]}
        result = self._post(self.config.prioritize_verb, args)
        scores = {
            hp["host"]: int(hp["score"])
            for hp in (result if isinstance(result, list) else result.get("items", []))
        }
        return scores, self.config.weight

    def bind(self, pod: Pod, node_name: str) -> Status:
        """extender.go Bind:362 — delegate the binding API call."""
        try:
            result = self._post(
                self.config.bind_verb,
                {
                    "podName": pod.meta.name,
                    "podNamespace": pod.meta.namespace,
                    "podUID": pod.meta.uid,
                    "node": node_name,
                },
            )
        except EXTENDER_ERRORS as e:
            return Status.as_error(RuntimeError(f"extender bind failed: {e}"))
        if result.get("error"):
            return Status.as_error(RuntimeError(result["error"]))
        return Status()


def find_nodes_that_pass_extenders(
    extenders: list[HTTPExtender],
    pod: Pod,
    feasible: list[NodeInfo],
    diagnosis,
) -> list[NodeInfo]:
    """schedule_one.go findNodesThatPassExtenders:890 — sequential fan-in;
    ignorable extenders' transport errors are skipped, others propagate."""
    for ext in extenders:
        if not feasible:
            break
        if not ext.is_filter() or not ext.is_interested(pod):
            continue
        try:
            feasible, failed, failed_unresolvable = ext.filter(pod, feasible)
        except EXTENDER_ERRORS as e:
            if ext.is_ignorable():
                continue
            raise RuntimeError(f"extender {ext.name} filter failed: {e}") from e
        for node_name, reason in failed_unresolvable.items():
            diagnosis.node_to_status.set(
                node_name, Status.unresolvable(reason, plugin="extender")
            )
        for node_name, reason in failed.items():
            if node_name not in failed_unresolvable:
                diagnosis.node_to_status.set(
                    node_name, Status.unschedulable(reason, plugin="extender")
                )
    return feasible


def extender_scores(
    extenders: list[HTTPExtender], pod: Pod, nodes: list[NodeInfo]
) -> dict[str, int]:
    """prioritizeNodes extender fan-out (schedule_one.go:985-1044): raw 0..10
    scores rescaled to the plugin 0..100 range and weight-combined."""
    combined: dict[str, int] = {}
    for ext in extenders:
        if not ext.is_prioritizer() or not ext.is_interested(pod):
            continue
        try:
            scores, weight = ext.prioritize(pod, nodes)
        except EXTENDER_ERRORS:
            continue  # prioritize errors are never fatal (schedule_one.go:996)
        factor = MAX_NODE_SCORE // MAX_EXTENDER_PRIORITY  # :1019 rescale
        for host, score in scores.items():
            combined[host] = combined.get(host, 0) + score * weight * factor
    return combined
