"""Scheduler object: wiring of cache, queue, profiles, informers.

Reference: pkg/scheduler/scheduler.go (Scheduler struct :67, New :273,
Run :536) + eventhandlers.go (addAllEventHandlers :481).
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Any

from ..api.resource import ResourceNames
from ..api.types import DEFAULT_SCHEDULER_NAME, RUNNING, Node, Pod
from ..client.informer import InformerFactory
from ..store.store import ADDED, DELETED, MODIFIED, Store
from .cache import Cache, Snapshot
from .framework import events as ev
from .framework.events import ClusterEvent
from .framework.runtime import Framework
from .plugins.registry import DEFAULT_WEIGHTS, default_plugins
from .queue.scheduling_queue import SchedulingQueue
from .schedule_one import ScheduleOneLoop, SchedulingAlgorithm
from .nodeinfo import PodInfo


@dataclass
class Handle:
    """What stateful plugins get to touch (framework.Handle, interface.go:804)."""

    store: Store
    cache: Cache
    queue: SchedulingQueue
    snapshot: Snapshot
    framework: Framework | None = None
    # async API pipeline (SchedulerAsyncAPICalls): preemption's executor
    # routes evictions through it so PostFilter never blocks on API writes
    api_dispatcher: Any = None


@dataclass
class Profile:
    name: str = DEFAULT_SCHEDULER_NAME
    percentage_of_nodes_to_score: int = 0
    plugin_args: dict = field(default_factory=dict)
    weights: dict = field(default_factory=lambda: dict(DEFAULT_WEIGHTS))
    backend: str = "host"  # "host" | "tpu"
    # per-profile plugin disable list (config PluginSet.disabled; "*" with
    # enabled names = whitelist, per the reference's profile semantics)
    disabled_plugins: tuple = ()
    enabled_plugins: tuple = ()  # only meaningful with "*" in disabled
    # >0 with backend="tpu": schedule_pending pops runs of up to wave_size
    # pods and schedules each run in ONE device program (bit-identical to
    # per-pod, see ScheduleOneLoop.schedule_wave) — the throughput mode
    wave_size: int = 0


# Reconcile-restored state (kubesched-lint rule CRASH01): the attributes a
# fresh scheduler's reconcile() re-derives from store truth after a crash.
# Each entry names the attribute and the ONE module sanctioned to write it
# (its owning class); CRASH01 cross-parses this literal and flags writes
# anywhere else — restart recovery is only sound if nothing mutates this
# state behind the reconcile contract's back.
RECONCILE_RESTORED_STATE = (
    ("_assumed_pods", "scheduler/cache/cache.py"),
    ("_groups", "scheduler/cache/podgroup_state.py"),
    ("_inflight_wave", "scheduler/schedule_one.py"),
    ("_wave_completions", "scheduler/schedule_one.py"),
)


def _apply_plugin_set(plugins: list, prof: "Profile") -> list:
    """Per-profile enable/disable (apis/config Plugins semantics): names in
    disabled are removed; disabled=("*",) whitelists enabled_plugins. The
    infrastructural plugins every cycle needs (QueueSort, Bind) survive a
    bare wildcard unless explicitly disabled by name."""
    disabled = set(prof.disabled_plugins)
    if not disabled:
        return plugins
    if "*" in disabled:
        keep = set(prof.enabled_plugins) | {"PrioritySort", "DefaultBinder"}
        return [p for p in plugins if p.name in keep]
    return [p for p in plugins if p.name not in disabled]


class Scheduler:
    # fleet ownership predicate (installed by scheduler/fleet.py, the sole
    # sanctioned writer — kubesched-lint rule FLEET01). None = own every
    # pod, the single-scheduler default. When set, _on_pod_event ignores
    # non-owned unbound pods at admission; the queue and loop carry the
    # same predicate on their own gates.
    shard_filter = None

    def __init__(
        self,
        store: Store,
        profiles: list[Profile] | None = None,
        names: ResourceNames | None = None,
        feature_gates: dict | None = None,
        clock=None,
        metrics=None,
        seed: int = 0,
        async_binding: bool = False,
        async_api_calls: bool = False,
        parallelism: int = 16,
        event_recorder=None,
        extenders: list | None = None,
        tracer=None,
        warm_start: bool = False,
    ):
        from ..utils.clock import Clock
        from .tpu.flightrecorder import FlightRecorder

        self.store = store
        self.names = names or ResourceNames()
        self.clock = clock or Clock()
        # AOT warm restart (scheduler/tpu/warmup.py): start() pre-lowers the
        # TPU wave kernels after informer sync. Default off — a cold-start
        # scheduler (and every golden test) is bit-identical without it.
        self.warm_start = warm_start
        self.metrics = metrics
        self.tracer = tracer
        # one wave flight recorder shared by the loop, every TPU backend,
        # and the perf harness/bench: all phase stopwatches, per-wave
        # records, and the slow-wave watchdog live here
        self.flight_recorder = FlightRecorder(tracer=tracer, metrics=metrics)
        if event_recorder is None:
            # every scheduler emits Scheduled/FailedScheduling events
            # (schedule_one.go:1174,1273); the recorder buffers + aggregates
            # so the binding path only appends to a dict
            from .events import EventRecorder

            event_recorder = EventRecorder(store)
        self.event_recorder = event_recorder
        if metrics is not None and getattr(event_recorder, "metrics", None) is None:
            # spill/aggregation/GC visibility (events are otherwise silently
            # folded): the recorder lands counters on the shared registry
            event_recorder.metrics = metrics
        self.cache = Cache(self.names)
        self.snapshot = Snapshot()
        self.feature_gates = dict(feature_gates or {})
        from .extender import HTTPExtender

        self.extenders = [
            e if isinstance(e, HTTPExtender) else HTTPExtender(e)
            for e in (extenders or [])
        ]

        profiles = profiles or [Profile()]
        self.wave_size = max((p.wave_size for p in profiles
                              if p.backend == "tpu"), default=0)
        self.frameworks: dict[str, Framework] = {}
        self.algorithms: dict[str, SchedulingAlgorithm] = {}
        pre_enqueue = []
        hint_map: dict = {}
        less_fn = None
        for prof in profiles:
            plugins = default_plugins(
                store, self.names, self.feature_gates, prof.plugin_args
            )
            plugins = _apply_plugin_set(plugins, prof)
            if prof.backend == "tpu":
                from .tpu.backend import KERNEL_FILTER_PLUGINS

                missing = KERNEL_FILTER_PLUGINS - {p.name for p in plugins}
                if missing:
                    raise ValueError(
                        f"profile {prof.name!r}: kernel-modeled plugins "
                        f"{sorted(missing)} cannot be disabled with "
                        f"backend=tpu (the dense kernel always runs them); "
                        f"use backend=host for this profile"
                    )
            fw = Framework(
                plugins, prof.weights, profile_name=prof.name, metrics=metrics, clock=self.clock
            )
            self.frameworks[prof.name] = fw
            if prof.backend == "tpu":
                from .tpu.backend import TPUBackend, TPUSchedulingAlgorithm

                backend = TPUBackend(self.names, plugin_args=prof.plugin_args,
                                     recorder=self.flight_recorder)
                fw.tpu_backend = backend
                self.algorithms[prof.name] = TPUSchedulingAlgorithm(
                    fw, backend, rng=random.Random(seed),
                    host_tail_percentage=prof.percentage_of_nodes_to_score,
                )
                self.algorithms[prof.name].extenders = self.extenders
            else:
                self.algorithms[prof.name] = SchedulingAlgorithm(
                    fw, prof.percentage_of_nodes_to_score, rng=random.Random(seed),
                    extenders=self.extenders,
                )  # nominator wired below once the queue exists
            pre_enqueue = fw.pre_enqueue_plugins  # last profile wins (single-profile typical)
            hint_map.update(fw.queueing_hint_map())
            if less_fn is None:
                less_fn = fw.queue_sort_less

        self.queue = SchedulingQueue(
            less_fn or (lambda a, b: a.timestamp < b.timestamp),
            clock=self.clock,
            pre_enqueue_plugins=pre_enqueue,
            queueing_hint_map=hint_map,
            pop_from_backoff=self.feature_gates.get(
                "SchedulerPopFromBackoffQ", True
            ),
        )
        # OpportunisticBatching (KEP-5598, alpha -> default off as in the
        # reference): one shared batch cache; flushed on node-shape events
        self.batch_cache = None
        if self.feature_gates.get("OpportunisticBatching", False):
            from .framework.batch import BatchCache

            self.batch_cache = BatchCache(metrics=metrics)
        for algo in self.algorithms.values():
            algo.nominator = self.queue
            algo.batch = self.batch_cache

        # SchedulerAsyncAPICalls: bind/status writes through the dispatcher
        self.api_dispatcher = None
        self.api_cacher = None
        if async_api_calls:
            from .api_dispatcher import APICacher, APIDispatcher

            self.api_dispatcher = APIDispatcher(parallelism, metrics=metrics,
                                                tracer=tracer,
                                                recorder=self.flight_recorder)
            self.api_dispatcher.run()
            self.api_cacher = APICacher(store, self.api_dispatcher)
            # event flushes ride the dispatcher too: maybe_flush enqueues the
            # store writes for a worker instead of paying them on the
            # scheduling thread (explicit flush() stays synchronous)
            self.event_recorder.dispatcher = self.api_dispatcher

        # wire handles into stateful plugins
        self.handle = Handle(store, self.cache, self.queue, self.snapshot,
                             api_dispatcher=self.api_dispatcher)
        for fw in self.frameworks.values():
            self.handle.framework = fw
            for p in fw.plugins:
                if hasattr(p, "set_handle"):
                    p.set_handle(self.handle)

        self.loop = ScheduleOneLoop(
            self.cache,
            self.queue,
            self.frameworks,
            self.algorithms,
            store,
            self.snapshot,
            metrics=metrics,
            async_binding=async_binding,
            event_recorder=event_recorder,
            names=self.names,
            api_cacher=self.api_cacher,
            pod_group_cycles=self.feature_gates.get("GenericWorkload", True),
            recorder=self.flight_recorder,
        )

        self._last_leftover_flush = self.clock.now()

        # informers (addAllEventHandlers, eventhandlers.go:481)
        self.informers = InformerFactory(store)
        # partition self-heal telemetry: every informer's detector reports
        # through the flight recorder (detection counter + repair-latency
        # histogram land on /metrics from there)
        self.informers.set_partition_observer(
            self.flight_recorder.partition_detected
        )
        self.informers.informer("Pod").add_handler(self._on_pod_event)
        self.informers.informer("Node").add_handler(self._on_node_event)
        self.informers.informer("PodGroup").add_handler(self._on_podgroup_event)
        # dynamic handlers for the EventResources plugins actually register
        # (eventhandlers.go:481 — only kinds some hint listens to get informers)
        registered = {
            h.event.resource
            for hints in hint_map.values()
            for h in hints
        }
        for kind in (ev.PVC, ev.PV, ev.STORAGE_CLASS, ev.CSI_NODE,
                     ev.RESOURCE_CLAIM, ev.RESOURCE_SLICE):
            if kind in registered:
                self.informers.informer(kind).add_handler(
                    self._make_generic_handler(kind)
                )

    # -- event handlers (eventhandlers.go) ----------------------------------

    def _group_key(self, pod: Pod) -> str | None:
        sg = pod.spec.scheduling_group
        return f"{pod.meta.namespace}/{sg.pod_group_name}" if sg else None

    def _mark_external(self) -> None:
        """Informer-observed external change: stale the wave carry but keep
        the in-flight wave's results (its pods were popped before the event
        — reference snapshot-at-cycle-start semantics)."""
        self.loop.mark_wave_external(poison=False)

    def _on_pod_event(self, etype: str, old: Pod | None, new: Pod) -> None:
        gk = self._group_key(new)
        ledger = self.flight_recorder.pod_ledger
        if etype == ADDED:
            if new.is_scheduled:
                if not self.cache.is_assumed_pod(new):
                    # a bound pod we did not place (foreign writer)
                    self._mark_external()
                self.cache.add_pod(new)
                if gk:
                    self.cache.pod_group_states.pod_scheduled(gk, new.meta.key)
                self.queue.move_all_to_active_or_backoff(
                    ClusterEvent(ev.ASSIGNED_POD, ev.ADD), None, new
                )
            else:
                # fleet gate: a peer's pod never enters this member's queue
                # (its owner admits it; bound-pod branches above stay
                # ungated so every member's cache mirrors ALL occupancy)
                sf = self.shard_filter
                if sf is not None and not sf(new):
                    return
                # ledger edges: informer delivered the pod, then it entered
                # the scheduling queue (the informer segment spans PodInfo
                # construction + queue admission)
                ledger.stamp(new.meta.key, "watch_arrival")
                if gk:
                    self.cache.pod_group_states.pod_added(gk, new.meta.key)
                self.queue.add(new, PodInfo(new, self.names))
                ledger.stamp(new.meta.key, "queue_admission")
                self.queue.move_all_to_active_or_backoff(
                    ClusterEvent(ev.UNSCHEDULED_POD, ev.ADD), None, new
                )
        elif etype == MODIFIED:
            if new.is_scheduled:
                if old is not None and not old.is_scheduled:
                    if not self.cache.is_assumed_pod(new):
                        self._mark_external()
                    # bind landed: cache confirms the assume
                    self.cache.add_pod(new)
                    if gk:
                        self.cache.pod_group_states.pod_scheduled(gk, new.meta.key)
                    self.queue.move_all_to_active_or_backoff(
                        ClusterEvent(ev.ASSIGNED_POD, ev.ADD), old, new
                    )
                else:
                    # update of a placed pod (labels/scale-down) changes the
                    # node planes outside the wave pipeline's writeback
                    if (old is not None and old.status.phase != RUNNING
                            and new.status.phase == RUNNING):
                        # kubelet reported the pod up: the ledger's final edge
                        ledger.stamp(new.meta.key, "status_ack")
                    self._mark_external()
                    self.cache.update_pod(old, new)
                    action = self._pod_update_actions(old, new)
                    if action:
                        self.queue.move_all_to_active_or_backoff(
                            ClusterEvent(ev.ASSIGNED_POD, action), old, new
                        )
            else:
                self.queue.update(old, new)
                action = self._pod_update_actions(old, new)
                if action:
                    self.queue.move_all_to_active_or_backoff(
                        ClusterEvent(ev.UNSCHEDULED_POD, action), old, new
                    )
        elif etype == DELETED:
            if gk:
                self.cache.pod_group_states.pod_removed(gk, new.meta.key)
            if self.metrics is not None and hasattr(self.metrics, "forget_pod"):
                self.metrics.forget_pod(new.meta.key)
            ledger.forget(new.meta.key)
            if new.is_scheduled:
                self._mark_external()
                self.cache.remove_pod(new)
                self.queue.move_all_to_active_or_backoff(
                    ClusterEvent(ev.ASSIGNED_POD, ev.DELETE), new, None
                )
            else:
                self.queue.delete(new)

    def _pod_update_actions(self, old: Pod | None, new: Pod) -> int:
        """OR of action bits describing what changed (eventhandlers.go
        podSchedulingPropertiesChange) — never a guess of a single bit."""
        if old is None:
            return ev.UPDATE
        action = 0
        if old.meta.labels != new.meta.labels:
            action |= ev.UPDATE_POD_LABEL
        if old.spec.tolerations != new.spec.tolerations:
            action |= ev.UPDATE_POD_TOLERATIONS
        if old.spec.scheduling_gates != new.spec.scheduling_gates and not new.spec.scheduling_gates:
            action |= ev.UPDATE_POD_SCHEDULING_GATES_ELIMINATED
        old_req = PodInfo(old, self.names).request
        new_req = PodInfo(new, self.names).request
        if any(n < o for o, n in zip(old_req.v, new_req.v)):
            action |= ev.UPDATE_POD_SCALE_DOWN
        return action

    def _on_node_event(self, etype: str, old: Node | None, new: Node) -> None:
        self._mark_external()
        if self.batch_cache is not None:
            # node shape changed: cached sorted score lists are stale
            self.batch_cache.flush()
        if etype == ADDED:
            self.cache.add_node(new)
            self.queue.move_all_to_active_or_backoff(
                ClusterEvent(ev.NODE, ev.ADD), None, new
            )
        elif etype == MODIFIED:
            self.cache.update_node(old, new)
            action = 0
            if old is not None:
                if old.status.allocatable != new.status.allocatable:
                    action |= ev.UPDATE_NODE_ALLOCATABLE
                if old.meta.labels != new.meta.labels:
                    action |= ev.UPDATE_NODE_LABEL
                if old.spec.taints != new.spec.taints:
                    action |= ev.UPDATE_NODE_TAINT
                if old.spec.unschedulable != new.spec.unschedulable:
                    action |= ev.UPDATE_NODE_TAINT
            if action:
                self.queue.move_all_to_active_or_backoff(
                    ClusterEvent(ev.NODE, action), old, new
                )
        elif etype == DELETED:
            self.cache.remove_node(new)

    def _make_generic_handler(self, kind: str):
        """Storage/DRA kinds only move queued pods; there is no cache state."""

        def handler(etype: str, old, new) -> None:
            action = {ADDED: ev.ADD, MODIFIED: ev.UPDATE, DELETED: ev.DELETE}[etype]
            self.queue.move_all_to_active_or_backoff(
                ClusterEvent(kind, action), old, new
            )

        return handler

    def _on_podgroup_event(self, etype: str, old, new) -> None:
        if etype in (ADDED, MODIFIED):
            self.cache.pod_group_states.set_group(new)
            self.queue.move_all_to_active_or_backoff(
                ClusterEvent(ev.POD_GROUP, ev.ADD), old, new
            )
        elif etype == DELETED:
            self.cache.pod_group_states.remove_group(new.meta.key)

    # -- run -----------------------------------------------------------------

    def start(self) -> None:
        """Sync informers (initial list), then reconcile half-applied state
        a previous incarnation may have left behind; with warm_start, end by
        pre-lowering the TPU wave kernels (AOT warm restart) so the first
        real wave pays zero compiles."""
        self.informers.start_all()
        self.reconcile(shard_pred=self.shard_filter)
        if self.warm_start:
            self._run_warmup()

    def _run_warmup(self) -> None:
        """Pre-lower every TPU profile's wave kernels against the live node
        planes (must run AFTER informer sync: bucket sizes come from the
        synced cache, and an empty snapshot has nothing to lower against)."""
        from .tpu.warmup import warm_backend

        self.cache.update_snapshot(self.snapshot)
        for algo in self.algorithms.values():
            backend = getattr(algo, "backend", None)
            if backend is not None:
                warm_backend(backend, self.snapshot, self.wave_size)

    def reconcile(self, shard_pred=None, kind_prefix="") -> dict:
        """Startup crash recovery: resolve every piece of mid-flight state a
        previous incarnation may have left behind against store truth (the
        README "Restart & recovery" contract). Three sweeps:

        1. Assumed-but-unconfirmed pods (orphaned assumes from in-flight
           pipeline waves, dispatcher calls lost between prepare and
           commit). A scheduler killed between assume and the async store
           write leaves the cache claiming resources the cluster never
           granted; one killed between the write and the confirming watch
           event leaves a bound pod still marked assumed. Store truth
           decides: bound → adopt; gone → forget; unbound → forget +
           requeue (the bind never happened, the pod must be scheduled
           again).
        2. Half-bound PodGroups (a gang crash between members' binds):
           all-or-nothing across restart — when the surviving members can
           still reach quorum, adopt the remainder through the host gang
           cycle (activate the pending members); when they cannot, release
           every landed member (delete the bound pods) so the gang never
           holds partial capacity forever.
        3. Stale gang Permit quorum state: group-state `assumed` entries
           backed by neither a live cache assume nor a store bind are
           reverted (or promoted to scheduled when the bind landed), so a
           fresh gang cycle starts from truthful quorum counts.

        Every outcome lands on the flight recorder's restart_events and the
        scheduler_restart_recoveries_total{kind} series. Gang/permit kinds
        appear in the returned stats only when non-zero.

        `shard_pred` scopes every sweep to one fleet member's ownership
        (None = own everything, the single-scheduler default): a member's
        reconcile must never forget/requeue a PEER's in-flight pod — the
        peer's assume is valid mid-flight state, not a crash leftover.
        `kind_prefix` namespaces the recorded recovery kinds (the fleet's
        shard adoption reuses these sweeps under "shard_adopt_*")."""
        stats = {"adopted": 0, "forgotten": 0, "requeued": 0}
        for pod in self.cache.assumed_pods():
            if shard_pred is not None and not shard_pred(pod):
                continue  # a peer's in-flight assume: not ours to resolve
            key = pod.meta.key
            cur = self.store.try_get("Pod", key)
            if cur is None:
                self.cache.forget_pod(pod)
                stats["forgotten"] += 1
                continue
            if cur.spec.node_name:
                # the bind landed (possibly on a different node than
                # assumed): add_pod confirms a matching assume and
                # re-places a divergent one
                self.cache.add_pod(cur)
                stats["adopted"] += 1
                continue
            # half-applied: assumed in cache, store write never landed
            self.cache.forget_pod(pod)
            stats["forgotten"] += 1
            # clear any stale in-flight queue record surviving the crash
            # (token=None clears unconditionally), then requeue
            self.queue.done(key)
            self.queue.add(cur, PodInfo(cur, self.names))
            stats["requeued"] += 1

        # -- sweep 2: half-bound PodGroups against store truth ------------
        # read-only listing duck-typed against the narrower RESTStore
        # surface (list() only) so a scheduler fronted by the apiserver
        # reconciles the same way as one on a native Store
        if hasattr(self.store, "list_refs"):
            _list = self.store.list_refs
        else:
            _list = lambda kind: self.store.list(kind)[0]  # noqa: E731
        gang_adopt = gang_release = 0
        members: dict[str, list] = {}
        for p in _list("Pod"):
            gk = self._group_key(p)
            if gk is not None:
                members.setdefault(gk, []).append(p)
        for g in _list("PodGroup"):
            gk = g.meta.key
            mem = members.get(gk, [])
            # gangs shard by group key, so one member decides the whole
            # gang's fate — a peer's half-bound gang is the peer's problem
            if shard_pred is not None and mem and not shard_pred(mem[0]):
                continue
            bound = [p for p in mem if p.spec.node_name]
            if not bound or len(bound) >= g.spec.policy.min_count:
                continue  # whole gang landed, or nothing did
            if len(mem) >= g.spec.policy.min_count:
                # salvageable: the pending remainder can still reach
                # quorum — adopt through the host gang cycle (the permit
                # plugin counts the already-scheduled members)
                self.queue.activate([p for p in mem if not p.spec.node_name])
                gang_adopt += 1
            else:
                # the remainder can never reach quorum: all-or-nothing
                # demands the landed members be released
                for p in bound:
                    try:
                        self.store.delete("Pod", p.meta.key)
                    except Exception:  # noqa: BLE001 — racing deletion
                        pass
                gang_release += 1

        # -- sweep 3: stale gang Permit quorum state ----------------------
        permit_cleared = 0
        live_assumes = {p.meta.key for p in self.cache.assumed_pods()}
        for gk, gstate in self.cache.pod_group_states.snapshot().items():
            mem = members.get(gk, [])
            if shard_pred is not None and mem and not shard_pred(mem[0]):
                continue  # a peer's gang quorum state
            for key in gstate.assumed:
                if key in live_assumes:
                    continue  # a real assume: sweep 1 owns its fate
                cur = self.store.try_get("Pod", key)
                if cur is not None and cur.spec.node_name:
                    # the bind landed but the quorum state never advanced
                    self.cache.pod_group_states.pod_scheduled(gk, key)
                else:
                    # assume died with the old incarnation: back to
                    # unscheduled so quorum counts match reality
                    self.cache.pod_group_states.pod_unassumed(gk, key)
                permit_cleared += 1

        if gang_adopt:
            stats["gang_adopt"] = gang_adopt
        if gang_release:
            stats["gang_release"] = gang_release
        if permit_cleared:
            stats["permit_cleared"] = permit_cleared
        for kind, n in stats.items():
            self.flight_recorder.restart_recovery(kind_prefix + kind, n)
        if stats["adopted"] or stats["forgotten"] or gang_release:
            # node occupancy changed under any live device carry
            self._mark_external()
        return stats

    def adopt_shard(self, shard_pred, kind_prefix: str = "shard_adopt_") -> dict:
        """Fleet shard adoption (scheduler/fleet.py calls this when a
        member acquires a shard — at boot, or after a dead peer's lease
        expired): the reconcile() sweeps scoped to the shard, plus a
        requeue pass for the shard's pending pods this member's admission
        gate had been filtering out while a peer owned them. Outcomes
        count on restart_recoveries{kind="<kind_prefix>*"}."""
        stats = self.reconcile(shard_pred=shard_pred, kind_prefix=kind_prefix)
        if hasattr(self.store, "list_refs"):
            _list = self.store.list_refs
        else:
            _list = lambda kind: self.store.list(kind)[0]  # noqa: E731
        pending = 0
        for pod in _list("Pod"):
            if pod.is_scheduled or not shard_pred(pod):
                continue
            key = pod.meta.key
            if self.queue.has_pod(key) or self.cache.is_assumed_pod(pod):
                continue
            # register gang membership first: the admission gate skipped
            # pod_added while a peer owned this shard, and the gang cycle
            # pops siblings from gstate.unscheduled — without this the
            # adopted gang can never reach quorum
            gk = self._group_key(pod)
            if gk is not None:
                self.cache.pod_group_states.pod_added(gk, key)
            # clear any stale in-flight record, then admit through the
            # queue's own gate (the shard is owned now, so it passes)
            self.queue.done(key)
            self.queue.add(pod, PodInfo(pod, self.names))
            pending += 1
        if pending:
            stats["pending"] = pending
            self.flight_recorder.restart_recovery(kind_prefix + "pending",
                                                  pending)
        return stats

    def pump(self) -> int:
        """Drain informer events (deterministic single-thread mode)."""
        with self.flight_recorder.phase("pump"):
            n = self.informers.pump_all()
        # event-recorder flush + leftover sweep + gauges: accounted apart
        # from informer pumping — at bench scale the recorder's store writes
        # were the single largest unattributed wall-time slice (round-4
        # verdict weak #3)
        with self.flight_recorder.phase("events"):
            # periodic safety net (reference: 30s ticker -> 5 min leftover
            # flush)
            now = self.clock.now()
            if now - self._last_leftover_flush > 30.0:
                self._last_leftover_flush = now
                self.queue.flush_unschedulable_leftover()
            if self.event_recorder is not None:
                # cadence-gated (and dispatcher-offloaded when async API
                # calls are on): the per-iteration cost here is a clock
                # read, not a store write per buffered event
                self.event_recorder.maybe_flush()
            if self.metrics is not None and hasattr(self.metrics,
                                                    "update_queue_gauges"):
                active, backoff, unsched = self.queue.pending_pods()
                self.metrics.update_queue_gauges(active, backoff, unsched)
        return n

    def schedule_pending(self, max_cycles: int = 100_000) -> int:
        """Run scheduling cycles until the queue stays empty; returns count.

        Each cycle pumps informers first so bind results confirm assumes.
        """
        scheduled = 0
        idle_rounds = 0
        for _ in range(max_cycles):
            self.pump()
            if self.wave_size > 0:
                n = self.loop.schedule_wave(self.wave_size, timeout=0.0)
            else:
                n = 1 if self.loop.schedule_one(timeout=0.0) else 0
            if n == 0:
                idle_rounds += 1
                if self.api_dispatcher is not None:
                    # flush queued async binds so their events confirm
                    # assumes (and may unblock gated/waiting pods) before
                    # declaring the queue drained
                    with self.flight_recorder.phase("drain"):
                        self.api_dispatcher.drain(timeout=1.0)
                # a lost watch delivery (lossy stream, injected
                # watch.deliver fault, or a watch.partition gap that opened
                # DURING the drain) can strand a pod invisible to the queue
                # forever — consult the partition detector on every idle
                # round, not a single unconditional pre-drain resync: the
                # no-gap cost is one revision probe per kind, and a gap
                # that opens between idle rounds still gets caught before
                # the queue is declared empty
                with self.flight_recorder.phase("pump"):
                    repaired = self.informers.detect_and_repair_all()
                if repaired:
                    idle_rounds = 0
                if idle_rounds > 2:
                    break
                continue
            idle_rounds = 0
            scheduled += n
        self.loop.wait_for_bindings()
        self.pump()
        return scheduled

    def run_forever(self, stop_event) -> None:
        """Threaded mode: pump + schedule until stop_event set."""
        while not stop_event.is_set():
            self.pump()
            self.loop.schedule_one(timeout=0.05)
