"""EventRecorder: "Scheduled" / "FailedScheduling" events as API objects.

Reference: client-go tools/events EventRecorder + the events.k8s.io Event
type — the scheduler emits an event per binding and per failure
(pkg/scheduler/schedule_one.go:1174,1273). The reference's recorder is an
async broadcaster with aggregation (an EventSeries bumps a count instead of
minting a new object for repeats); this recorder buffers and aggregates the
same way and flushes batches to the store, so the hot binding path only
appends to a list.
"""

from __future__ import annotations

import threading
import time

from ..api.events import (  # noqa: F401 - re-exported for compat
    EVENT_TYPE_NORMAL,
    EVENT_TYPE_WARNING,
    Event,
)
from ..api.meta import ObjectMeta  # noqa: F401 - public re-export


class EventRecorder:
    """Buffered, aggregating recorder; thread-safe appends, batched flush."""

    # events older than this are garbage-collected (the reference relies on
    # the apiserver's event TTL, default 1h)
    EVENT_TTL_S = 3600.0
    # sweep the stored events after this many writes since the last sweep
    GC_EVERY_WRITES = 512

    def __init__(self, store, component: str = "default-scheduler",
                 max_buffer: int = 4096):
        self.store = store
        self.component = component
        # probe each fast path INDEPENDENTLY (in-process Store has both;
        # REST/native facades may grow one without the other) — a silent
        # except-pass around a TypeError would drop every event
        import inspect

        try:
            self._fast_create = (
                "copy_return" in inspect.signature(store.create).parameters
            )
        except (TypeError, ValueError):
            self._fast_create = False
        self._fast_list = hasattr(store, "list_refs")
        self._mu = threading.Lock()
        # (involved, type, reason, message) -> pending Event
        self._pending: dict[tuple, Event] = {}
        self._seq = 0
        self._max_buffer = max_buffer
        self._writes_since_gc = 0

    def event(self, obj, etype: str, reason: str, message: str) -> None:
        """Record one event (schedule_one.go:1174 "Scheduled",
        :1273 "FailedScheduling"). Repeats aggregate into a count."""
        involved = f"{obj.kind}/{obj.meta.key}"
        key = (involved, etype, reason, message)
        now = time.time()
        flush_now = False
        with self._mu:
            ev = self._pending.get(key)
            if ev is not None:
                ev.count += 1
                ev.last_timestamp = now
            else:
                # deterministic name per (involved, type, reason, message):
                # repeats aggregate into the SAME stored object across
                # flushes (EventSeries semantics), never a new one per flush
                import hashlib

                digest = hashlib.sha1(
                    "|".join(key).encode()
                ).hexdigest()[:12]
                name = f"{obj.meta.name}.{digest}"
                self._pending[key] = Event(
                    meta=ObjectMeta(name=name, namespace=obj.meta.namespace),
                    involved_object=involved,
                    type=etype,
                    reason=reason,
                    message=message,
                    first_timestamp=now,
                    last_timestamp=now,
                    reporting_controller=self.component,
                )
            flush_now = len(self._pending) >= self._max_buffer
        if flush_now:
            self.flush()

    def flush(self) -> int:
        """Write buffered events to the store; returns how many landed."""
        with self._mu:
            pending, self._pending = self._pending, {}
        n = 0
        for ev in pending.values():
            try:
                existing = self.store.try_get("Event", ev.meta.key)
                if existing is not None:
                    existing.count += ev.count
                    existing.last_timestamp = ev.last_timestamp
                    self.store.update(existing, check_version=False)
                elif self._fast_create:
                    # copy_return=False: the returned copy was discarded, and
                    # at bench scale (one event per bound pod) the per-event
                    # deepcopy was a measurable slice of scheduling wall time
                    self.store.create(ev, copy_return=False)
                else:
                    # REST/native stores take no copy_return kwarg
                    self.store.create(ev)
                n += 1
            except Exception:  # noqa: BLE001 - events are best-effort
                pass
        self._writes_since_gc += n
        if self._writes_since_gc >= self.GC_EVERY_WRITES:
            self._writes_since_gc = 0
            self._gc()
        return n

    def _gc(self) -> None:
        """Expire stored events past the TTL — the store has no apiserver
        event TTL, so unbounded churny runs would otherwise leak objects."""
        cutoff = time.time() - self.EVENT_TTL_S
        try:
            # read-only scan (list_refs): a deepcopying list() here grew
            # O(stored-events) per sweep and dominated event-write cost at
            # bench scale (21 sweeps x 11k events)
            if self._fast_list:
                events = self.store.list_refs("Event")
            else:
                events, _ = self.store.list("Event")
            expired = [ev.meta.key for ev in events
                       if ev.last_timestamp < cutoff]
            for key in expired:
                self.store.delete("Event", key)
        except Exception:  # noqa: BLE001
            pass
