"""EventRecorder: "Scheduled" / "FailedScheduling" events as API objects.

Reference: client-go tools/events EventRecorder + the events.k8s.io Event
type — the scheduler emits an event per binding and per failure
(pkg/scheduler/schedule_one.go:1174,1273). The reference's recorder is an
async broadcaster with aggregation (an EventSeries bumps a count instead of
minting a new object for repeats); this recorder buffers and aggregates the
same way and flushes batches to the store, so the hot binding path only
appends to a list.
"""

from __future__ import annotations

import threading
import time

from ..api.events import (  # noqa: F401 - re-exported for compat
    EVENT_TYPE_NORMAL,
    EVENT_TYPE_WARNING,
    Event,
)
from ..api.meta import ObjectMeta  # noqa: F401 - public re-export


class EventRecorder:
    """Buffered, aggregating recorder; thread-safe appends, batched flush."""

    # events older than this are garbage-collected (the reference relies on
    # the apiserver's event TTL, default 1h)
    EVENT_TTL_S = 3600.0
    # sweep the stored events after this many writes since the last sweep
    GC_EVERY_WRITES = 512
    # ... but never sweep more often than this (the sweep walks every stored
    # event; it belongs on a slow timer, not the scheduling hot loop)
    GC_MIN_INTERVAL_S = 30.0
    # correlation spill threshold (the reference correlator's
    # EventAggregator, defaultAggregateMaxEvents=10): the first N events
    # sharing a correlation key stay individual; the rest collapse into ONE
    # aggregate object whose count keeps climbing
    AGGREGATE_SPILL = 10
    # maybe_flush cadence: the scheduler pump calls it every iteration, but
    # store writes happen at most this often
    FLUSH_INTERVAL_S = 0.25

    def __init__(self, store, component: str = "default-scheduler",
                 max_buffer: int = 4096):
        self.store = store
        self.component = component
        # probe each fast path INDEPENDENTLY (in-process Store has both;
        # REST/native facades may grow one without the other) — a silent
        # except-pass around a TypeError would drop every event
        import inspect

        try:
            self._fast_create = (
                "copy_return" in inspect.signature(store.create).parameters
            )
        except (TypeError, ValueError):
            self._fast_create = False
        self._fast_list = hasattr(store, "list_refs")
        self._mu = threading.Lock()
        # (involved, type, reason, message) -> pending Event
        self._pending: dict[tuple, Event] = {}
        self._seq = 0
        self._max_buffer = max_buffer
        self._writes_since_gc = 0
        # events recorded per (correlation, type, reason) since last flush
        self._corr_counts: dict[tuple, int] = {}
        # optional APIDispatcher: maybe_flush routes the store writes
        # through its workers so the scheduling thread never pays them
        self.dispatcher = None
        # optional SchedulerMetrics: aggregation/spill/GC were previously
        # silent — with a metrics facade wired, every disposition is counted
        self.metrics = None
        self._flush_seq = 0
        self._last_flush = float("-inf")  # monotonic
        self._last_gc = time.monotonic()

    def event(self, obj, etype: str, reason: str, message: str,
              correlation: str | None = None) -> None:
        """Record one event (schedule_one.go:1174 "Scheduled",
        :1273 "FailedScheduling"). Repeats aggregate into a count.

        correlation groups similar-but-not-identical events (e.g. one wave's
        per-pod "Scheduled" events, whose messages differ by node): past
        AGGREGATE_SPILL events per key, the remainder becomes a single
        aggregate object ("combined from similar events"), exactly the
        reference correlator's spam-vs-signal compromise."""
        involved = f"{obj.kind}/{obj.meta.key}"
        now = time.time()
        flush_now = False
        with self._mu:
            aggregated = False
            if correlation is not None:
                ckey = (correlation, etype, reason)
                seen = self._corr_counts.get(ckey, 0) + 1
                self._corr_counts[ckey] = seen
                aggregated = seen > self.AGGREGATE_SPILL
            if aggregated:
                key = (correlation, etype, reason, None)
                message = f"(combined from similar events): {message}"
                involved = correlation
            else:
                key = (involved, etype, reason, message)
            ev = self._pending.get(key)
            if ev is not None:
                ev.count += 1
                ev.last_timestamp = now
                if aggregated:
                    ev.message = message  # latest representative
            else:
                # deterministic name per key: repeats aggregate into the
                # SAME stored object across flushes (EventSeries semantics),
                # never a new one per flush
                import hashlib

                digest = hashlib.sha1(
                    "|".join(k or "aggregated" for k in key).encode()
                ).hexdigest()[:12]
                name = f"{obj.meta.name}.{digest}"
                self._pending[key] = Event(
                    meta=ObjectMeta(name=name, namespace=obj.meta.namespace),
                    involved_object=involved,
                    type=etype,
                    reason=reason,
                    message=message,
                    first_timestamp=now,
                    last_timestamp=now,
                    reporting_controller=self.component,
                )
            flush_now = len(self._pending) >= self._max_buffer
        if self.metrics is not None and hasattr(self.metrics, "event_recorded"):
            self.metrics.event_recorded(aggregated)
        if flush_now:
            self.flush()

    def maybe_flush(self) -> int:
        """Hot-loop entry point: flush at most every FLUSH_INTERVAL_S, and
        through the async dispatcher when one is wired — either way the
        per-iteration cost in the scheduling loop is a clock read."""
        now = time.monotonic()
        if now - self._last_flush < self.FLUSH_INTERVAL_S:
            return 0
        with self._mu:
            if not self._pending:
                return 0
        self._last_flush = now
        if self.dispatcher is not None:
            from .api_dispatcher import APICall

            self._flush_seq += 1
            self.dispatcher.add(APICall(
                "event_flush", f"__events__/{self._flush_seq}",
                self.flush,
            ))
            return 0
        return self.flush()

    def flush(self) -> int:
        """Write buffered events to the store; returns how many landed."""
        with self._mu:
            pending, self._pending = self._pending, {}
            self._corr_counts.clear()
        n = 0
        for ev in pending.values():
            try:
                existing = self.store.try_get("Event", ev.meta.key)
                if existing is not None:
                    existing.count += ev.count
                    existing.last_timestamp = ev.last_timestamp
                    existing.message = ev.message
                    self.store.update(existing, check_version=False)
                elif self._fast_create:
                    # copy_return=False: the returned copy was discarded, and
                    # at bench scale (one event per bound pod) the per-event
                    # deepcopy was a measurable slice of scheduling wall time
                    self.store.create(ev, copy_return=False)
                else:
                    # REST/native stores take no copy_return kwarg
                    self.store.create(ev)
                n += 1
            except Exception:  # noqa: BLE001 - events are best-effort
                pass
        self._writes_since_gc += n
        now_m = time.monotonic()
        if (self._writes_since_gc >= self.GC_EVERY_WRITES
                and now_m - self._last_gc >= self.GC_MIN_INTERVAL_S):
            self._writes_since_gc = 0
            self._last_gc = now_m
            self._gc()
        return n

    def _gc(self) -> int:
        """Expire stored events past the TTL — the store has no apiserver
        event TTL, so unbounded churny runs would otherwise leak objects.
        Returns how many series it pruned (previously discarded silently)."""
        cutoff = time.time() - self.EVENT_TTL_S
        pruned = 0
        try:
            # read-only scan (list_refs): a deepcopying list() here grew
            # O(stored-events) per sweep and dominated event-write cost at
            # bench scale (21 sweeps x 11k events)
            if self._fast_list:
                events = self.store.list_refs("Event")
            else:
                events, _ = self.store.list("Event")
            expired = [ev.meta.key for ev in events
                       if ev.last_timestamp < cutoff]
            for key in expired:
                self.store.delete("Event", key)
                pruned += 1
        except Exception:  # noqa: BLE001
            pass
        if self.metrics is not None and hasattr(self.metrics, "events_pruned"):
            self.metrics.events_pruned(pruned)
        return pruned
