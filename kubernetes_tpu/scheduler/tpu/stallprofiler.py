"""Pipeline stall profiler: critical-path attribution for streaming waves.

`pipeline_overlap_ratio` says WHETHER the double-buffer engaged; this
profiler says WHY NOT. Every completed wave's wall clock is decomposed
into **overlap** (host prep hidden under an in-flight predecessor — the
good time the pipeline exists to create) plus a closed set of named
**stall reasons**, with the invariant

    overlap_s + sum(stall_by_reason.values()) ~= wave wall clock

(coverage >= 95%, asserted by the unit suite and the chaos trace soaks).
The decomposition is derived from the wave's own phase stopwatches plus
gap marks stamped at the loop/backend seams, so it costs no extra clock
reads on the hot path:

- ``prep_serialized``   launch-side host prep that ran with the device
  idle (prep seconds not covered by `WaveRecord.overlap_s`) — the
  pipeline_depth<=1 / cold-start regime.
- ``device_busy``       the host blocked on device results (the backend's
  `wait` phase), plus any unmarked open-record gap: after launch returns
  the device owns the wave until collect, so un-stamped time defaults
  here rather than silently vanishing.
- ``bind_backpressure`` the bind-side host segment: per-pod finish
  cycles, PreBind, the batched bind dispatch, and dispatcher in-flight
  waits — time spent pushing results out instead of prepping a successor.
- ``queue_empty``       the record sat open because the queue had no pods
  to prep a successor from (marked by `schedule_wave`'s empty-pop flush).
- ``capacity_gate``     the wave-size controller's target was clipped by
  the per-call cap — the ticked trace regime's one-wave-per-tick gate,
  the dominant reason behind the burst-trace overlap collapse.
- ``flush``             forced pipeline drains: breaker OPEN, poisoned
  carry, incompatible in-flight wave, trailer ordering, shutdown.

Like the pod ledger and device telemetry, the profiler is owned by the
FlightRecorder and is HOST-SIDE ONLY (OBS01): stamps are plain float
arithmetic behind the recorder's already-paid phase clocks, no rng is
consumed, and no scheduling decision reads profiler state — the
bit-compat goldens hold with the profiler armed or disarmed.

Lint contract (kubesched-lint OBS04, analysis/stall_seam.py): every stall
stamp at a seam names a literal from STALL_REASONS below, and the stall
fields on WaveRecord (`stall_by_reason` & co.) are writable only in this
module — seams report through `mark_gap`/`note_stall`, never by poking
record state. Every metric series this module emits is declared in
STALL_SERIES and registered in scheduler/metrics.py (the OBS02 pattern).
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from contextlib import contextmanager

from .podlatency import StreamingQuantile

# The closed set of stall reasons. OBS04 checks (a) this stays a literal
# tuple of string constants and (b) every mark_gap/note_stall call site
# names a literal member. Adding a reason is an API change: update the
# README stall table and the zpage/bench consumers together.
STALL_REASONS = (
    "queue_empty",
    "capacity_gate",
    "prep_serialized",
    "device_busy",
    "flush",
    "bind_backpressure",
)

# Series this profiler emits; registered in scheduler/metrics.py (OBS02
# pattern — stall_seam.py cross-parses the two files).
STALL_SERIES = (
    "scheduler_tpu_pipeline_stall_seconds",
    "scheduler_tpu_pipeline_stall_total_seconds",
)

# launch-side host-prep phases (mirrors flightrecorder.PREP_PHASES; kept
# literal here so the profiler never imports its owner)
_PREP_PHASES = ("sync", "features", "upload", "dedup", "tie", "dispatch")
_DEVICE_PHASES = ("wait",)
_BIND_PHASES = ("finish", "bind")

DEFAULT_CAPACITY = 256  # per-wave attribution rows retained for the zpage
DEFAULT_WINDOW = 4096   # coverage/stall quantile sample window
_RESIDUAL_FLOOR_S = 1e-9

# the coverage invariant the tests/soaks assert: attributed time must
# cover at least this share of every wave's wall clock (and not exceed
# it by more than the same slack — double counting is as much a bug as
# a gap)
COVERAGE_FLOOR = 0.95


class StallProfiler:
    """Per-wave wall-clock decomposition into overlap + named stalls.

    Owned by the FlightRecorder (one per scheduler). Seams stamp through
    `mark_gap` (attribute the record's open-but-untimed gap) and
    `note_stall`/`stall` (explicit timed intervals); `finalize` runs once
    per wave from FlightRecorder.end_wave and writes the record's
    `stall_by_reason`/`stall_coverage`/`stall_dominant` — the ONLY place
    stall state lands on a record (OBS04). `enabled` exists for the
    bit-compat golden's off arm; production keeps it armed
    (KUBE_TPU_STALL_PROFILER=0 disarms).
    """

    def __init__(self, metrics=None, capacity: int = DEFAULT_CAPACITY,
                 window: int = DEFAULT_WINDOW):
        self.enabled = os.environ.get("KUBE_TPU_STALL_PROFILER", "1") != "0"
        self.metrics = metrics
        self.capacity = capacity
        self._lock = threading.Lock()
        # cumulative seconds per reason (finalized waves + record-less
        # explicit stamps such as the per-pod bind wait)
        self.stall_totals: dict[str, float] = {r: 0.0 for r in STALL_REASONS}
        # how many times each reason was stamped/marked at a seam — the
        # chaos soaks' "flush appears exactly when the breaker trips" hook
        self.stall_events: dict[str, int] = {r: 0 for r in STALL_REASONS}
        self.waves_profiled = 0
        self.wall_s_total = 0.0
        self.overlap_s_total = 0.0
        # TPUBackend double-buffer handoffs: how many launches swapped in
        # over a live predecessor (chained) vs into an idle device
        self.handoffs_total = 0
        self.handoffs_chained = 0
        self.coverage_min: float | None = None
        self._coverage = StreamingQuantile(window)
        self._rows: collections.deque[dict] = collections.deque(
            maxlen=capacity
        )

    # -- emission (every name literal, declared in STALL_SERIES) -------------

    def _series(self, name: str):
        m = self.metrics
        registry = getattr(m, "registry", None) if m is not None else None
        return registry.get(name) if registry is not None else None

    # -- seam stamps ---------------------------------------------------------

    def mark_gap(self, record, reason: str) -> None:
        """Attribute `record`'s open-but-untimed gap to `reason` (last
        mark wins; `finalize` assigns the residual). `record` may be None
        — flush seams with nothing in flight still count the event."""
        if not self.enabled:
            return
        if reason not in STALL_REASONS:
            raise ValueError(f"undeclared stall reason {reason!r}")
        with self._lock:
            self.stall_events[reason] += 1
        if record is not None:
            record._stall_mark = reason

    def note_handoff(self, record, chained: bool) -> None:
        """TPUBackend buffer handoff: a launch swapped into the double
        buffer over a live predecessor (`chained`) or into an idle device
        — the per-wave pipeline-engagement bit behind overlap_s."""
        if not self.enabled:
            return
        with self._lock:
            self.handoffs_total += 1
            if chained:
                self.handoffs_chained += 1

    def note_stall(self, record, reason: str, seconds: float) -> None:
        """Record an explicitly timed stall interval. With a record, it
        folds into that wave's decomposition at finalize; without one
        (per-pod paths) it lands straight on the cumulative totals."""
        if not self.enabled or seconds < 0.0:
            return
        if reason not in STALL_REASONS:
            raise ValueError(f"undeclared stall reason {reason!r}")
        with self._lock:
            self.stall_events[reason] += 1
            if record is None:
                self.stall_totals[reason] += seconds
                self._land_histogram(reason, seconds)
                return
        acc = record._stall_acc
        acc[reason] = acc.get(reason, 0.0) + seconds

    @contextmanager
    def stall(self, record, reason: str):
        """Time a block as an explicit stall interval (note_stall)."""
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.note_stall(record, reason, time.perf_counter() - t0)

    # -- finalization (the one writer of record stall state: OBS04) ----------

    def finalize(self, record) -> None:
        """Decompose `record.duration_s` into overlap + stalls; called
        once per wave from FlightRecorder.end_wave, after duration_s and
        phases are final. Idempotence guard: a record finalizes once."""
        if not self.enabled or getattr(record, "_stall_done", False):
            return
        record._stall_done = True
        wall = record.duration_s
        phases = record.phases
        prep = sum(phases.get(p, 0.0) for p in _PREP_PHASES)
        overlap = min(record.overlap_s, prep)
        stalls = {r: 0.0 for r in STALL_REASONS}
        for reason, seconds in record._stall_acc.items():
            stalls[reason] += seconds
        stalls["prep_serialized"] += max(prep - overlap, 0.0)
        stalls["device_busy"] += sum(
            phases.get(p, 0.0) for p in _DEVICE_PHASES
        )
        stalls["bind_backpressure"] += sum(
            phases.get(p, 0.0) for p in _BIND_PHASES
        )
        attributed = overlap + sum(stalls.values())
        residual = wall - attributed
        if residual > _RESIDUAL_FLOOR_S:
            # the record sat open with nothing stamping a phase: a seam
            # mark names the cause; unmarked gaps default to device_busy
            # (post-launch, the device owns the wave until collect)
            stalls[record._stall_mark or "device_busy"] += residual
            attributed = wall
        stalls = {r: s for r, s in stalls.items() if s > 0.0}
        coverage = (attributed / wall) if wall > 0.0 else 1.0
        dominant = max(stalls, key=stalls.get) if stalls else None
        record.stall_by_reason = {r: round(s, 9) for r, s in stalls.items()}
        record.stall_coverage = round(coverage, 6)
        record.stall_dominant = dominant
        with self._lock:
            self.waves_profiled += 1
            self.wall_s_total += wall
            self.overlap_s_total += overlap
            for reason, seconds in stalls.items():
                self.stall_totals[reason] += seconds
            self._coverage.add(coverage)
            if self.coverage_min is None or coverage < self.coverage_min:
                self.coverage_min = coverage
            self._rows.append({
                "wave_id": record.wave_id,
                "wall_s": round(wall, 9),
                "overlap_s": round(overlap, 9),
                "stall_by_reason": dict(record.stall_by_reason),
                "coverage": record.stall_coverage,
                "dominant": dominant,
            })
        for reason, seconds in stalls.items():
            self._land_histogram(reason, seconds)
        self._update_total_gauge()

    def _land_histogram(self, reason: str, seconds: float) -> None:
        hist = self._series("scheduler_tpu_pipeline_stall_seconds")
        if hist is not None:
            hist.observe(seconds, reason)

    def _update_total_gauge(self) -> None:
        gauge = self._series("scheduler_tpu_pipeline_stall_total_seconds")
        if gauge is None:
            return
        with self._lock:
            totals = dict(self.stall_totals)
        for reason, seconds in totals.items():
            gauge.set(seconds, reason)

    # -- queries / snapshots -------------------------------------------------

    def summary(self) -> dict:
        with self._lock:
            totals = {r: round(s, 6) for r, s in self.stall_totals.items()
                      if s > 0.0}
            stalled = sum(self.stall_totals.values())
            dominant = (max(self.stall_totals, key=self.stall_totals.get)
                        if stalled > 0.0 else None)
            cov_p50 = self._coverage.quantile(0.50)
            return {
                "waves_profiled": self.waves_profiled,
                "wall_s": round(self.wall_s_total, 6),
                "overlap_s": round(self.overlap_s_total, 6),
                "stall_s": totals,
                "dominant": dominant,
                "dominant_share": (
                    round(self.stall_totals[dominant] / self.wall_s_total, 4)
                    if dominant is not None and self.wall_s_total > 0.0
                    else None
                ),
                "coverage_p50": (round(cov_p50, 4)
                                 if cov_p50 is not None else None),
                "coverage_min": (round(self.coverage_min, 4)
                                 if self.coverage_min is not None else None),
                "handoffs": {"total": self.handoffs_total,
                             "chained": self.handoffs_chained},
                "events": {r: n for r, n in self.stall_events.items() if n},
            }

    def snapshot(self, last: int | None = None) -> dict:
        """The /debug/stalls zpage payload: cumulative summary, the last N
        per-wave attribution rows, and the critical path of the slowest
        retained wave."""
        with self._lock:
            rows = list(self._rows)
        out = {"summary": self.summary()}
        if last:
            out["last"] = rows[-last:]
        if rows:
            worst = max(rows, key=lambda r: r["wall_s"])
            out["critical_path"] = critical_path_of_row(worst)
        return out

    def bench_columns(self) -> dict:
        """Flat stall_* columns for bench/trace_bench/bench_suite rows.
        Wall-clock derived — NEVER add these to DETERMINISTIC_KEYS."""
        s = self.summary()
        cols = {
            "stall_dominant": s["dominant"],
            "stall_coverage_p50": s["coverage_p50"],
            "stall_total_s": round(sum(self.stall_totals.values()), 6),
        }
        for reason in STALL_REASONS:
            cols[f"stall_{reason}_s"] = round(
                self.stall_totals.get(reason, 0.0), 6
            )
        return cols


# -- critical-path analysis ----------------------------------------------------


def critical_path_of_row(row: dict) -> dict:
    """Edge chain for one per-wave attribution row: overlap plus each
    stall reason as an ordered edge, dominant edge flagged."""
    chain = []
    if row.get("overlap_s"):
        chain.append({"edge": "overlap", "seconds": row["overlap_s"]})
    for reason, seconds in sorted(row.get("stall_by_reason", {}).items(),
                                  key=lambda kv: -kv[1]):
        chain.append({"edge": reason, "seconds": seconds})
    return {
        "wave_id": row.get("wave_id"),
        "wall_s": row.get("wall_s"),
        "dominant": row.get("dominant"),
        "chain": chain,
    }


def critical_path(records: list[dict]) -> dict:
    """Critical-path analysis over to_dict()-shaped wave records (the
    flight recorder dump / ring buffer): per burst, the guilty stall kind
    (largest summed reason) and the dominant edge chain of the single
    slowest wave. Pure function — usable on post-mortem dumps."""
    waves = [r for r in records if r.get("stall_by_reason")]
    if not waves:
        return {"waves": 0, "guilty": None, "chain": []}
    totals: dict[str, float] = {}
    wall = 0.0
    overlap = 0.0
    for r in waves:
        wall += r.get("duration_s", 0.0)
        overlap += r.get("overlap_s", 0.0)
        for reason, seconds in r["stall_by_reason"].items():
            totals[reason] = totals.get(reason, 0.0) + seconds
    guilty = max(totals, key=totals.get) if totals else None
    worst = max(waves, key=lambda r: r.get("duration_s", 0.0))
    worst_path = critical_path_of_row({
        "wave_id": worst.get("wave_id"),
        "wall_s": worst.get("duration_s"),
        "overlap_s": worst.get("overlap_s", 0.0),
        "stall_by_reason": worst["stall_by_reason"],
        "dominant": worst.get("stall_dominant"),
    })
    return {
        "waves": len(waves),
        "wall_s": round(wall, 6),
        "overlap_s": round(overlap, 6),
        "stall_s": {r: round(s, 6) for r, s in sorted(
            totals.items(), key=lambda kv: -kv[1])},
        "guilty": guilty,
        "guilty_share": (round(totals[guilty] / wall, 4)
                         if guilty is not None and wall > 0.0 else None),
        "critical_wave": worst_path,
        "chain": worst_path["chain"],
    }


def critical_path_of_span(root) -> list[dict]:
    """Dominant edge chain through one `wave/<id>` root of the recorder's
    span tree (utils.tracing.Span): at every level, descend into the
    longest child. Works on live Span objects from an InMemoryExporter."""
    chain: list[dict] = []
    node = root
    while getattr(node, "children", None):
        node = max(node.children, key=lambda c: c.duration_s)
        chain.append({
            "edge": node.name,
            "seconds": round(node.duration_s, 9),
        })
    return chain


# -- CLI: smoke / demo ---------------------------------------------------------


def _synthetic_record(wave_id: int, wall: float, phases: dict,
                      overlap_s: float = 0.0, mark: str | None = None):
    """A WaveRecord-shaped stand-in driven by a synthetic clock — the
    smoke and the unit suite decompose known wall clocks, no sleeping."""

    class _Rec:
        pass

    rec = _Rec()
    rec.wave_id = wave_id
    rec.duration_s = wall
    rec.phases = dict(phases)
    rec.overlap_s = overlap_s
    rec._stall_acc = {}
    rec._stall_mark = mark
    rec.stall_by_reason = {}
    rec.stall_coverage = 0.0
    rec.stall_dominant = None
    return rec


def _smoke(demo: bool = False) -> int:
    """Deterministic critical-path smoke (the `make verify` hook): feed
    synthetic waves through the full decompose -> analyze path and assert
    the coverage invariant and dominant-edge selection."""
    prof = StallProfiler()
    prof.enabled = True
    # wave 1: healthy pipeline — prep fully hidden, device-bound
    r1 = _synthetic_record(
        1, wall=1.0,
        phases={"sync": 0.05, "features": 0.15, "dispatch": 0.10,
                "wait": 0.55, "finish": 0.05, "bind": 0.10},
        overlap_s=0.30,
    )
    # wave 2: the burst-trace collapse — cap-gated gap dominates
    r2 = _synthetic_record(
        2, wall=2.0,
        phases={"sync": 0.02, "features": 0.08, "wait": 0.10,
                "finish": 0.05, "bind": 0.05},
        overlap_s=0.0, mark="capacity_gate",
    )
    prof.mark_gap(r2, "capacity_gate")
    # wave 3: breaker drain
    r3 = _synthetic_record(
        3, wall=0.5, phases={"wait": 0.05}, overlap_s=0.0, mark=None,
    )
    prof.mark_gap(r3, "flush")
    for rec in (r1, r2, r3):
        prof.finalize(rec)
        total = rec.overlap_s + sum(rec.stall_by_reason.values())
        assert rec.duration_s * COVERAGE_FLOOR <= total <= \
            rec.duration_s * (2.0 - COVERAGE_FLOOR), (
                f"wave {rec.wave_id}: attribution {total} vs wall "
                f"{rec.duration_s}"
            )
        assert rec.stall_coverage >= COVERAGE_FLOOR
    assert r1.stall_dominant == "device_busy", r1.stall_dominant
    assert r2.stall_dominant == "capacity_gate", r2.stall_dominant
    assert r3.stall_dominant == "flush", r3.stall_dominant
    rows = [{
        "wave_id": r.wave_id, "duration_s": r.duration_s,
        "overlap_s": r.overlap_s, "stall_by_reason": r.stall_by_reason,
        "stall_dominant": r.stall_dominant,
    } for r in (r1, r2, r3)]
    cp = critical_path(rows)
    assert cp["guilty"] == "capacity_gate", cp
    assert cp["critical_wave"]["wave_id"] == 2, cp
    assert cp["chain"] and cp["chain"][0]["edge"] == "capacity_gate", cp
    # span-tree flavor: the dominant edge chain must descend into the
    # longest child at every level
    from ...utils.tracing import InMemoryExporter, Tracer

    exporter = InMemoryExporter()
    tracer = Tracer("stall-smoke", exporter=exporter)
    with tracer.span("wave/9"):
        with tracer.span("phase/kernel"):
            with tracer.span("wave_phase/wait"):
                time.sleep(0.002)
        with tracer.span("phase/bind"):
            pass
    chain = critical_path_of_span(exporter.find("wave/")[0])
    assert [e["edge"] for e in chain] == ["phase/kernel", "wave_phase/wait"], \
        chain
    summary = prof.summary()
    assert summary["dominant"] == "capacity_gate", summary
    assert summary["coverage_min"] >= COVERAGE_FLOOR, summary
    if demo:
        print(json.dumps({
            "summary": summary,
            "critical_path": cp,
            "snapshot": prof.snapshot(last=3),
        }, indent=2))
    else:
        print("stall profiler smoke OK: "
              f"guilty={cp['guilty']} share={cp['guilty_share']} "
              f"coverage_min={summary['coverage_min']}")
    return 0


def main(argv: list[str] | None = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m kubernetes_tpu.scheduler.tpu.stallprofiler",
        description="Streaming-wave stall attribution / critical path",
    )
    parser.add_argument("dump", nargs="?",
                        help="flight-recorder JSON dump to analyze "
                             "('-' reads stdin)")
    parser.add_argument("--smoke", action="store_true",
                        help="run the deterministic critical-path smoke "
                             "(the `make verify` hook)")
    parser.add_argument("--demo", action="store_true",
                        help="print the smoke profiler's summary JSON")
    parser.add_argument("--last", type=int, default=None,
                        help="limit record analysis to the last N waves")
    args = parser.parse_args(argv)

    if args.smoke:
        return _smoke()
    if args.demo:
        return _smoke(demo=True)
    if args.dump:
        import sys

        raw = (sys.stdin.read() if args.dump == "-"
               else open(args.dump).read())
        payload = json.loads(raw)
        records = payload.get("records", [])
        if args.last:
            records = records[-args.last:]
        print(json.dumps(critical_path(records), indent=2))
        return 0
    parser.print_usage()
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
