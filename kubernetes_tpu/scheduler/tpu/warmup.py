"""AOT warm restart: pre-lower the wave kernels before the first real pod.

A cold scheduler pays XLA compilation for every (kernel, shape) pair the
first wave of each pow2 bucket touches — seconds of dead air exactly when a
restarted scheduler should be re-entering service. With the persistent jax
compilation cache (utils/jaxcache) those lowerings are disk artifacts: the
FIRST incarnation pays them once, and every restart replays them as cache
hits. What a restart still pays without this module is the host-side
tracing + cache probe per signature — and, worse, any signature the crash
window never reached. `warm_backend` walks the pow2 wave-size buckets
through the REAL launch/collect path (both the cold-carry and the
chained + cross-wave-replay jit signatures), the single-pod fit_and_score
program, the delta-scatter row buckets, and the gang kernel shapes the
workload uses, all inside a named `warmup` flight-recorder phase — so a
warm restart's steady state runs with `compile_count_since_warm() == 0`.

Everything here is best-effort: a warmup failure logs and degrades to lazy
compilation; it never breaks scheduler construction. Warmup never touches
host planes or the live rng (it draws from its own throwaway stream), and
it ends by invalidating the carry, so the base device mirror remains exact
host truth and the first real wave starts from a clean seam.
"""

from __future__ import annotations

import random

import numpy as np

from ...ops.vocab import next_pow2
from ...utils.jaxcache import enable_persistent_cache
from ...utils.logging import get_logger

_log = get_logger("kubernetes_tpu.tpu.warmup")

# the smallest wave/scatter bucket the backend emits (pow2 floors)
_FLOOR = 8

# default gang shapes to pre-lower: (members, n_constrained, has_fallback)
# — the plugin-less gang plan (GangPlan([parent], 0, True, ...)) with up
# to 4 members is the shape every topology-free PodGroup produces
DEFAULT_GANG_SHAPES = ((4, 0, True),)


def _warm_pods(n: int, namespace: str = "default"):
    """Label-less synthetic pods with a plain-pod kernel config — the same
    cfg wave traffic compiles against. They ride the real register path, so
    they must intern the SAME vocab entries traffic will: system-default
    spread interns a (namespace, selector) pair per pod shape, and a warmup
    namespace traffic never uses would leave the selector bucket one short
    of steady state — the first real pod would grow it and recompile."""
    from ...testing import make_pod

    return [
        make_pod(f"warm-{i}", namespace=namespace, cpu="100m", mem="128Mi")
        for i in range(n)
    ]


def _pow2_buckets(top: int) -> list[int]:
    buckets, b = [], _FLOOR
    top = max(top, _FLOOR)
    while b <= top:
        buckets.append(b)
        b *= 2
    return buckets


def warm_backend(backend, snapshot, wave_size: int, rng_seed: int = 0,
                 gang_shapes=DEFAULT_GANG_SHAPES) -> dict:
    """Pre-lower every jit entry point the wave pipeline dispatches.

    Per pow2 bucket up to next_pow2(wave_size): TWO chained
    launch_batched/collect rounds — the first compiles the cold-carry
    batched_assign signature, the second (same signatures, carry live)
    the cross-wave-replay variant. Then one single-pod `run`
    (fit_and_score), the `_scatter_rows_jit` delta buckets, and one
    `run_gang` per requested gang shape. Returns a summary dict; never
    raises."""
    summary: dict = {"buckets": [], "scatter": [], "gangs": [],
                     "skipped": [], "cache_dir": None, "compiles": 0}
    if snapshot.num_nodes() == 0:
        # nothing to lower against — bucket sizes come from the node planes
        summary["skipped"].append("no nodes in snapshot")
        backend.telemetry.mark_warm()
        return summary
    summary["cache_dir"] = str(enable_persistent_cache())
    tele = backend.telemetry
    base_compiles = tele.compile_count()
    rng = random.Random(rng_seed)  # throwaway: the live rng never moves
    with backend.recorder.phase("warmup"):
        for b in _pow2_buckets(next_pow2(max(wave_size, 1))):
            try:
                for _ in range(2):  # cold-carry, then chained + replay
                    fl = backend.launch_batched(
                        _warm_pods(2), snapshot, rng=rng, pad_to=b)
                    backend.collect(fl, rng=rng)
                summary["buckets"].append(b)
            except Exception as e:  # noqa: BLE001 — degrade to lazy compile
                backend.invalidate_carry()
                summary["skipped"].append(f"wave{b}: {e}")
        try:
            backend.run(_warm_pods(1)[0], snapshot)
        except Exception as e:  # noqa: BLE001
            summary["skipped"].append(f"single: {e}")
        _warm_scatter(backend, snapshot, wave_size, summary)
        for shape in gang_shapes:
            _warm_gang(backend, snapshot, shape, rng, summary)
        # the carry holds warmup placements no host state backs: drop it so
        # the base mirror (untouched — warmup binds nothing) stays truth
        backend.invalidate_carry()
    summary["compiles"] = tele.compile_count() - base_compiles
    tele.mark_warm()
    _log.info("warm restart pre-lowering done",
              compiles=summary["compiles"], buckets=summary["buckets"],
              gangs=summary["gangs"], skipped=summary["skipped"] or None)
    return summary


def _warm_scatter(backend, snapshot, wave_size: int, summary: dict) -> None:
    """Pre-lower the fused delta-scatter for each pow2 row bucket a wave's
    binds can dirty (device_inputs pads dirty-row counts the same way).
    Scatters node rows onto themselves — content is a no-op, only the
    (bucket_sizes, idx-length) program shape matters."""
    from .backend import _scatter_rows_jit

    try:
        planes = backend.sync(snapshot)
        dev = backend._device_planes
        if dev is None:
            summary["skipped"].append("scatter: no device planes")
            return
        host = planes.as_dict()
        # binds dirty up to ~wave_size rows between uploads; one extra
        # bucket covers a wave of stragglers accumulating on top
        for size in _pow2_buckets(2 * next_pow2(max(wave_size, 1))):
            scatter_in = {k: v for k, v in dev.items() if k != "ipa_term_key"}
            idx = np.zeros(size, np.int32)
            rows_host = {k: host[k][idx] for k in scatter_in}
            rows_dev = backend.telemetry.accounted_put(
                "delta_rows", rows_host, put=backend._ctx.put_replicated)
            idx_dev = backend.telemetry.accounted_put(
                "delta_idx", idx, put=backend._ctx.put_replicated)
            with backend.telemetry.compile_span(
                    "scatter_rows", ("scatter", planes.bucket_sizes, size),
                    label=f"rows{size}"):
                updated = _scatter_rows_jit(scatter_in, rows_dev, idx_dev)
            # arg 0 is donated: the old buffers are dead — adopt the result
            # (same values: we scattered truth rows onto themselves, so the
            # mirror stays exact and warmup's closing invalidate_carry
            # covers the signature cache)
            updated["ipa_term_key"] = dev["ipa_term_key"]
            backend._device_planes = updated  # kubesched-lint: disable=SIG02
            dev = updated
            summary["scatter"].append(size)
    except Exception as e:  # noqa: BLE001
        summary["skipped"].append(f"scatter: {e}")


def _warm_gang(backend, snapshot, shape, rng, summary: dict) -> None:
    """Pre-lower one gang_assign program shape: `shape` is (members,
    n_constrained, has_fallback) mirroring GangPlan — domain rows are
    fabricated all-node placements (mask content never changes the
    compiled program, only the row count does)."""
    from ..cache.snapshot import Placement

    n_pods, n_constrained, has_fallback = shape
    try:
        names = [ni.name for ni in snapshot.list_nodes()]
        placements = [Placement(f"warm-d{i}", names)
                      for i in range(n_constrained)]
        if has_fallback:
            placements.append(Placement("warm-all", names))
        backend.run_gang(_warm_pods(n_pods), snapshot, placements,
                         n_constrained, bool(has_fallback), rng)
        summary["gangs"].append(shape)
    except Exception as e:  # noqa: BLE001
        summary["skipped"].append(f"gang{shape}: {e}")
